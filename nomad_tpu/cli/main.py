"""CLI entry point — the `command/` layer of the reference.

Reference behavior: main.go:63-73 registers the top-level verbs
(agent, job, node, alloc, eval, deployment, namespace, acl, operator,
server, status, system, ui, version) with mitchellh/cli; each verb
talks to the cluster through the api/ SDK. This module provides the
same verb tree over argparse on top of nomad_tpu.api.client.

Usage::

    python -m nomad_tpu agent -dev
    python -m nomad_tpu job run example.hcl
    python -m nomad_tpu node status
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import Any, Dict, List, Optional

from nomad_tpu.api.client import APIClient, APIError, QueryOptions
from nomad_tpu.cli.fmt import dict_rows, format_kv, format_list, short_id

VERSION = "0.1.0"


def make_client(args) -> APIClient:
    return APIClient(
        address=args.address,
        token=args.token,
        namespace=args.namespace,
        region=getattr(args, "region", "") or "",
        ca_cert=getattr(args, "ca_cert", "") or "",
        client_cert=getattr(args, "client_cert", "") or "",
        client_key=getattr(args, "client_key", "") or "",
    )


def _fail(msg: str) -> int:
    print(f"Error: {msg}", file=sys.stderr)
    return 1


# --- job ----------------------------------------------------------------


def _job_variables(args) -> tuple:
    """(-var flags, NOMAD_VAR_* env) — jobspec2 variable sources.
    Flags naming undeclared variables error; env values for
    undeclared variables are ignored."""
    flags: Dict = {}
    for item in getattr(args, "var", None) or []:
        if "=" not in item:
            raise ValueError(f"-var needs key=value, got {item!r}")
        k, v = item.split("=", 1)
        flags[k] = v
    env = {k[len("NOMAD_VAR_"):]: v for k, v in os.environ.items()
           if k.startswith("NOMAD_VAR_")}
    return flags, env


def _load_jobfile(path: str, variables: Optional[tuple] = None) -> Dict:
    """Parse an HCL or JSON jobspec file to a wire-format job dict
    (jobspec2.Parse → api.Job in the reference)."""
    from nomad_tpu.api.codec import encode
    from nomad_tpu.jobspec.parse import parse_hcl, parse_json

    if path == "-":
        src = sys.stdin.read()
    else:
        with open(path) as f:
            src = f.read()
    if path.endswith(".json"):
        data = json.loads(src)
        job = parse_json(data.get("Job", data))
    else:
        flags, env = variables or ({}, {})
        job = parse_hcl(src, flags, env)
    return encode(job)


def _monitor_eval(api: APIClient, eval_id: str, timeout: float = 30.0) -> int:
    """Poll an eval to completion, printing placement results — the
    `monitor` in command/monitor.go."""
    deadline = time.time() + timeout
    last_status = ""
    while time.time() < deadline:
        try:
            ev = api.evaluations.info(eval_id)
        except APIError as e:
            return _fail(f"eval lookup failed: {e}")
        status = ev.get("Status", "")
        if status != last_status:
            print(f"==> Evaluation \"{short_id(eval_id)}\" status \"{status}\"")
            last_status = status
        if status in ("complete", "failed", "canceled"):
            allocs = api.evaluations.allocations(eval_id)
            for a in allocs:
                print(
                    f"    Allocation \"{short_id(a['ID'])}\" created on node "
                    f"\"{short_id(a.get('NodeID', ''))}\""
                )
            blocked = ev.get("BlockedEval")
            if blocked:
                print(
                    f"==> Evaluation \"{short_id(eval_id)}\" waiting for "
                    f"additional capacity to place remainder (blocked eval "
                    f"\"{short_id(blocked)}\")"
                )
            if ev.get("FailedTGAllocs"):
                for tg, metric in ev["FailedTGAllocs"].items():
                    print(f"    Task Group \"{tg}\" (failed to place)")
                    for cls, n in (metric.get("ClassFiltered") or {}).items():
                        print(f"      * Class {cls}: {n} nodes filtered")
                    for dim, n in (metric.get("ConstraintFiltered") or {}).items():
                        print(f"      * Constraint {dim}: {n} nodes filtered")
            return 0 if status == "complete" else 2
        time.sleep(0.2)
    return _fail("eval monitoring timed out")


def cmd_job_run(args) -> int:
    api = make_client(args)
    try:
        job = _load_jobfile(args.jobfile, _job_variables(args))
    except Exception as e:
        return _fail(f"parsing jobspec: {e}")
    res = api.jobs.register(job)
    eval_id = res.get("EvalID", "")
    if args.detach or not eval_id:
        print(f"Job registration successful")
        if eval_id:
            print(f"Evaluation ID: {eval_id}")
        return 0
    return _monitor_eval(api, eval_id)


def cmd_job_plan(args) -> int:
    api = make_client(args)
    try:
        job = _load_jobfile(args.jobfile, _job_variables(args))
    except Exception as e:
        return _fail(f"parsing jobspec: {e}")
    res = api.jobs.plan(job, diff=True)
    diff = res.get("Diff") or {}
    print(f"+/- Job: \"{job.get('ID', '')}\"")
    if diff:
        print(f"Diff type: {diff.get('Type', 'None')}")
        for tg in diff.get("TaskGroups") or []:
            print(f"  Task Group: \"{tg.get('Name')}\" ({tg.get('Type')})")
    anno = res.get("Annotations") or {}
    for tg, changes in (anno.get("DesiredTGUpdates") or {}).items():
        parts = ", ".join(f"{k}: {v}" for k, v in changes.items() if v)
        print(f"  Group \"{tg}\": {parts or 'no changes'}")
    # reference exits 1 when the diff is non-empty so scripts can gate
    return 1 if diff.get("Type") not in (None, "", "None") else 0


def cmd_job_validate(args) -> int:
    """job_validate.go: parse + server-side structural validation."""
    api = make_client(args)
    try:
        job = _load_jobfile(args.jobfile, _job_variables(args))
    except Exception as e:
        return _fail(f"parsing jobspec: {e}")
    res = api.put("/v1/validate/job", {"Job": job})
    errs = res.get("ValidationErrors") or []
    if errs:
        print("Job validation errors:")
        for e in errs:
            print(f"  * {e}")
        return 1
    print("Job validation successful")
    return 0


def cmd_job_status(args) -> int:
    api = make_client(args)
    if not args.job_id:
        jobs = api.jobs.list()
        if not jobs:
            print("No running jobs")
            return 0
        print(dict_rows(jobs, ["ID", "Type", "Priority", "Status"]))
        return 0
    job = _resolve_one(api, args.job_id, "jobs", api.jobs.info)
    if job is None:
        return 1
    rows = [
        f"ID|{job['ID']}",
        f"Name|{job.get('Name', '')}",
        f"Type|{job.get('Type', '')}",
        f"Priority|{job.get('Priority', '')}",
        f"Datacenters|{','.join(job.get('Datacenters') or [])}",
        f"Status|{job.get('Status', '')}",
        f"Version|{job.get('Version', 0)}",
    ]
    print(format_kv(rows))
    try:
        summ = api.jobs.summary(job["ID"])
        print("\nSummary")
        srows = ["Task Group|Queued|Starting|Running|Failed|Complete|Lost"]
        for tg, s in sorted((summ.get("Summary") or {}).items()):
            srows.append(
                f"{tg}|{s.get('Queued', 0)}|{s.get('Starting', 0)}|"
                f"{s.get('Running', 0)}|{s.get('Failed', 0)}|"
                f"{s.get('Complete', 0)}|{s.get('Lost', 0)}"
            )
        print(format_list(srows))
    except APIError:
        pass
    allocs = api.jobs.allocations(job["ID"])
    if allocs:
        print("\nAllocations")
        arows = ["ID|Node ID|Task Group|Desired|Status"]
        for a in allocs:
            arows.append(
                f"{short_id(a['ID'])}|{short_id(a.get('NodeID', ''))}|"
                f"{a.get('TaskGroup', '')}|{a.get('DesiredStatus', '')}|"
                f"{a.get('ClientStatus', '')}"
            )
        print(format_list(arows))
    return 0


def cmd_job_stop(args) -> int:
    api = make_client(args)
    job = _resolve_one(api, args.job_id, "jobs", api.jobs.info)
    if job is None:
        return 1
    res = api.jobs.deregister(job["ID"], purge=args.purge)
    eval_id = res.get("EvalID", "")
    if args.detach or not eval_id:
        if eval_id:
            print(f"Evaluation ID: {eval_id}")
        return 0
    return _monitor_eval(api, eval_id)


def cmd_job_inspect(args) -> int:
    api = make_client(args)
    job = _resolve_one(api, args.job_id, "jobs", api.jobs.info)
    if job is None:
        return 1
    print(json.dumps({"Job": job}, indent=4, sort_keys=True))
    return 0


def cmd_job_history(args) -> int:
    api = make_client(args)
    res = api.jobs.versions(args.job_id)
    for v in res.get("Versions") or []:
        print(format_kv([
            f"Version|{v.get('Version')}",
            f"Stable|{v.get('Stable', False)}",
            f"Status|{v.get('Status', '')}",
        ]))
        print()
    return 0


def cmd_job_revert(args) -> int:
    api = make_client(args)
    res = api.jobs.revert(args.job_id, args.version)
    eval_id = res.get("EvalID", "")
    if eval_id and not args.detach:
        return _monitor_eval(api, eval_id)
    print(f"Evaluation ID: {eval_id}")
    return 0


def cmd_job_dispatch(args) -> int:
    api = make_client(args)
    meta = {}
    for kv in args.meta or []:
        if "=" not in kv:
            return _fail(f"-meta must be key=value, got \"{kv}\"")
        k, v = kv.split("=", 1)
        meta[k] = v
    payload = b""
    if args.input_file:
        with open(args.input_file, "rb") as f:
            payload = f.read()
    res = api.jobs.dispatch(args.job_id, meta=meta, payload=payload)
    print(f"Dispatched Job ID = {res['DispatchedJobID']}")
    if res.get("EvalID") and not args.detach:
        return _monitor_eval(api, res["EvalID"])
    return 0


def cmd_job_scale(args) -> int:
    api = make_client(args)
    res = api.jobs.scale(args.job_id, args.group, args.count,
                         message="scaled via CLI")
    if res.get("EvalID") and not args.detach:
        return _monitor_eval(api, res["EvalID"])
    print(f"Evaluation ID: {res.get('EvalID', '')}")
    return 0


def cmd_job_periodic_force(args) -> int:
    api = make_client(args)
    res = api.jobs.periodic_force(args.job_id)
    print(f"Evaluation ID: {res.get('EvalID', '')}")
    return 0


def cmd_job_deployments(args) -> int:
    api = make_client(args)
    deps = api.jobs.deployments(args.job_id)
    if not deps:
        print("No deployments found")
        return 0
    print(dict_rows(deps, ["ID", "JobID", "Status", "StatusDescription"]))
    return 0


# --- node ---------------------------------------------------------------


def cmd_node_status(args) -> int:
    api = make_client(args)
    if not args.node_id:
        nodes = api.nodes.list()
        rows = ["ID|DC|Name|Class|Drain|Eligibility|Status"]
        for n in nodes:
            rows.append(
                f"{short_id(n['ID'])}|{n.get('Datacenter', '')}|"
                f"{n.get('Name', '')}|{n.get('NodeClass', '')}|"
                f"{n.get('Drain', False)}|"
                f"{n.get('SchedulingEligibility', '')}|{n.get('Status', '')}"
            )
        print(format_list(rows))
        return 0
    node = _resolve_one(api, args.node_id, "nodes", api.nodes.info)
    if node is None:
        return 1
    print(format_kv([
        f"ID|{node['ID']}",
        f"Name|{node.get('Name', '')}",
        f"Class|{node.get('NodeClass', '')}",
        f"DC|{node.get('Datacenter', '')}",
        f"Drain|{node.get('Drain', False)}",
        f"Eligibility|{node.get('SchedulingEligibility', '')}",
        f"Status|{node.get('Status', '')}",
    ]))
    allocs = api.nodes.allocations(node["ID"])
    if allocs:
        print("\nAllocations")
        rows = ["ID|Job ID|Task Group|Desired|Status"]
        for a in allocs:
            rows.append(
                f"{short_id(a['ID'])}|{a.get('JobID', '')}|"
                f"{a.get('TaskGroup', '')}|{a.get('DesiredStatus', '')}|"
                f"{a.get('ClientStatus', '')}"
            )
        print(format_list(rows))
    return 0


def cmd_node_drain(args) -> int:
    api = make_client(args)
    if args.enable == args.disable:
        return _fail("exactly one of -enable or -disable is required")
    node = _resolve_one(api, args.node_id, "nodes", api.nodes.info)
    if node is None:
        return 1
    enable = args.enable
    api.nodes.drain(node["ID"], enable=enable, deadline_s=args.deadline)
    print(f"Node \"{short_id(node['ID'])}\" drain strategy "
          f"{'set' if enable else 'unset'}")
    return 0


def cmd_node_eligibility(args) -> int:
    api = make_client(args)
    if args.enable == args.disable:
        return _fail("exactly one of -enable or -disable is required")
    node = _resolve_one(api, args.node_id, "nodes", api.nodes.info)
    if node is None:
        return 1
    eligible = args.enable
    api.nodes.eligibility(node["ID"], eligible)
    print(f"Node \"{short_id(node['ID'])}\" scheduling eligibility set: "
          f"{'eligible' if eligible else 'ineligible'}")
    return 0


# --- alloc / eval / deployment -----------------------------------------


def cmd_alloc_status(args) -> int:
    api = make_client(args)
    alloc = _resolve_one(api, args.alloc_id, "allocs", api.allocations.info)
    if alloc is None:
        return 1
    print(format_kv([
        f"ID|{alloc['ID']}",
        f"Eval ID|{short_id(alloc.get('EvalID', ''))}",
        f"Name|{alloc.get('Name', '')}",
        f"Node ID|{short_id(alloc.get('NodeID', ''))}",
        f"Job ID|{alloc.get('JobID', '')}",
        f"Client Status|{alloc.get('ClientStatus', '')}",
        f"Desired Status|{alloc.get('DesiredStatus', '')}",
    ]))
    metrics = alloc.get("Metrics") or {}
    if metrics.get("ScoreMetaData"):
        print("\nPlacement Metrics")
        rows = ["Node|Score"]
        for sm in metrics["ScoreMetaData"][:5]:
            rows.append(f"{short_id(sm.get('NodeID', ''))}|"
                        f"{sm.get('NormScore', 0):.3f}")
        print(format_list(rows))
    return 0


def cmd_alloc_stop(args) -> int:
    api = make_client(args)
    alloc = _resolve_one(api, args.alloc_id, "allocs", api.allocations.info)
    if alloc is None:
        return 1
    res = api.allocations.stop(alloc["ID"])
    if res.get("EvalID") and not args.detach:
        return _monitor_eval(api, res["EvalID"])
    print(f"Evaluation ID: {res.get('EvalID', '')}")
    return 0


def cmd_alloc_logs(args) -> int:
    api = make_client(args)
    logtype = "stderr" if args.stderr else "stdout"
    if args.follow:
        # reconnect with offset when the server's stream deadline
        # expires mid-task (command/alloc_logs.go follows until the
        # task stops)
        pos = 0
        try:
            while True:
                for chunk in api.allocations.logs_follow(
                        args.alloc_id, args.task, logtype, offset=pos):
                    pos += len(chunk)
                    print(chunk.decode(errors="replace"), end="",
                          flush=True)
                alloc = api.allocations.info(args.alloc_id)
                if alloc.get("ClientStatus") not in ("pending", "running"):
                    break
        except (KeyboardInterrupt, APIError):
            pass
        return 0
    print(api.allocations.logs(args.alloc_id, args.task, logtype), end="")
    return 0


def cmd_alloc_restart(args) -> int:
    api = make_client(args)
    api.allocations.restart(args.alloc_id, args.task or "")
    print(f"Restarted allocation \"{args.alloc_id}\"")
    return 0


def cmd_alloc_signal(args) -> int:
    api = make_client(args)
    api.allocations.signal(args.alloc_id, args.signal, args.task or "")
    print(f"Signalled allocation \"{args.alloc_id}\"")
    return 0


def cmd_alloc_exec(args) -> int:
    api = make_client(args)
    if not (args.interactive or args.tty):
        out = api.allocations.exec(args.alloc_id, args.task, args.cmd)
        if out.get("stdout"):
            print(out["stdout"], end="")
        if out.get("stderr"):
            import sys as _sys
            print(out["stderr"], end="", file=_sys.stderr)
        return int(out.get("exit_code", 0) or 0)
    return _alloc_exec_interactive(api, args)


def _alloc_exec_interactive(api, args) -> int:
    """Streaming exec (`alloc exec -i [-t]`): websocket pty session
    (reference api/allocations_exec.go + command/alloc_exec.go)."""
    import sys as _sys
    import threading as _threading

    session = api.allocations.exec_stream(
        args.alloc_id, args.task, args.cmd, tty=args.tty)

    stdin_fd = _sys.stdin.fileno() if _sys.stdin.isatty() else None
    restore = None
    if args.tty and stdin_fd is not None:
        import termios
        import tty as _ttymod

        restore = termios.tcgetattr(stdin_fd)
        _ttymod.setraw(stdin_fd)
        try:
            import fcntl
            import struct as _struct

            import termios as _t

            packed = fcntl.ioctl(1, _t.TIOCGWINSZ,
                                 _struct.pack("HHHH", 0, 0, 0, 0))
            rows, cols, _, _ = _struct.unpack("HHHH", packed)
            session.resize(rows, cols)
        except OSError:
            pass

    stop = _threading.Event()

    def pump_stdin() -> None:
        try:
            while not stop.is_set():
                data = _sys.stdin.buffer.read1(4096) \
                    if hasattr(_sys.stdin.buffer, "read1") \
                    else _sys.stdin.buffer.read(4096)
                if not data:
                    session.close_stdin()
                    break
                session.send_stdin(data)
        except (OSError, ValueError, ConnectionError):
            pass

    t = _threading.Thread(target=pump_stdin, daemon=True)
    t.start()
    code = 1
    try:
        for frame in session.events():
            for name, out in (("stdout", _sys.stdout), ("stderr", _sys.stderr)):
                blob = frame.get(name) or {}
                if blob.get("bytes"):
                    out.buffer.write(blob["bytes"])
                    out.flush()
        code = session.exit_code if session.exit_code is not None else 1
    finally:
        stop.set()
        session.close()
        if restore is not None:
            import termios

            termios.tcsetattr(stdin_fd, termios.TCSADRAIN, restore)
    return int(code)


def cmd_alloc_fs(args) -> int:
    api = make_client(args)
    path = args.path or "/"
    stat = api.allocations.fs_stat(args.alloc_id, path)
    if stat.get("IsDir"):
        entries = api.allocations.fs_ls(args.alloc_id, path)
        print(dict_rows(entries, ["Name", "Size", "IsDir"]))
    else:
        print(api.allocations.fs_cat(args.alloc_id, path), end="")
    return 0


def cmd_eval_list(args) -> int:
    api = make_client(args)
    evals = api.evaluations.list()
    rows = ["ID|Priority|Triggered By|Job ID|Status"]
    for e in evals[: args.limit]:
        rows.append(
            f"{short_id(e['ID'])}|{e.get('Priority', '')}|"
            f"{e.get('TriggeredBy', '')}|{e.get('JobID', '')}|"
            f"{e.get('Status', '')}"
        )
    print(format_list(rows))
    return 0


def cmd_eval_status(args) -> int:
    api = make_client(args)
    ev = _resolve_one(api, args.eval_id, "evals", api.evaluations.info)
    if ev is None:
        return 1
    print(format_kv([
        f"ID|{ev['ID']}",
        f"Status|{ev.get('Status', '')}",
        f"Type|{ev.get('Type', '')}",
        f"Triggered By|{ev.get('TriggeredBy', '')}",
        f"Job ID|{ev.get('JobID', '')}",
        f"Priority|{ev.get('Priority', '')}",
        f"Placement Failures|{bool(ev.get('FailedTGAllocs'))}",
    ]))
    return 0


def cmd_deployment_list(args) -> int:
    api = make_client(args)
    deps = api.deployments.list()
    if not deps:
        print("No deployments found")
        return 0
    print(dict_rows(deps, ["ID", "JobID", "Status", "StatusDescription"]))
    return 0


def cmd_deployment_status(args) -> int:
    api = make_client(args)
    dep = _resolve_one(api, args.deployment_id, "deployment",
                       api.deployments.info)
    if dep is None:
        return 1
    print(format_kv([
        f"ID|{dep['ID']}",
        f"Job ID|{dep.get('JobID', '')}",
        f"Status|{dep.get('Status', '')}",
        f"Description|{dep.get('StatusDescription', '')}",
    ]))
    for tg, st in (dep.get("TaskGroups") or {}).items():
        print(f"\nTask Group \"{tg}\"")
        print(format_kv([
            f"Desired|{st.get('DesiredTotal', 0)}",
            f"Placed|{st.get('PlacedAllocs', 0)}",
            f"Healthy|{st.get('HealthyAllocs', 0)}",
            f"Unhealthy|{st.get('UnhealthyAllocs', 0)}",
        ]))
    return 0


def cmd_deployment_promote(args) -> int:
    api = make_client(args)
    api.deployments.promote(args.deployment_id)
    print(f"Deployment \"{short_id(args.deployment_id)}\" promoted")
    return 0


def cmd_deployment_fail(args) -> int:
    api = make_client(args)
    api.deployments.fail(args.deployment_id)
    print(f"Deployment \"{short_id(args.deployment_id)}\" marked failed")
    return 0


def cmd_deployment_pause(args) -> int:
    api = make_client(args)
    api.deployments.pause(args.deployment_id, pause=not args.resume)
    print(f"Deployment \"{short_id(args.deployment_id)}\" "
          f"{'resumed' if args.resume else 'paused'}")
    return 0


# --- status (generic prefix resolver) ----------------------------------


def _resolve_one(api: APIClient, prefix: str, context: str, info_fn):
    """Exact lookup, falling back to prefix search — the reference's
    short-ID UX (command/helpers.go getByPrefix pattern)."""
    try:
        return info_fn(prefix)
    except APIError:
        pass
    try:
        res = api.search.prefix(prefix, context)
        matches = (res.get("Matches") or {}).get(context) or []
    except APIError:
        matches = []
    if not matches:
        print(f"Error: no {context} match prefix \"{prefix}\"",
              file=sys.stderr)
        return None
    if len(matches) > 1:
        print(f"Error: prefix \"{prefix}\" matched multiple {context}:\n  "
              + "\n  ".join(matches), file=sys.stderr)
        return None
    return info_fn(matches[0])


def cmd_status(args) -> int:
    api = make_client(args)
    if not args.identifier:
        return cmd_job_status(argparse.Namespace(**{**vars(args), "job_id": ""}))
    res = api.search.prefix(args.identifier, "all")
    matches = {k: v for k, v in (res.get("Matches") or {}).items() if v}
    if not matches:
        return _fail(f"no matches for \"{args.identifier}\"")
    context, ids = next(iter(matches.items()))
    sub = {
        "jobs": (cmd_job_status, "job_id"),
        "nodes": (cmd_node_status, "node_id"),
        "allocs": (cmd_alloc_status, "alloc_id"),
        "evals": (cmd_eval_status, "eval_id"),
        "deployment": (cmd_deployment_status, "deployment_id"),
    }.get(context)
    if sub is None:
        print("\n".join(f"{context}: {i}" for i in ids))
        return 0
    fn, attr = sub
    return fn(argparse.Namespace(**{**vars(args), attr: ids[0]}))


# --- namespace / acl / operator / server / system ----------------------


def cmd_namespace_list(args) -> int:
    api = make_client(args)
    nss = api.namespaces.list()
    print(dict_rows(nss, ["Name", "Description"]))
    return 0


def cmd_namespace_apply(args) -> int:
    api = make_client(args)
    api.namespaces.register(args.name, args.description or "")
    print(f"Successfully applied namespace \"{args.name}\"")
    return 0


def cmd_namespace_delete(args) -> int:
    api = make_client(args)
    api.namespaces.delete(args.name)
    print(f"Successfully deleted namespace \"{args.name}\"")
    return 0


def cmd_service_list(args) -> int:
    api = make_client(args)
    rows = []
    for ns_block in api.services.list():
        for svc in ns_block.get("Services", []):
            rows.append({
                "ServiceName": svc.get("ServiceName", ""),
                "Namespace": ns_block.get("Namespace", ""),
                "Tags": ",".join(svc.get("Tags", [])),
            })
    print(dict_rows(rows, ["ServiceName", "Namespace", "Tags"]))
    return 0


def cmd_service_info(args) -> int:
    api = make_client(args)
    regs = api.services.get(args.service_name)
    print(dict_rows(regs, ["ID", "Address", "Port", "NodeID", "AllocID"]))
    return 0


def cmd_service_delete(args) -> int:
    api = make_client(args)
    api.services.delete(args.service_name, args.service_id)
    print(f"Successfully deleted service registration \"{args.service_id}\"")
    return 0


def cmd_volume_register(args) -> int:
    import json as _json

    api = make_client(args)
    with open(args.file) as f:
        spec = f.read()
    try:
        vol = _json.loads(spec)
    except _json.JSONDecodeError:
        from nomad_tpu.jobspec.hcl import parse_hcl
        vol = parse_hcl(spec).get("volume", {})
    api.csi_volumes.register(vol)
    print(f"Successfully registered volume \"{vol.get('ID', vol.get('id', ''))}\"")
    return 0


def cmd_volume_status(args) -> int:
    api = make_client(args)
    if args.volume_id:
        v = api.csi_volumes.info(args.volume_id)
        print(format_kv([
            f"ID|{v.get('ID', '')}",
            f"Name|{v.get('Name', '')}",
            f"External ID|{v.get('ExternalID', '')}",
            f"Plugin ID|{v.get('PluginID', '')}",
            f"Schedulable|{v.get('Schedulable', '')}",
            f"Readers|{len(v.get('ReadClaims') or {})}",
            f"Writers|{len(v.get('WriteClaims') or {})}",
        ]))
    else:
        vols = api.csi_volumes.list()
        print(dict_rows(vols, ["ID", "Name", "PluginID", "Schedulable"]))
    return 0


def cmd_volume_deregister(args) -> int:
    api = make_client(args)
    api.csi_volumes.deregister(args.volume_id, force=args.force)
    print(f"Successfully deregistered volume \"{args.volume_id}\"")
    return 0


def cmd_volume_detach(args) -> int:
    api = make_client(args)
    api.csi_volumes.detach(args.volume_id, node_id=args.node or "")
    print(f"Successfully detached volume \"{args.volume_id}\"")
    return 0


def cmd_plugin_status(args) -> int:
    api = make_client(args)
    if args.plugin_id:
        p = api.csi_plugins.info(args.plugin_id)
        print(format_kv([
            f"ID|{p.get('ID', '')}",
            f"Provider|{p.get('Provider', '')}",
            f"Controllers Healthy|{p.get('ControllersHealthy', 0)}",
            f"Nodes Healthy|{p.get('NodesHealthy', 0)}",
        ]))
    else:
        plugins = api.csi_plugins.list()
        print(dict_rows(
            plugins,
            ["ID", "Provider", "ControllersHealthy", "NodesHealthy"],
        ))
    return 0


def cmd_acl_bootstrap(args) -> int:
    api = make_client(args)
    tok = api.acl.bootstrap()
    print(format_kv([
        f"Accessor ID|{tok.get('AccessorID', '')}",
        f"Secret ID|{tok.get('SecretID', '')}",
        f"Type|{tok.get('Type', '')}",
    ]))
    return 0


def cmd_acl_policy_apply(args) -> int:
    api = make_client(args)
    with open(args.rules_file) as f:
        rules = f.read()
    api.acl.put_policy(args.name, rules, args.description or "")
    print(f"Successfully wrote \"{args.name}\" ACL policy")
    return 0


def cmd_acl_policy_list(args) -> int:
    api = make_client(args)
    print(dict_rows(api.acl.policies(), ["Name", "Description"]))
    return 0


def cmd_acl_policy_delete(args) -> int:
    api = make_client(args)
    api.acl.delete_policy(args.name)
    print(f"Successfully deleted \"{args.name}\" ACL policy")
    return 0


def cmd_acl_token_create(args) -> int:
    api = make_client(args)
    tok = api.acl.create_token(
        name=args.name or "", type=args.type,
        policies=args.policy or [], global_=args.global_token,
    )
    print(format_kv([
        f"Accessor ID|{tok.get('AccessorID', '')}",
        f"Secret ID|{tok.get('SecretID', '')}",
        f"Name|{tok.get('Name', '')}",
        f"Type|{tok.get('Type', '')}",
        f"Policies|{','.join(tok.get('Policies') or [])}",
    ]))
    return 0


def cmd_acl_token_list(args) -> int:
    api = make_client(args)
    print(dict_rows(api.acl.tokens(), ["AccessorID", "Name", "Type"]))
    return 0


def cmd_acl_token_delete(args) -> int:
    api = make_client(args)
    api.acl.delete_token(args.accessor_id)
    print("Token deleted")
    return 0


def cmd_operator_scheduler_get(args) -> int:
    api = make_client(args)
    cfg = api.operator.scheduler_config()["SchedulerConfig"]
    print(format_kv([
        f"Scheduler Algorithm|{cfg.get('SchedulerAlgorithm', '')}",
        f"Preemption System|{(cfg.get('PreemptionConfig') or {}).get('SystemSchedulerEnabled', False)}",
        f"Preemption Service|{(cfg.get('PreemptionConfig') or {}).get('ServiceSchedulerEnabled', False)}",
        f"Preemption Batch|{(cfg.get('PreemptionConfig') or {}).get('BatchSchedulerEnabled', False)}",
    ]))
    return 0


def cmd_operator_scheduler_set(args) -> int:
    api = make_client(args)
    cfg = api.operator.scheduler_config()["SchedulerConfig"]
    if args.scheduler_algorithm:
        cfg["SchedulerAlgorithm"] = args.scheduler_algorithm
    api.operator.set_scheduler_config(cfg)
    print("Scheduler configuration updated!")
    return 0


def cmd_operator_snapshot_save(args) -> int:
    api = make_client(args)
    data = api.operator.snapshot_save()
    with open(args.file, "wb") as f:
        f.write(data)
    print(f"State file written to {args.file} ({len(data)} bytes)")
    return 0


def cmd_operator_snapshot_restore(args) -> int:
    api = make_client(args)
    with open(args.file, "rb") as f:
        data = f.read()
    api.operator.snapshot_restore(data)
    print("Snapshot restored")
    return 0


def cmd_operator_autopilot_get(args) -> int:
    api = make_client(args)
    cfg = api.operator.autopilot_configuration()
    print(format_kv([
        f"CleanupDeadServers|{cfg.get('CleanupDeadServers', '')}",
        f"LastContactThreshold|{cfg.get('LastContactThreshold', '')}",
        f"ServerStabilizationTime|{cfg.get('ServerStabilizationTime', '')}",
    ]))
    return 0


def cmd_operator_autopilot_set(args) -> int:
    api = make_client(args)
    cfg = api.operator.autopilot_configuration()
    if args.cleanup_dead_servers is not None:
        cfg["CleanupDeadServers"] = args.cleanup_dead_servers == "true"
    if args.last_contact_threshold:
        cfg["LastContactThreshold"] = args.last_contact_threshold
    api.operator.set_autopilot_configuration(cfg)
    print("Configuration updated!")
    return 0


def cmd_operator_autopilot_health(args) -> int:
    api = make_client(args)
    h = api.operator.autopilot_health()
    print(f"Healthy: {h.get('Healthy')}")
    print(f"FailureTolerance: {h.get('FailureTolerance')}")
    print(dict_rows(h.get("Servers", []),
                    ["ID", "Leader", "Healthy", "LastContact"]))
    return 0


def cmd_tls_ca_create(args) -> int:
    """tls_ca_create.go: write nomad-agent-ca{,-key}.pem."""
    from nomad_tpu.utils.tlsutil import generate_ca

    cert, key = generate_ca(common_name=args.common_name)
    for path, data, mode in (("nomad-agent-ca.pem", cert, 0o644),
                             ("nomad-agent-ca-key.pem", key, 0o600)):
        with open(path, "wb") as f:
            f.write(data)
        os.chmod(path, mode)
        print(f"==> CA {'certificate' if mode == 0o644 else 'key'} "
              f"saved to: {path}")
    return 0


def cmd_tls_cert_create(args) -> int:
    """tls_cert_create.go: issue a server/client/cli cert off the CA."""
    from nomad_tpu.utils.tlsutil import generate_cert

    try:
        with open(args.ca, "rb") as f:
            ca_cert = f.read()
        with open(args.key, "rb") as f:
            ca_key = f.read()
    except OSError as e:
        return _fail(f"cannot read CA material (run 'tls ca create' "
                     f"first?): {e}")
    role = "server" if args.server else ("client" if args.client else "cli")
    name = f"{role}.{args.cert_region}.nomad"
    cert, key = generate_cert(
        ca_cert, ca_key, common_name=name,
        san_dns=[name] + (args.additional_dnsname or []),
        # client *agents* also serve the HTTPS API (fs/exec proxying),
        # so their certs carry serverAuth too; only cli certs are
        # client-only (reference tls_cert_create.go)
        server=args.server or args.client,
        client=True,
    )
    base = f"{args.cert_region}-{role}-nomad"
    for suffix, data, mode in ((".pem", cert, 0o644),
                               ("-key.pem", key, 0o600)):
        path = base + suffix
        with open(path, "wb") as f:
            f.write(data)
        os.chmod(path, mode)
        print(f"==> Cert saved to: {path}")
    return 0


def cmd_monitor(args) -> int:
    api = make_client(args)
    try:
        for line in api.agent.monitor(log_level=args.log_level):
            print(line)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_operator_debug(args) -> int:
    """operator_debug.go: capture a support bundle."""
    import json as _json
    import tarfile
    import io
    import time as _time

    api = make_client(args)
    captures = {
        "agent-self.json": lambda: api.agent.self(),
        "agent-health.json": lambda: api.agent.health(),
        "agent-members.json": lambda: api.agent.members(),
        "metrics.json": lambda: api.agent.metrics(),
        "nodes.json": lambda: api.nodes.list(),
        "regions.json": lambda: api.get("/v1/regions"),
        "operator-raft.json": lambda: api.operator.raft_configuration(),
        "operator-autopilot-health.json":
            lambda: api.operator.autopilot_health(),
        "operator-scheduler-config.json":
            lambda: api.operator.scheduler_config(),
        "pprof-goroutine.txt": lambda: api.agent.pprof("goroutine"),
        "pprof-heap.txt": lambda: api.agent.pprof("heap"),
        "pprof-profile.txt":
            lambda: api.agent.pprof("profile", seconds=args.seconds),
    }
    out = args.output or f"nomad-debug-{int(_time.time())}.tar.gz"
    with tarfile.open(out, "w:gz") as tar:
        for name, fn in captures.items():
            try:
                payload = fn()
            except Exception as e:              # noqa: BLE001
                payload = {"error": str(e)}
            data = (payload if isinstance(payload, str)
                    else _json.dumps(payload, indent=2, default=str)).encode()
            info = tarfile.TarInfo(name=name)
            info.size = len(data)
            info.mtime = int(_time.time())
            tar.addfile(info, io.BytesIO(data))
            print(f"  captured {name}")
    print(f"Created debug archive: {out}")
    return 0


def cmd_operator_raft_list(args) -> int:
    api = make_client(args)
    cfg = api.operator.raft_configuration()
    servers = cfg.get("Servers") or []
    print(dict_rows(servers, ["ID", "Node", "Address", "Leader", "Voter"]))
    return 0


def cmd_server_members(args) -> int:
    api = make_client(args)
    res = api.agent.members()
    members = res.get("Members") or []
    rows = ["Name|Address|Status|Leader|Region|DC"]
    for m in members:
        rows.append(
            f"{m.get('Name', '')}|{m.get('Addr', '')}|{m.get('Status', '')}|"
            f"{m.get('Leader', False)}|"
            f"{(m.get('Tags') or {}).get('region', '')}|"
            f"{(m.get('Tags') or {}).get('dc', '')}"
        )
    print(format_list(rows))
    return 0


def cmd_system_gc(args) -> int:
    api = make_client(args)
    api.system.gc()
    return 0


def cmd_system_reconcile(args) -> int:
    api = make_client(args)
    api.system.reconcile_summaries()
    return 0


def cmd_ui(args) -> int:
    print(f"Opening URL \"{args.address}/ui\"")
    return 0


def cmd_version(args) -> int:
    print(f"nomad-tpu v{VERSION}")
    return 0


# --- agent --------------------------------------------------------------


def cmd_agent(args) -> int:
    """Run an agent process (command/agent/command.go Run)."""
    from nomad_tpu.api.agent import Agent, AgentConfig

    if args.config:
        from nomad_tpu.api.config_file import load_config_files
        try:
            cfg = load_config_files(args.config)
        except (OSError, ValueError) as e:
            return _fail(f"loading config: {e}")
        if args.dev:
            cfg.server_enabled = cfg.client_enabled = True
        cfg.server_enabled = cfg.server_enabled or args.server
        cfg.client_enabled = cfg.client_enabled or args.client
        if not (cfg.server_enabled or cfg.client_enabled):
            return _fail("config enables neither server nor client")
    elif args.dev:
        cfg = AgentConfig.dev()
    elif not args.server and not args.client:
        return _fail("must specify either -server, -client or -dev")
    else:
        cfg = AgentConfig(
            server_enabled=args.server, client_enabled=args.client
        )
    # explicit flags override config files (config.go merge order);
    # -bind/-http-port default to None so "flag given" is unambiguous
    if args.name:
        cfg.name = args.name
    cfg.region = args.region or cfg.region
    cfg.datacenter = args.dc or cfg.datacenter
    if args.bind is not None:
        cfg.bind_addr = args.bind
    if args.http_port is not None:
        cfg.http_port = args.http_port
    elif cfg.http_port == 0:
        cfg.http_port = 4646   # reference default port
    if args.raft_peers:
        cfg.raft_peers = list(args.raft_peers)
    if args.raft_port is not None:
        cfg.raft_port = args.raft_port
    if args.raft_advertise:
        cfg.raft_advertise = args.raft_advertise
    if args.plugin_dir:
        cfg.plugin_dir = args.plugin_dir
    if args.tls_cert or args.tls_key:
        if not (args.tls_cert and args.tls_key and args.tls_ca):
            return _fail("TLS needs -tls-ca, -tls-cert and -tls-key")
        from nomad_tpu.utils.tlsutil import TLSConfig
        cfg.tls = TLSConfig(
            enabled=True, ca_file=args.tls_ca, cert_file=args.tls_cert,
            key_file=args.tls_key,
            verify_https_client=args.tls_verify_https_client,
        )
    try:
        agent = Agent(cfg)
    except ValueError as e:
        return _fail(str(e))
    agent.start()
    print(f"==> Nomad-TPU agent started! HTTP at {agent.http_addr}")
    mode = ("server+client" if cfg.server_enabled and cfg.client_enabled
            else "server" if cfg.server_enabled else "client")
    print(f"    Mode: {mode}  Region: {cfg.region}  DC: {cfg.datacenter}")

    stop = {"flag": False}

    def _sig(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    try:
        while not stop["flag"]:
            time.sleep(0.2)
    finally:
        print("==> Caught signal, gracefully shutting down")
        agent.shutdown()
    return 0


# --- parser -------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nomad-tpu")
    p.add_argument("-address", default=os.environ.get(
        "NOMAD_ADDR", "http://127.0.0.1:4646"))
    p.add_argument("-token", default=os.environ.get("NOMAD_TOKEN", ""))
    p.add_argument("-namespace", default=os.environ.get(
        "NOMAD_NAMESPACE", "default"))
    p.add_argument("-region", default=os.environ.get("NOMAD_REGION", ""))
    p.add_argument("-ca-cert", dest="ca_cert",
                   default=os.environ.get("NOMAD_CACERT", ""))
    p.add_argument("-client-cert", dest="client_cert",
                   default=os.environ.get("NOMAD_CLIENT_CERT", ""))
    p.add_argument("-client-key", dest="client_key",
                   default=os.environ.get("NOMAD_CLIENT_KEY", ""))
    sub = p.add_subparsers(dest="command")

    # agent
    ag = sub.add_parser("agent", help="run an agent")
    ag.add_argument("-dev", action="store_true")
    ag.add_argument("-server", action="store_true")
    ag.add_argument("-client", action="store_true")
    ag.add_argument("-name", default="")
    ag.add_argument("-dc", default="")
    ag.add_argument("-bind", default=None)
    ag.add_argument("-http-port", dest="http_port", type=int, default=None)
    ag.add_argument("-config", action="append", default=[],
                    help="config file or directory (repeatable)")
    ag.add_argument("-raft-port", dest="raft_port", type=int, default=None)
    ag.add_argument("-raft-peer", dest="raft_peers", action="append",
                    default=[], help="raft address of a server peer "
                    "(repeatable; enables HA mode)")
    ag.add_argument("-raft-advertise", dest="raft_advertise", default="",
                    help="address peers dial this server's raft on "
                    "(required with a wildcard -bind)")
    ag.add_argument("-plugin-dir", dest="plugin_dir", default="",
                    help="directory of external driver plugins")
    ag.add_argument("-tls-ca", dest="tls_ca", default="")
    ag.add_argument("-tls-cert", dest="tls_cert", default="")
    ag.add_argument("-tls-key", dest="tls_key", default="")
    ag.add_argument("-tls-verify-https-client", action="store_true",
                    dest="tls_verify_https_client")
    ag.set_defaults(fn=cmd_agent)

    # job
    job = sub.add_parser("job", help="job commands").add_subparsers(
        dest="subcommand", required=True)
    jr = job.add_parser("run")
    jr.add_argument("jobfile")
    jr.add_argument("-detach", action="store_true")
    jr.add_argument("-var", action="append", dest="var")
    jr.set_defaults(fn=cmd_job_run)
    jp = job.add_parser("plan")
    jp.add_argument("jobfile")
    jp.add_argument("-var", action="append", dest="var")
    jp.set_defaults(fn=cmd_job_plan)
    jv = job.add_parser("validate")
    jv.add_argument("jobfile")
    jv.add_argument("-var", action="append", dest="var")
    jv.set_defaults(fn=cmd_job_validate)
    js = job.add_parser("status")
    js.add_argument("job_id", nargs="?", default="")
    js.set_defaults(fn=cmd_job_status)
    jst = job.add_parser("stop")
    jst.add_argument("job_id")
    jst.add_argument("-purge", action="store_true")
    jst.add_argument("-detach", action="store_true")
    jst.set_defaults(fn=cmd_job_stop)
    ji = job.add_parser("inspect")
    ji.add_argument("job_id")
    ji.set_defaults(fn=cmd_job_inspect)
    jh = job.add_parser("history")
    jh.add_argument("job_id")
    jh.set_defaults(fn=cmd_job_history)
    jrev = job.add_parser("revert")
    jrev.add_argument("job_id")
    jrev.add_argument("version", type=int)
    jrev.add_argument("-detach", action="store_true")
    jrev.set_defaults(fn=cmd_job_revert)
    jd = job.add_parser("dispatch")
    jd.add_argument("job_id")
    jd.add_argument("input_file", nargs="?", default="")
    jd.add_argument("-meta", action="append")
    jd.add_argument("-detach", action="store_true")
    jd.set_defaults(fn=cmd_job_dispatch)
    jsc = job.add_parser("scale")
    jsc.add_argument("job_id")
    jsc.add_argument("group")
    jsc.add_argument("count", type=int)
    jsc.add_argument("-detach", action="store_true")
    jsc.set_defaults(fn=cmd_job_scale)
    jpf = job.add_parser("periodic-force")
    jpf.add_argument("job_id")
    jpf.set_defaults(fn=cmd_job_periodic_force)
    jdp = job.add_parser("deployments")
    jdp.add_argument("job_id")
    jdp.set_defaults(fn=cmd_job_deployments)

    # run/stop/plan top-level aliases (reference keeps both)
    run = sub.add_parser("run")
    run.add_argument("jobfile")
    run.add_argument("-detach", action="store_true")
    run.add_argument("-var", action="append", dest="var")
    run.set_defaults(fn=cmd_job_run)
    stop = sub.add_parser("stop")
    stop.add_argument("job_id")
    stop.add_argument("-purge", action="store_true")
    stop.add_argument("-detach", action="store_true")
    stop.set_defaults(fn=cmd_job_stop)
    plan = sub.add_parser("plan")
    plan.add_argument("jobfile")
    plan.add_argument("-var", action="append", dest="var")
    plan.set_defaults(fn=cmd_job_plan)

    # node
    node = sub.add_parser("node", help="node commands").add_subparsers(
        dest="subcommand", required=True)
    ns = node.add_parser("status")
    ns.add_argument("node_id", nargs="?", default="")
    ns.set_defaults(fn=cmd_node_status)
    nd = node.add_parser("drain")
    nd.add_argument("node_id")
    nd.add_argument("-enable", action="store_true")
    nd.add_argument("-disable", action="store_true")
    nd.add_argument("-deadline", type=float, default=0.0)
    nd.set_defaults(fn=cmd_node_drain)
    ne = node.add_parser("eligibility")
    ne.add_argument("node_id")
    ne.add_argument("-enable", action="store_true")
    ne.add_argument("-disable", action="store_true")
    ne.set_defaults(fn=cmd_node_eligibility)

    # alloc
    alloc = sub.add_parser("alloc", help="alloc commands").add_subparsers(
        dest="subcommand", required=True)
    als = alloc.add_parser("status")
    als.add_argument("alloc_id")
    als.set_defaults(fn=cmd_alloc_status)
    alst = alloc.add_parser("stop")
    alst.add_argument("alloc_id")
    alst.add_argument("-detach", action="store_true")
    alst.set_defaults(fn=cmd_alloc_stop)
    alog = alloc.add_parser("logs")
    alog.add_argument("alloc_id")
    alog.add_argument("task")
    alog.add_argument("-stderr", action="store_true")
    alog.add_argument("-f", dest="follow", action="store_true")
    alog.set_defaults(fn=cmd_alloc_logs)
    ares = alloc.add_parser("restart")
    ares.add_argument("alloc_id")
    ares.add_argument("task", nargs="?", default="")
    ares.set_defaults(fn=cmd_alloc_restart)
    asig = alloc.add_parser("signal")
    asig.add_argument("-s", dest="signal", default="SIGTERM")
    asig.add_argument("alloc_id")
    asig.add_argument("task", nargs="?", default="")
    asig.set_defaults(fn=cmd_alloc_signal)
    aex = alloc.add_parser("exec")
    aex.add_argument("-task", required=True)
    aex.add_argument("-i", dest="interactive", action="store_true",
                     help="stream stdin (websocket exec)")
    aex.add_argument("-t", dest="tty", action="store_true",
                     help="allocate a pty")
    aex.add_argument("alloc_id")
    aex.add_argument("cmd", nargs="+")
    aex.set_defaults(fn=cmd_alloc_exec)
    afs = alloc.add_parser("fs")
    afs.add_argument("alloc_id")
    afs.add_argument("path", nargs="?", default="/")
    afs.set_defaults(fn=cmd_alloc_fs)

    # eval
    ev = sub.add_parser("eval", help="eval commands").add_subparsers(
        dest="subcommand", required=True)
    evl = ev.add_parser("list")
    evl.add_argument("-limit", type=int, default=50)
    evl.set_defaults(fn=cmd_eval_list)
    evs = ev.add_parser("status")
    evs.add_argument("eval_id")
    evs.set_defaults(fn=cmd_eval_status)

    # deployment
    dep = sub.add_parser("deployment").add_subparsers(
        dest="subcommand", required=True)
    dl = dep.add_parser("list")
    dl.set_defaults(fn=cmd_deployment_list)
    ds = dep.add_parser("status")
    ds.add_argument("deployment_id")
    ds.set_defaults(fn=cmd_deployment_status)
    dpm = dep.add_parser("promote")
    dpm.add_argument("deployment_id")
    dpm.set_defaults(fn=cmd_deployment_promote)
    df = dep.add_parser("fail")
    df.add_argument("deployment_id")
    df.set_defaults(fn=cmd_deployment_fail)
    dpa = dep.add_parser("pause")
    dpa.add_argument("deployment_id")
    dpa.add_argument("-resume", action="store_true")
    dpa.set_defaults(fn=cmd_deployment_pause)

    # status
    st = sub.add_parser("status", help="generic identifier lookup")
    st.add_argument("identifier", nargs="?", default="")
    st.set_defaults(fn=cmd_status)

    # namespace
    nsp = sub.add_parser("namespace").add_subparsers(
        dest="subcommand", required=True)
    nl = nsp.add_parser("list")
    nl.set_defaults(fn=cmd_namespace_list)
    na = nsp.add_parser("apply")
    na.add_argument("name")
    na.add_argument("-description", default="")
    na.set_defaults(fn=cmd_namespace_apply)
    ndel = nsp.add_parser("delete")
    ndel.add_argument("name")
    ndel.set_defaults(fn=cmd_namespace_delete)

    # service (native discovery)
    svc = sub.add_parser("service").add_subparsers(dest="subcommand",
                                                   required=True)
    svl = svc.add_parser("list")
    svl.set_defaults(fn=cmd_service_list)
    svi = svc.add_parser("info")
    svi.add_argument("service_name")
    svi.set_defaults(fn=cmd_service_info)
    svd = svc.add_parser("delete")
    svd.add_argument("service_name")
    svd.add_argument("service_id")
    svd.set_defaults(fn=cmd_service_delete)

    # volume + plugin (CSI)
    vol = sub.add_parser("volume").add_subparsers(dest="subcommand",
                                                  required=True)
    vreg = vol.add_parser("register")
    vreg.add_argument("file")
    vreg.set_defaults(fn=cmd_volume_register)
    vst = vol.add_parser("status")
    vst.add_argument("volume_id", nargs="?", default="")
    vst.set_defaults(fn=cmd_volume_status)
    vdereg = vol.add_parser("deregister")
    vdereg.add_argument("volume_id")
    vdereg.add_argument("-force", action="store_true")
    vdereg.set_defaults(fn=cmd_volume_deregister)
    vdet = vol.add_parser("detach")
    vdet.add_argument("volume_id")
    vdet.add_argument("-node", default="")
    vdet.set_defaults(fn=cmd_volume_detach)

    plug = sub.add_parser("plugin").add_subparsers(dest="subcommand",
                                                   required=True)
    pst = plug.add_parser("status")
    pst.add_argument("plugin_id", nargs="?", default="")
    pst.set_defaults(fn=cmd_plugin_status)

    # acl
    acl = sub.add_parser("acl").add_subparsers(dest="subcommand",
                                               required=True)
    ab = acl.add_parser("bootstrap")
    ab.set_defaults(fn=cmd_acl_bootstrap)
    apol = acl.add_parser("policy").add_subparsers(dest="subsub",
                                                   required=True)
    apa = apol.add_parser("apply")
    apa.add_argument("name")
    apa.add_argument("rules_file")
    apa.add_argument("-description", default="")
    apa.set_defaults(fn=cmd_acl_policy_apply)
    apl = apol.add_parser("list")
    apl.set_defaults(fn=cmd_acl_policy_list)
    apd = apol.add_parser("delete")
    apd.add_argument("name")
    apd.set_defaults(fn=cmd_acl_policy_delete)
    atok = acl.add_parser("token").add_subparsers(dest="subsub",
                                                  required=True)
    atc = atok.add_parser("create")
    atc.add_argument("-name", default="")
    atc.add_argument("-type", default="client")
    atc.add_argument("-policy", action="append")
    atc.add_argument("-global", dest="global_token", action="store_true")
    atc.set_defaults(fn=cmd_acl_token_create)
    atl = atok.add_parser("list")
    atl.set_defaults(fn=cmd_acl_token_list)
    atd = atok.add_parser("delete")
    atd.add_argument("accessor_id")
    atd.set_defaults(fn=cmd_acl_token_delete)

    # operator
    op = sub.add_parser("operator").add_subparsers(dest="subcommand",
                                                   required=True)
    osch = op.add_parser("scheduler").add_subparsers(dest="subsub",
                                                     required=True)
    og = osch.add_parser("get-config")
    og.set_defaults(fn=cmd_operator_scheduler_get)
    ose = osch.add_parser("set-config")
    ose.add_argument("-scheduler-algorithm", dest="scheduler_algorithm",
                     choices=["binpack", "spread"], default="")
    ose.set_defaults(fn=cmd_operator_scheduler_set)
    osnap = op.add_parser("snapshot").add_subparsers(dest="subsub",
                                                     required=True)
    oss = osnap.add_parser("save")
    oss.add_argument("file")
    oss.set_defaults(fn=cmd_operator_snapshot_save)
    osr = osnap.add_parser("restore")
    osr.add_argument("file")
    osr.set_defaults(fn=cmd_operator_snapshot_restore)
    oauto = op.add_parser("autopilot").add_subparsers(dest="subsub",
                                                      required=True)
    oag = oauto.add_parser("get-config")
    oag.set_defaults(fn=cmd_operator_autopilot_get)
    oas = oauto.add_parser("set-config")
    oas.add_argument("-cleanup-dead-servers", dest="cleanup_dead_servers",
                     choices=["true", "false"], default=None)
    oas.add_argument("-last-contact-threshold",
                     dest="last_contact_threshold", default="")
    oas.set_defaults(fn=cmd_operator_autopilot_set)
    oah = oauto.add_parser("health")
    oah.set_defaults(fn=cmd_operator_autopilot_health)
    oraft = op.add_parser("raft").add_subparsers(dest="subsub",
                                                 required=True)
    orl = oraft.add_parser("list-peers")
    orl.set_defaults(fn=cmd_operator_raft_list)
    odbg = op.add_parser("debug")
    odbg.add_argument("-output", default="")
    odbg.add_argument("-seconds", type=int, default=2)
    odbg.set_defaults(fn=cmd_operator_debug)

    # monitor
    mon = sub.add_parser("monitor", help="stream agent logs")
    mon.add_argument("-log-level", dest="log_level", default="info")
    mon.set_defaults(fn=cmd_monitor)

    # tls
    tls = sub.add_parser("tls", help="TLS certificate helpers") \
        .add_subparsers(dest="subcommand", required=True)
    tca = tls.add_parser("ca").add_subparsers(dest="verb", required=True)
    tcac = tca.add_parser("create")
    tcac.add_argument("-common-name", dest="common_name",
                      default="nomad-tpu CA")
    tcac.set_defaults(fn=cmd_tls_ca_create)
    tcert = tls.add_parser("cert").add_subparsers(dest="verb", required=True)
    tcc = tcert.add_parser("create")
    tcc.add_argument("-ca", default="nomad-agent-ca.pem")
    tcc.add_argument("-key", default="nomad-agent-ca-key.pem")
    tcc.add_argument("-server", action="store_true")
    tcc.add_argument("-client", action="store_true")
    tcc.add_argument("-region", dest="cert_region", default="global")
    tcc.add_argument("-additional-dnsname", action="append",
                     dest="additional_dnsname")
    tcc.set_defaults(fn=cmd_tls_cert_create)

    # server
    srv = sub.add_parser("server").add_subparsers(dest="subcommand",
                                                  required=True)
    sm = srv.add_parser("members")
    sm.set_defaults(fn=cmd_server_members)

    # system
    system = sub.add_parser("system").add_subparsers(dest="subcommand",
                                                     required=True)
    sg = system.add_parser("gc")
    sg.set_defaults(fn=cmd_system_gc)
    sr = system.add_parser("reconcile").add_subparsers(dest="subsub",
                                                       required=True)
    srs = sr.add_parser("summaries")
    srs.set_defaults(fn=cmd_system_reconcile)

    # ui / version
    ui = sub.add_parser("ui")
    ui.set_defaults(fn=cmd_ui)
    ver = sub.add_parser("version")
    ver.set_defaults(fn=cmd_version)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "fn", None):
        parser.print_help()
        return 1
    try:
        return args.fn(args)
    except APIError as e:
        return _fail(str(e))
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    sys.exit(main())
