"""CLI verb tree over the /v1 SDK (reference: command/ + main.go)."""

from nomad_tpu.cli.main import main  # noqa: F401
