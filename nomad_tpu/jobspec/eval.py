"""HCL2 evaluation: variables, locals, functions, interpolation,
dynamic blocks.

Reference behavior: jobspec2/parse.go:19-40 decodes jobspecs with full
HCL2 — `variable` blocks overridable from the CLI, `locals`, a cty
stdlib function table (functions.go:26), `${...}` interpolation with
expressions, and `dynamic` block expansion. This module evaluates the
Body tree hcl.py produces into plain values before struct mapping:

- ``variable "name" { default = ... }`` + caller overrides
- ``locals { k = expr }`` (may reference vars and other locals)
- dotted references ``var.x`` / ``local.y`` / ``<iterator>.value``
- function calls ``upper(var.x)`` (subset of the cty stdlib)
- string interpolation ``"${expr}"`` including arithmetic/comparison/
  ternary operators inside the interpolation
- ``dynamic "svc" { for_each = ...; labels = [...]; content {...} }``

Out of scope (documented divergence): for-expressions, splat
operators, and operators outside ``${...}`` interpolations.
"""

from __future__ import annotations

import base64
import hashlib
import json
import math
import re
from typing import Any, Dict, List, Optional

from nomad_tpu.jobspec.hcl import Body, Call, HCLParseError, _Lexer, _parse_value


class EvalError(ValueError):
    pass


# -- function table (jobspec2/functions.go:26 cty stdlib subset) --------

def _format(fmt: str, *args: Any) -> str:
    # go-style verbs %s %d %v %f map onto %-formatting closely enough
    return re.sub(r"%v", "%s", fmt) % args


FUNCS: Dict[str, Any] = {
    "upper": lambda s: str(s).upper(),
    "lower": lambda s: str(s).lower(),
    "title": lambda s: str(s).title(),
    "trimspace": lambda s: str(s).strip(),
    "trimprefix": lambda s, p: str(s)[len(p):] if str(s).startswith(p) else str(s),
    "trimsuffix": lambda s, p: str(s)[:-len(p)] if p and str(s).endswith(p) else str(s),
    "replace": lambda s, old, new: str(s).replace(old, new),
    "split": lambda sep, s: str(s).split(sep),
    "join": lambda sep, xs: str(sep).join(str(x) for x in xs),
    "format": _format,
    "length": lambda x: len(x),
    "concat": lambda *xs: [v for x in xs for v in x],
    "contains": lambda xs, v: v in xs,
    "coalesce": lambda *xs: next((x for x in xs if x not in (None, "")), None),
    "min": min,
    "max": max,
    "abs": abs,
    "ceil": lambda x: int(math.ceil(x)),
    "floor": lambda x: int(math.floor(x)),
    "pow": lambda a, b: a ** b,
    "range": lambda *a: list(range(*(int(x) for x in a))),
    "element": lambda xs, i: xs[int(i) % len(xs)],
    "keys": lambda m: sorted(m.keys()),
    "values": lambda m: [m[k] for k in sorted(m.keys())],
    "lookup": lambda m, k, *d: m.get(k, d[0] if d else None),
    "merge": lambda *ms: {k: v for m in ms for k, v in m.items()},
    "flatten": lambda xs: [v for x in xs
                           for v in (x if isinstance(x, list) else [x])],
    "distinct": lambda xs: list(dict.fromkeys(xs)),
    "reverse": lambda xs: list(reversed(xs)),
    "sort": lambda xs: sorted(xs),
    "jsonencode": lambda x: json.dumps(x),
    "jsondecode": lambda s: json.loads(s),
    "base64encode": lambda s: base64.b64encode(str(s).encode()).decode(),
    "base64decode": lambda s: base64.b64decode(str(s)).decode(),
    "md5": lambda s: hashlib.md5(str(s).encode()).hexdigest(),
    "sha256": lambda s: hashlib.sha256(str(s).encode()).hexdigest(),
    "tostring": lambda x: str(x),
    "tonumber": lambda x: float(x) if "." in str(x) else int(x),
}

_INTERP_RE = re.compile(r"\$\{")

_REF_RE = re.compile(r"[A-Za-z_][\w-]*(\.[\w.-]+)*")


class Scope:
    def __init__(self, roots: Dict[str, Any]) -> None:
        self.roots = roots

    def child(self, extra: Dict[str, Any]) -> "Scope":
        merged = dict(self.roots)
        merged.update(extra)
        return Scope(merged)

    def resolve(self, path: str) -> Any:
        parts = path.split(".")
        if parts[0] not in self.roots:
            raise KeyError(path)
        cur: Any = self.roots[parts[0]]
        for p in parts[1:]:
            if isinstance(cur, dict):
                if p not in cur:
                    raise EvalError(f"unknown reference {path!r}")
                cur = cur[p]
            else:
                raise EvalError(f"cannot index {path!r}")
        return cur


def eval_value(v: Any, scope: Scope) -> Any:
    if isinstance(v, str):
        return _eval_string(v, scope)
    if isinstance(v, Call):
        fn = FUNCS.get(v.name)
        if fn is None:
            raise EvalError(f"unknown function {v.name!r}")
        return fn(*[eval_value(a, scope) for a in v.args])
    if isinstance(v, list):
        return [eval_value(x, scope) for x in v]
    if isinstance(v, dict):
        return {k: eval_value(x, scope) for k, x in v.items()}
    return v


def _eval_string(s: str, scope: Scope) -> Any:
    """Bare dotted reference or ${...} interpolation; plain strings
    pass through."""
    # bare reference: whole string is a resolvable dotted path
    if re.fullmatch(r"[A-Za-z_][\w-]*(\.[\w-]+)+", s):
        try:
            return scope.resolve(s)
        except KeyError:
            return s    # enum-ish bare ident ("system", "host", ...)
    if "${" not in s:
        return s
    # parts: (is_expr, value); a string that is exactly one ${expr}
    # keeps the expression's native type (HCL2 semantics)
    parts: List[tuple] = []
    i = 0
    while i < len(s):
        m = _INTERP_RE.search(s, i)
        if m is None:
            if s[i:]:
                parts.append((False, s[i:]))
            break
        if s[i:m.start()]:
            parts.append((False, s[i:m.start()]))
        # brace-match the expression
        depth = 1
        j = m.end()
        while j < len(s) and depth:
            if s[j] == "{":
                depth += 1
            elif s[j] == "}":
                depth -= 1
            j += 1
        if depth:
            raise EvalError(f"unterminated interpolation in {s!r}")
        expr = s[m.end():j - 1]
        root = expr.strip().split(".")[0].split("[")[0]
        if _REF_RE.fullmatch(expr.strip()) and root not in scope.roots:
            # a bare reference whose root is not a parse-time scope
            # (attr.*, node.*, env.*, meta.*, NOMAD_* and other
            # runtime env) stays literal for the scheduler/client to
            # resolve; only var./local./iterator roots evaluate here
            parts.append((False, "${" + expr + "}"))
        else:
            parts.append((True, eval_expr(expr, scope)))
        i = j
    if len(parts) == 1 and parts[0][0]:
        return parts[0][1]
    return "".join(v if not is_expr else _to_str(v)
                   for is_expr, v in parts)


def _to_str(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


# -- expression mini-parser for interpolations -------------------------
# precedence-climbing over: literals, refs, calls, unary !/-, binary
# arithmetic/comparison/logical, ternary ?:

_BINOPS = [
    ("||",), ("&&",), ("==", "!="), ("<=", ">=", "<", ">"),
    ("+", "-"), ("*", "/", "%"),
]


def eval_expr(text: str, scope: Scope) -> Any:
    p = _ExprParser(text, scope)
    try:
        v = p.parse_ternary()
    except EvalError:
        raise
    except Exception as e:   # noqa: BLE001 — IndexError/TypeError/...
        raise EvalError(f"error evaluating {text!r}: {e}")
    p.skip()
    if not p.at_end():
        raise EvalError(f"trailing input in expression {text!r}")
    return v


class _ExprParser:
    def __init__(self, text: str, scope: Scope) -> None:
        self.text = text
        self.pos = 0
        self.scope = scope
        # >0 while parsing a ternary branch the condition excluded:
        # the branch must still be consumed syntactically, but its
        # evaluation is suppressed (errors in dead branches are fine
        # — the HCL guard-then-index idiom depends on it)
        self.dead = 0

    def at_end(self) -> bool:
        return self.pos >= len(self.text)

    def skip(self) -> None:
        while not self.at_end() and self.text[self.pos] in " \t\r\n":
            self.pos += 1

    def _match(self, tok: str) -> bool:
        self.skip()
        if self.text.startswith(tok, self.pos):
            nxt = (self.text[self.pos + len(tok)]
                   if self.pos + len(tok) < len(self.text) else "")
            # don't split "<=" into "<" etc.
            if tok in ("<", ">", "=", "!") and nxt == "=":
                return False
            self.pos += len(tok)
            return True
        return False

    def _parse_dead(self, fn) -> Any:
        self.dead += 1
        try:
            return fn()
        finally:
            self.dead -= 1

    def parse_ternary(self) -> Any:
        cond = self.parse_binary(0)
        if self._match("?"):
            take_a = bool(cond) and not self.dead
            a = self.parse_ternary() if take_a \
                else self._parse_dead(self.parse_ternary)
            self.skip()
            if not self._match(":"):
                raise EvalError("expected ':' in ternary")
            b = self._parse_dead(self.parse_ternary) if take_a or self.dead \
                else self.parse_ternary()
            return a if take_a else b
        return cond

    def parse_binary(self, level: int) -> Any:
        if level >= len(_BINOPS):
            return self.parse_unary()
        left = self.parse_binary(level + 1)
        while True:
            matched = None
            for op in _BINOPS[level]:
                if self._match(op):
                    matched = op
                    break
            if matched is None:
                return left
            right = self.parse_binary(level + 1)
            left = None if self.dead else _apply(matched, left, right)

    def parse_unary(self) -> Any:
        self.skip()
        if self._match("!"):
            v = self.parse_unary()
            return None if self.dead else not v
        if not self.at_end() and self.text[self.pos] == "-" and not (
            self.pos + 1 < len(self.text) and self.text[self.pos + 1].isdigit()
        ):
            self.pos += 1
            v = self.parse_unary()
            return None if self.dead else -v
        return self.parse_primary()

    def parse_primary(self) -> Any:
        self.skip()
        if self._match("("):
            v = self.parse_ternary()
            self.skip()
            if not self._match(")"):
                raise EvalError("expected ')'")
            return v
        # reuse the HCL value lexer for literals/refs/calls
        was_quoted = not self.at_end() and self.text[self.pos] == '"'
        lx = _Lexer(self.text[self.pos:])
        try:
            raw = _parse_value(lx)
        except HCLParseError as e:
            raise EvalError(f"bad expression at {self.text[self.pos:]!r}: {e}")
        self.pos += lx.pos
        if self.dead:
            val = None
        else:
            val = eval_value(raw, self.scope)
            if not was_quoted and isinstance(val, str) \
                    and re.fullmatch(r"[A-Za-z_][\w-]*", val) and raw == val:
                # bare single ident inside an expression must resolve
                try:
                    return self.scope.resolve(val)
                except (KeyError, EvalError):
                    raise EvalError(f"unknown reference {val!r}")
        # indexing: a[0] / m["k"]
        while True:
            self.skip()
            if not self.at_end() and self.text[self.pos] == "[":
                self.pos += 1
                idx = self.parse_ternary()
                self.skip()
                if not self._match("]"):
                    raise EvalError("expected ']'")
                if not self.dead:
                    val = val[idx if isinstance(idx, str) else int(idx)]
            else:
                return val


def _apply(op: str, a: Any, b: Any) -> Any:
    if op == "||":
        return bool(a) or bool(b)
    if op == "&&":
        return bool(a) and bool(b)
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == ">":
        return a > b
    if op == "<=":
        return a <= b
    if op == ">=":
        return a >= b
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "%":
        return a % b
    if op == "/":
        return a / b
    raise EvalError(f"unknown operator {op}")


# -- body evaluation ----------------------------------------------------

def _convert_override(raw: Any, default: Any) -> Any:
    """-var/NOMAD_VAR_* values arrive as strings; coerce to the
    declared variable's type (jobspec2 converts via the cty type)."""
    if not isinstance(raw, str) or isinstance(default, str) \
            or default is None:
        return raw
    try:
        if isinstance(default, bool):
            return raw.lower() in ("1", "true", "yes")
        if isinstance(default, int):
            return int(raw)
        if isinstance(default, float):
            return float(raw)
        if isinstance(default, (list, dict)):
            return json.loads(raw)
    except (ValueError, json.JSONDecodeError) as e:
        raise EvalError(
            f"cannot convert override {raw!r} to the variable's "
            f"{type(default).__name__} type: {e}")
    return raw


def evaluate(body: Body, variables: Optional[Dict[str, Any]] = None,
             env_variables: Optional[Dict[str, Any]] = None) -> Body:
    """Collect variable/locals blocks, then return a new Body with all
    expressions evaluated and dynamic blocks expanded.

    ``variables`` are explicit overrides (-var): naming an undeclared
    variable errors. ``env_variables`` come from the environment
    (NOMAD_VAR_*): undeclared ones are silently ignored, matching the
    reference's env handling."""
    overrides = variables or {}
    env_over = env_variables or {}
    var_values: Dict[str, Any] = {}
    for labels, vb in body.get_blocks("variable"):
        name = labels[0] if labels else ""
        default = None
        if "default" in vb.attrs:
            default = eval_value(vb.attrs["default"], Scope({"var": {}}))
        if name in overrides:
            var_values[name] = _convert_override(overrides[name], default)
        elif name in env_over:
            var_values[name] = _convert_override(env_over[name], default)
        elif "default" in vb.attrs:
            var_values[name] = default
        else:
            raise EvalError(f"variable {name!r} has no value "
                            "(no default, no override)")
    unknown = set(overrides) - set(var_values)
    if unknown:
        raise EvalError(f"undeclared variables passed: {sorted(unknown)}")

    scope = Scope({"var": var_values, "local": {}})
    # locals may reference vars and earlier locals; fixpoint over a few
    # passes handles forward references, cycles error out
    pending = {}
    for _labels, lb in body.get_blocks("locals"):
        pending.update(lb.attrs)
    for _ in range(len(pending) + 1):
        progressed = False
        for k, v in list(pending.items()):
            try:
                scope.roots["local"][k] = eval_value(v, scope)
            except (EvalError, KeyError):
                continue
            del pending[k]
            progressed = True
        if not pending:
            break
        if not progressed:
            raise EvalError(
                f"unresolvable locals (cycle or unknown ref): "
                f"{sorted(pending)}")

    return _eval_body(body, scope, drop={"variable", "locals"})


def _eval_body(body: Body, scope: Scope, drop=frozenset()) -> Body:
    out = Body()
    for k, v in body.attrs.items():
        out.attrs[k] = eval_value(v, scope)
    for btype, labels, child in body.blocks:
        if btype in drop:
            continue
        if btype == "dynamic":
            out.blocks.extend(_expand_dynamic(labels, child, scope))
            continue
        out.blocks.append((
            btype,
            [str(eval_value(l, scope)) for l in labels],
            _eval_body(child, scope),
        ))
    return out


def _expand_dynamic(labels: List[str], spec: Body, scope: Scope):
    """dynamic "svc" { for_each = <coll>; iterator = it;
    labels = [...]; content { ... } } -> N concrete svc blocks."""
    btype = labels[0] if labels else ""
    coll = eval_value(spec.attrs.get("for_each", []), scope)
    iterator = spec.attrs.get("iterator", btype)
    content = spec.first_block("content")
    if content is None:
        raise EvalError(f"dynamic {btype!r} has no content block")
    label_exprs = spec.attrs.get("labels", [])
    items = (list(coll.items()) if isinstance(coll, dict)
             else list(enumerate(coll)))
    blocks = []
    for key, value in items:
        sub = scope.child({iterator: {"key": key, "value": value}})
        blabels = [str(eval_value(l, sub)) for l in label_exprs]
        blocks.append((btype, blabels, _eval_body(content[1], sub)))
    return blocks
