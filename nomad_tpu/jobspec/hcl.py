"""A from-scratch HCL2-subset reader.

Covers what jobspecs and ACL policies use (reference jobspec2/parse.go
feeds hashicorp/hcl2; this is an independent implementation of the
subset): nested blocks with string labels, `key = value` attributes,
strings (escapes), numbers, bools, null, lists, objects, heredocs
(<<EOF / <<-EOF), and #, //, /* */ comments. Interpolations (`${...}`)
are preserved as literal text; duration strings ("10s", "5m") are the
caller's concern.

The parse result is a Body: ``attrs`` dict + ``blocks`` list of
(type, labels, Body).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


class HCLParseError(ValueError):
    def __init__(self, msg: str, line: int) -> None:
        super().__init__(f"line {line}: {msg}")
        self.line = line


@dataclass
class Call:
    """A function call expression, evaluated by jobspec/eval.py
    (reference jobspec2/functions.go stdlib)."""

    name: str
    args: List[Any]


@dataclass
class Body:
    attrs: Dict[str, Any] = field(default_factory=dict)
    blocks: List[Tuple[str, List[str], "Body"]] = field(default_factory=list)

    def get_blocks(self, btype: str) -> List[Tuple[List[str], "Body"]]:
        return [(labels, b) for t, labels, b in self.blocks if t == btype]

    def first_block(self, btype: str) -> Optional[Tuple[List[str], "Body"]]:
        found = self.get_blocks(btype)
        return found[0] if found else None


class _Lexer:
    def __init__(self, src: str) -> None:
        self.src = src
        self.pos = 0
        self.line = 1

    def error(self, msg: str) -> HCLParseError:
        return HCLParseError(msg, self.line)

    def _peek(self, ahead: int = 0) -> str:
        i = self.pos + ahead
        return self.src[i] if i < len(self.src) else ""

    def _advance(self) -> str:
        c = self.src[self.pos]
        self.pos += 1
        if c == "\n":
            self.line += 1
        return c

    def skip_space(self, newlines: bool = True) -> None:
        while self.pos < len(self.src):
            c = self._peek()
            if c in " \t\r" or (newlines and c == "\n"):
                self._advance()
            elif c == "#" or (c == "/" and self._peek(1) == "/"):
                while self.pos < len(self.src) and self._peek() != "\n":
                    self._advance()
            elif c == "/" and self._peek(1) == "*":
                self._advance(); self._advance()
                while self.pos < len(self.src) and not (
                    self._peek() == "*" and self._peek(1) == "/"
                ):
                    self._advance()
                if self.pos < len(self.src):
                    self._advance(); self._advance()
            else:
                return

    def at_end(self) -> bool:
        return self.pos >= len(self.src)

    def read_ident(self) -> str:
        start = self.pos
        while self.pos < len(self.src) and (
            self._peek().isalnum() or self._peek() in "_-."
        ):
            self._advance()
        if start == self.pos:
            raise self.error(f"expected identifier, got {self._peek()!r}")
        return self.src[start:self.pos]

    def read_string(self) -> str:
        quote = self._advance()  # "
        out = []
        while True:
            if self.at_end():
                raise self.error("unterminated string")
            c = self._advance()
            if c == quote:
                break
            if c == "\\":
                esc = self._advance()
                out.append({
                    "n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\",
                }.get(esc, "\\" + esc))
            else:
                out.append(c)
        return "".join(out)

    def read_heredoc(self) -> str:
        # at '<<'; optional '-'
        self._advance(); self._advance()
        indent = False
        if self._peek() == "-":
            indent = True
            self._advance()
        tag = self.read_ident()
        # consume to end of line
        while not self.at_end() and self._peek() != "\n":
            self._advance()
        if not self.at_end():
            self._advance()
        lines = []
        while True:
            if self.at_end():
                raise self.error(f"unterminated heredoc <<{tag}")
            start = self.pos
            while not self.at_end() and self._peek() != "\n":
                self._advance()
            line = self.src[start:self.pos]
            if not self.at_end():
                self._advance()
            if line.strip() == tag:
                break
            lines.append(line)
        if indent:
            strip = min(
                (len(l) - len(l.lstrip()) for l in lines if l.strip()),
                default=0,
            )
            lines = [l[strip:] for l in lines]
        return "\n".join(lines) + ("\n" if lines else "")

    def read_number(self):
        start = self.pos
        if self._peek() == "-":
            self._advance()
        while not self.at_end() and (self._peek().isdigit() or self._peek() == "."):
            self._advance()
        text = self.src[start:self.pos]
        # duration-ish suffix (5s, 10m): keep as string for the mapper
        if not self.at_end() and self._peek().isalpha():
            while not self.at_end() and self._peek().isalnum():
                self._advance()
            return self.src[start:self.pos]
        try:
            return float(text) if "." in text else int(text)
        except ValueError:
            raise self.error(f"bad number {text!r}")


def _parse_value(lx: _Lexer) -> Any:
    lx.skip_space()
    c = lx._peek()
    if c == '"':
        return lx.read_string()
    if c == "<" and lx._peek(1) == "<":
        return lx.read_heredoc()
    if c == "[":
        lx._advance()
        items = []
        while True:
            lx.skip_space()
            if lx._peek() == "]":
                lx._advance()
                return items
            items.append(_parse_value(lx))
            lx.skip_space()
            if lx._peek() == ",":
                lx._advance()
    if c == "{":
        lx._advance()
        obj: Dict[str, Any] = {}
        while True:
            lx.skip_space()
            if lx._peek() == "}":
                lx._advance()
                return obj
            if lx._peek() == '"':
                key = lx.read_string()
            else:
                key = lx.read_ident()
            lx.skip_space()
            if lx._peek() in "=:":
                lx._advance()
            obj[key] = _parse_value(lx)
            lx.skip_space()
            if lx._peek() == ",":
                lx._advance()
    if c.isdigit() or c == "-":
        return lx.read_number()
    ident = lx.read_ident()
    if ident == "true":
        return True
    if ident == "false":
        return False
    if ident == "null":
        return None
    lx.skip_space(newlines=False)
    if lx._peek() == "(":
        # function call: name(arg, ...) — evaluated by jobspec/eval.py
        lx._advance()
        args: List[Any] = []
        while True:
            lx.skip_space()
            if lx._peek() == ")":
                lx._advance()
                return Call(ident, args)
            args.append(_parse_value(lx))
            lx.skip_space()
            if lx._peek() == ",":
                lx._advance()
    # bare identifier: enum-ish value, or a var./local./iterator
    # reference the evaluator resolves against its scope
    return ident


def _parse_body(lx: _Lexer, terminator: str = "") -> Body:
    body = Body()
    while True:
        lx.skip_space()
        if lx.at_end():
            if terminator:
                raise lx.error(f"expected '{terminator}' before EOF")
            return body
        if terminator and lx._peek() == terminator:
            lx._advance()
            return body
        name = lx.read_ident() if lx._peek() != '"' else lx.read_string()
        lx.skip_space(newlines=False)
        c = lx._peek()
        if c == "=":
            lx._advance()
            body.attrs[name] = _parse_value(lx)
            continue
        # block: zero or more string labels, then {
        labels: List[str] = []
        while c == '"':
            labels.append(lx.read_string())
            lx.skip_space(newlines=False)
            c = lx._peek()
        if c != "{":
            raise lx.error(
                f"expected '=' or '{{' after {name!r}, got {c!r}"
            )
        lx._advance()
        body.blocks.append((name, labels, _parse_body(lx, "}")))


def parse(src: str) -> Body:
    lx = _Lexer(src)
    return _parse_body(lx)


def duration_s(v: Any, default: float = 0.0) -> float:
    """'30s' / '5m' / '1h30m' / 10 (seconds) -> seconds."""
    if v is None:
        return default
    if isinstance(v, (int, float)):
        return float(v)
    import re

    total = 0.0
    matched = False
    for m in re.finditer(r"(\d+(?:\.\d+)?)(ms|s|m|h|d)", str(v)):
        matched = True
        total += float(m.group(1)) * {
            "ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0,
        }[m.group(2)]
    if not matched:
        try:
            return float(v)
        except (TypeError, ValueError):
            return default
    return total
