"""Map parsed HCL / JSON jobspecs onto Job structs.

Reference behavior: jobspec/parse.go (block -> struct mapping, duration
parsing, singleton block enforcement) and jobspec2's HCL2 grammar. One
`job "id" { ... }` block with nested group/task/resources/network/
constraint/affinity/spread/update/migrate/restart/reschedule/periodic/
parameterized/scaling/volume/service/template/artifact/logs/lifecycle
blocks.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from nomad_tpu.jobspec.hcl import Body, duration_s, parse
from nomad_tpu.structs.constraints import Affinity, Constraint, Spread, SpreadTarget
from nomad_tpu.structs.job import (
    EphemeralDisk,
    Job,
    LogConfig,
    MigrateStrategy,
    ParameterizedJobConfig,
    PeriodicConfig,
    ReschedulePolicy,
    RestartPolicy,
    ScalingPolicy,
    Service,
    Task,
    TaskGroup,
    TaskLifecycleConfig,
    Template,
    UpdateStrategy,
    VolumeRequest,
)
from nomad_tpu.structs.network import NetworkResource, Port
from nomad_tpu.structs.resources import RequestedDevice, Resources


def parse_hcl(src: str, variables: Optional[Dict] = None,
              env_variables: Optional[Dict] = None) -> Job:
    """HCL jobspec text -> Job (jobspec2/parse.go Parse).

    ``variables`` overrides `variable` block defaults (the -var CLI
    flag — undeclared names error); ``env_variables`` are NOMAD_VAR_*
    values (undeclared names ignored)."""
    from nomad_tpu.jobspec.eval import evaluate

    body = evaluate(parse(src), variables, env_variables)
    found = body.first_block("job")
    if found is None:
        raise ValueError("jobspec must contain a 'job' block")
    labels, jb = found
    if not labels:
        raise ValueError("job block requires a label: job \"name\" { ... }")
    return _map_job(labels[0], jb)


def parse_json(data: Dict) -> Job:
    """JSON jobspec (the API wire shape) -> Job."""
    from nomad_tpu.api.codec import decode

    payload = data.get("Job", data)
    job = decode(payload, Job)
    if job is None or not job.id:
        raise ValueError("Job.ID is required")
    return job


# -- block mappers -------------------------------------------------------


def _constraints(body: Body) -> List[Constraint]:
    out = []
    for _labels, cb in body.get_blocks("constraint"):
        a = cb.attrs
        operand = a.get("operator", a.get("op", "="))
        # sugar forms (jobspec/parse.go parseConstraints)
        if "distinct_hosts" in a:
            out.append(Constraint(operand="distinct_hosts"))
            continue
        if "distinct_property" in a:
            out.append(Constraint(
                operand="distinct_property",
                ltarget=str(a["distinct_property"]),
                rtarget=str(a.get("value", "")),
            ))
            continue
        for sugar in ("regexp", "version", "semver", "set_contains"):
            if sugar in a:
                operand = sugar
                a = {**a, "value": a[sugar]}
                break
        out.append(Constraint(
            ltarget=str(a.get("attribute", "")),
            rtarget=str(a.get("value", "")),
            operand=str(operand),
        ))
    return out


def _affinities(body: Body) -> List[Affinity]:
    out = []
    for _labels, ab in body.get_blocks("affinity"):
        a = ab.attrs
        operand = a.get("operator", "=")
        for sugar in ("regexp", "version", "semver", "set_contains",
                      "set_contains_any", "set_contains_all"):
            if sugar in a:
                operand = sugar
                a = {**a, "value": a[sugar]}
                break
        out.append(Affinity(
            ltarget=str(a.get("attribute", "")),
            rtarget=str(a.get("value", "")),
            operand=str(operand),
            weight=int(a.get("weight", 50)),
        ))
    return out


def _spreads(body: Body) -> List[Spread]:
    out = []
    for _labels, sb in body.get_blocks("spread"):
        targets = [
            SpreadTarget(value=labels[0] if labels else "",
                         percent=int(tb.attrs.get("percent", 0)))
            for labels, tb in sb.get_blocks("target")
        ]
        out.append(Spread(
            attribute=str(sb.attrs.get("attribute", "")),
            weight=int(sb.attrs.get("weight", 50)),
            spread_target=targets,
        ))
    return out


def _network(nb: Body) -> NetworkResource:
    net = NetworkResource(
        mode=str(nb.attrs.get("mode", "host")),
        mbits=int(nb.attrs.get("mbits", 0)),
    )
    for labels, pb in nb.get_blocks("port"):
        label = labels[0] if labels else ""
        port = Port(
            label=label,
            value=int(pb.attrs.get("static", 0)),
            to=int(pb.attrs.get("to", 0)),
            host_network=str(pb.attrs.get("host_network", "default")),
        )
        if port.value:
            net.reserved_ports.append(port)
        else:
            net.dynamic_ports.append(port)
    return net


def _resources(rb: Body) -> Resources:
    r = Resources(
        cpu=int(rb.attrs.get("cpu", 100)),
        cores=int(rb.attrs.get("cores", 0)),
        memory_mb=int(rb.attrs.get("memory", 300)),
        memory_max_mb=int(rb.attrs.get("memory_max", 0)),
        disk_mb=int(rb.attrs.get("disk", 0)),
    )
    for labels, db in rb.get_blocks("device"):
        r.devices.append(RequestedDevice(
            name=labels[0] if labels else "",
            count=int(db.attrs.get("count", 1)),
            constraints=_constraints(db),
            affinities=_affinities(db),
        ))
    for _labels, nb in rb.get_blocks("network"):
        r.networks.append(_network(nb))
    return r


def _update(ub: Body) -> UpdateStrategy:
    a = ub.attrs
    return UpdateStrategy(
        stagger_s=duration_s(a.get("stagger"), 30.0),
        max_parallel=int(a.get("max_parallel", 1)),
        health_check=str(a.get("health_check", "checks")),
        min_healthy_time_s=duration_s(a.get("min_healthy_time"), 10.0),
        healthy_deadline_s=duration_s(a.get("healthy_deadline"), 300.0),
        progress_deadline_s=duration_s(a.get("progress_deadline"), 600.0),
        auto_revert=bool(a.get("auto_revert", False)),
        auto_promote=bool(a.get("auto_promote", False)),
        canary=int(a.get("canary", 0)),
    )


def _task(name: str, tb: Body) -> Task:
    a = tb.attrs
    task = Task(
        name=name,
        driver=str(a.get("driver", "mock")),
        env={k: str(v) for k, v in (a.get("env") or {}).items()}
        if isinstance(a.get("env"), dict) else {},
        meta={k: str(v) for k, v in (a.get("meta") or {}).items()}
        if isinstance(a.get("meta"), dict) else {},
        kill_timeout_s=duration_s(a.get("kill_timeout"), 5.0),
        kill_signal=str(a.get("kill_signal", "")),
        leader=bool(a.get("leader", False)),
        user=str(a.get("user", "")),
        constraints=_constraints(tb),
        affinities=_affinities(tb),
    )
    for _l, eb in tb.get_blocks("env"):
        task.env.update({k: str(v) for k, v in eb.attrs.items()})
    for _l, mb in tb.get_blocks("meta"):
        task.meta.update({k: str(v) for k, v in mb.attrs.items()})
    cfg = tb.first_block("config")
    if cfg is not None:
        task.config = _body_to_dict(cfg[1])
    res = tb.first_block("resources")
    if res is not None:
        task.resources = _resources(res[1])
    lc = tb.first_block("lifecycle")
    if lc is not None:
        task.lifecycle = TaskLifecycleConfig(
            hook=str(lc[1].attrs.get("hook", "")),
            sidecar=bool(lc[1].attrs.get("sidecar", False)),
        )
    logs = tb.first_block("logs")
    if logs is not None:
        task.log_config = LogConfig(
            max_files=int(logs[1].attrs.get("max_files", 10)),
            max_file_size_mb=int(logs[1].attrs.get("max_file_size", 10)),
        )
    for _l, t in tb.get_blocks("template"):
        task.templates.append(Template(
            source_path=str(t.attrs.get("source", "")),
            dest_path=str(t.attrs.get("destination", "")),
            embedded_tmpl=str(t.attrs.get("data", "")),
            change_mode=str(t.attrs.get("change_mode", "restart")),
            change_signal=str(t.attrs.get("change_signal", "")),
        ))
    vb = tb.first_block("vault")
    if vb is not None:
        from nomad_tpu.structs.job import Vault
        task.vault = Vault(
            policies=[str(p) for p in vb[1].attrs.get("policies", [])],
            env=bool(vb[1].attrs.get("env", True)),
            change_mode=str(vb[1].attrs.get("change_mode", "restart")),
            change_signal=str(vb[1].attrs.get("change_signal", "")),
        )
    for _l, art in tb.get_blocks("artifact"):
        task.artifacts.append(_body_to_dict(art))
    for labels, sb in tb.get_blocks("service"):
        task.services.append(_service(labels, sb))
    return task


def _service(labels: List[str], sb: Body) -> Service:
    svc = Service(
        name=str(sb.attrs.get("name", labels[0] if labels else "")),
        port_label=str(sb.attrs.get("port", "")),
        provider=str(sb.attrs.get("provider", "builtin")),
        tags=[str(t) for t in sb.attrs.get("tags", [])],
    )
    for _l, cb in sb.get_blocks("check"):
        check = _body_to_dict(cb)
        for dur in ("interval", "timeout"):
            if dur in check:
                check[dur] = duration_s(check[dur])
        svc.checks.append(check)
    # connect stanza (services.go ConsulConnect): sidecar_service with
    # optional proxy { upstreams { ... } local_service_port }, or
    # native = true
    for _l, conb in sb.get_blocks("connect"):
        if conb.attrs.get("native"):
            svc.connect["native"] = True
        for _sl, scb in conb.get_blocks("sidecar_service"):
            sidecar: dict = {}
            for _pl, pb in scb.get_blocks("proxy"):
                proxy = {"upstreams": []}
                if "local_service_port" in pb.attrs:
                    proxy["local_service_port"] = int(
                        pb.attrs["local_service_port"])
                for _ul, ub in pb.get_blocks("upstreams"):
                    proxy["upstreams"].append({
                        "destination_name": str(
                            ub.attrs.get("destination_name", "")),
                        "local_bind_port": int(
                            ub.attrs.get("local_bind_port", 0)),
                    })
                sidecar["proxy"] = proxy
            svc.connect["sidecar_service"] = sidecar
    return svc


def _group(name: str, gb: Body) -> TaskGroup:
    a = gb.attrs
    tg = TaskGroup(
        name=name,
        count=int(a.get("count", 1)),
        constraints=_constraints(gb),
        affinities=_affinities(gb),
        spreads=_spreads(gb),
        meta={k: str(v) for k, v in (a.get("meta") or {}).items()}
        if isinstance(a.get("meta"), dict) else {},
    )
    if "stop_after_client_disconnect" in a:
        tg.stop_after_client_disconnect_s = duration_s(
            a["stop_after_client_disconnect"]
        )
    if "max_client_disconnect" in a:
        tg.max_client_disconnect_s = duration_s(a["max_client_disconnect"])
    for _l, mb in gb.get_blocks("meta"):
        tg.meta.update({k: str(v) for k, v in mb.attrs.items()})
    for _l, nb in gb.get_blocks("network"):
        tg.networks.append(_network(nb))
    for labels, tb in gb.get_blocks("task"):
        tg.tasks.append(_task(labels[0] if labels else "", tb))
    for labels, vb in gb.get_blocks("volume"):
        va = vb.attrs
        tg.volumes[labels[0] if labels else ""] = VolumeRequest(
            name=labels[0] if labels else "",
            type=str(va.get("type", "host")),
            source=str(va.get("source", "")),
            read_only=bool(va.get("read_only", False)),
            access_mode=str(va.get("access_mode", "")),
            attachment_mode=str(va.get("attachment_mode", "")),
            per_alloc=bool(va.get("per_alloc", False)),
        )
    for labels, sb in gb.get_blocks("service"):
        tg.services.append(_service(labels, sb))
    rp = gb.first_block("restart")
    if rp is not None:
        ra = rp[1].attrs
        tg.restart_policy = RestartPolicy(
            attempts=int(ra.get("attempts", 2)),
            interval_s=duration_s(ra.get("interval"), 1800.0),
            delay_s=duration_s(ra.get("delay"), 15.0),
            mode=str(ra.get("mode", "fail")),
        )
    rs = gb.first_block("reschedule")
    if rs is not None:
        ra = rs[1].attrs
        tg.reschedule_policy = ReschedulePolicy(
            attempts=int(ra.get("attempts", 0)),
            interval_s=duration_s(ra.get("interval"), 0.0),
            delay_s=duration_s(ra.get("delay"), 30.0),
            delay_function=str(ra.get("delay_function", "exponential")),
            max_delay_s=duration_s(ra.get("max_delay"), 3600.0),
            unlimited=bool(ra.get("unlimited", False)),
        )
    ed = gb.first_block("ephemeral_disk")
    if ed is not None:
        ea = ed[1].attrs
        tg.ephemeral_disk = EphemeralDisk(
            size_mb=int(ea.get("size", 300)),
            sticky=bool(ea.get("sticky", False)),
            migrate=bool(ea.get("migrate", False)),
        )
    up = gb.first_block("update")
    if up is not None:
        tg.update = _update(up[1])
    mg = gb.first_block("migrate")
    if mg is not None:
        ma = mg[1].attrs
        tg.migrate = MigrateStrategy(
            max_parallel=int(ma.get("max_parallel", 1)),
            health_check=str(ma.get("health_check", "checks")),
            min_healthy_time_s=duration_s(ma.get("min_healthy_time"), 10.0),
            healthy_deadline_s=duration_s(ma.get("healthy_deadline"), 300.0),
        )
    sc = gb.first_block("scaling")
    if sc is not None:
        sa = sc[1].attrs
        policy = sc[1].first_block("policy")
        tg.scaling = ScalingPolicy(
            min=int(sa.get("min", 0)),
            max=int(sa.get("max", 0)),
            enabled=bool(sa.get("enabled", True)),
            policy=_body_to_dict(policy[1]) if policy else {},
        )
    return tg


def _map_job(job_id: str, jb: Body) -> Job:
    a = jb.attrs
    job = Job(
        id=job_id,
        name=str(a.get("name", job_id)),
        namespace=str(a.get("namespace", "default")),
        region=str(a.get("region", "global")),
        type=str(a.get("type", "service")),
        priority=int(a.get("priority", 50)),
        datacenters=[str(d) for d in a.get("datacenters", ["dc1"])],
        node_pool=str(a.get("node_pool", "default")),
        all_at_once=bool(a.get("all_at_once", False)),
        constraints=_constraints(jb),
        affinities=_affinities(jb),
        spreads=_spreads(jb),
        meta={k: str(v) for k, v in (a.get("meta") or {}).items()}
        if isinstance(a.get("meta"), dict) else {},
    )
    for _l, mb in jb.get_blocks("meta"):
        job.meta.update({k: str(v) for k, v in mb.attrs.items()})
    up = jb.first_block("update")
    if up is not None:
        job.update = _update(up[1])
    per = jb.first_block("periodic")
    if per is not None:
        pa = per[1].attrs
        job.periodic = PeriodicConfig(
            enabled=bool(pa.get("enabled", True)),
            spec=str(pa.get("cron", pa.get("spec", ""))),
            prohibit_overlap=bool(pa.get("prohibit_overlap", False)),
            timezone=str(pa.get("time_zone", "UTC")),
        )
    mr = jb.first_block("multiregion")
    if mr is not None:
        mrb = mr[1]
        multiregion: Dict = {"strategy": {}, "regions": []}
        strat = mrb.first_block("strategy")
        if strat is not None:
            sa = strat[1].attrs
            multiregion["strategy"] = {
                "max_parallel": int(sa.get("max_parallel", 0) or 0),
                "on_failure": str(sa.get("on_failure", "")),
            }
        for labels, rb in mrb.get_blocks("region"):
            ra = rb.attrs
            multiregion["regions"].append({
                "name": labels[0] if labels else "",
                "count": int(ra.get("count", 0) or 0),
                "datacenters": [str(d) for d in ra.get("datacenters", [])],
                "meta": {k: str(v) for k, v in (ra.get("meta") or {}).items()}
                if isinstance(ra.get("meta"), dict) else {},
            })
        job.multiregion = multiregion
    par = jb.first_block("parameterized")
    if par is not None:
        pa = par[1].attrs
        job.parameterized = ParameterizedJobConfig(
            payload=str(pa.get("payload", "optional")),
            meta_required=[str(m) for m in pa.get("meta_required", [])],
            meta_optional=[str(m) for m in pa.get("meta_optional", [])],
        )
    for labels, gb in jb.get_blocks("group"):
        job.task_groups.append(_group(labels[0] if labels else "", gb))
    # bare task at job level gets an implicit group (jobspec/parse.go)
    for labels, tb in jb.get_blocks("task"):
        name = labels[0] if labels else ""
        job.task_groups.append(TaskGroup(name=name, tasks=[_task(name, tb)]))
    return job


def _body_to_dict(body: Body) -> Dict[str, Any]:
    out: Dict[str, Any] = dict(body.attrs)
    for btype, labels, sub in body.blocks:
        entry = _body_to_dict(sub)
        if labels:
            out.setdefault(btype, {})[labels[0]] = entry
        else:
            out.setdefault(btype, []) if isinstance(out.get(btype), list) else None
            if isinstance(out.get(btype), list):
                out[btype].append(entry)
            elif btype in out:
                out[btype] = [out[btype], entry]
            else:
                out[btype] = entry
    return out
