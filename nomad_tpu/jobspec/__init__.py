"""Job specification parsing: HCL2-subset + JSON.

Reference: jobspec/ (HCL1, parse.go:26) and jobspec2/ (HCL2,
parse.go:19-40). The from-scratch parser in hcl.py covers the jobspec
grammar (blocks, attributes, lists, objects, heredocs, comments);
parse.py maps the syntax tree onto the Job structs.
"""

from nomad_tpu.jobspec.parse import parse_hcl, parse_json  # noqa: F401
