"""nomad-tpu: a TPU-native workload-orchestration framework.

A brand-new framework with the capabilities of HashiCorp Nomad (reference:
/root/reference, v1.3.x, Go), redesigned TPU-first: the per-evaluation
scheduling hot path (feasibility -> bin-pack -> spread -> score-normalization,
reference scheduler/stack.go:43-69) is a batched node-tensor kernel in JAX --
constraint checks are boolean masks, scoring is a vmap'd kernel, global node
selection is top-k/argmax, and the node axis shards across a TPU slice via
``jax.sharding`` + ``shard_map`` with ``psum``-style collectives.

Layer map (mirrors reference SURVEY.md section 1):
  structs/    core data model (reference nomad/structs/)
  tensors/    NodeTensor/AskTensor flattening contract (TPU-native, new)
  ops/        JAX scheduling kernels (replaces scheduler/ iterator hot loop)
  scheduler/  scheduler interface, reconciler, stacks (reference scheduler/)
  state/      versioned in-memory state store (reference nomad/state/)
  server/     eval broker, plan applier, workers, leader (reference nomad/)
  client/     node agent, fingerprinting, task runners (reference client/)
  api/        HTTP API + SDK (reference command/agent/, api/)
  parallel/   mesh/sharding utilities (TPU-native, new)
"""

__version__ = "0.1.0"
