"""`python -m nomad_tpu` — the single-binary entry point (main.go:80)."""

import sys

from nomad_tpu.cli import main

sys.exit(main())
