"""Mask-program + evaluated-mask caches.

Two LRU layers, both process-wide and thread-safe:

- **programs**: constraint-tree signature -> compiled ``MaskProgram``.
  Compilation is cheap but the signature is the sharing key: two jobs
  with equal trees land on ONE program (and so one evaluated mask).
- **masks**: (uid, structure_version, signature) -> ``MaskEntry`` —
  the fully-evaluated static feasibility plane plus the memoized side
  channel the Python builder produced per eval (per-reason filter
  counts for AllocMetric, per-class eligibility for blocked evals).
  Keyed by the usage index's generation key so node-structure forks
  invalidate cleanly; an entry evaluated against a different
  ClusterTensors object for the same key is re-checked against row
  count before reuse (rebuilds of one structure_version are
  bit-identical by the incremental-cache contract).

Evaluated masks are FROZEN and content-deduped: two signatures whose
masks come out equal share one canonical array, so wave members of
*different* jobs still ship one identity-shared base-mask plane per
wave (parallel/coalesce job-sharing group) and one device-resident
copy ever (tensors/device_state frozen registry).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from nomad_tpu.feasibility.compiler import MaskProgram, compile_program

__all__ = ["MaskEntry", "MaskProgramCache", "default_mask_cache"]


class MaskEntry:
    """One evaluated (program, node structure) result."""

    __slots__ = ("mask", "filter_counts", "class_job_elig",
                 "class_tg_elig", "cluster_n", "cluster_ref")

    def __init__(self, mask: np.ndarray,
                 filter_counts: List[Tuple[str, str, int]],
                 class_job_elig: Dict[str, bool],
                 class_tg_elig: Dict[str, bool],
                 cluster) -> None:
        self.mask = mask                        # frozen bool[n_pad]
        #: [(reason, node_class, count)] exactly as the Python
        #: builder's metrics.filter_node calls would have tallied
        self.filter_counts = filter_counts
        #: computed class -> eligible, in the same conditions the
        #: Python builder populated EvalEligibility (empty when the
        #: program escaped — escaped evals never memoize)
        self.class_job_elig = class_job_elig
        self.class_tg_elig = class_tg_elig
        self.cluster_n = cluster.n_real
        #: set (pinning the build) only for usage-less identity keys,
        #: where a recycled id() must not alias a dead cluster; for
        #: (uid, structure_version) keys the key itself defines the
        #: node structure and pinning would hold whole builds hostage
        self.cluster_ref = None


class MaskProgramCache:
    def __init__(self, max_programs: int = 256,
                 max_masks: int = 512) -> None:
        self._lock = threading.Lock()
        self._programs: "OrderedDict[tuple, Optional[MaskProgram]]" = \
            OrderedDict()
        self._masks: "OrderedDict[tuple, MaskEntry]" = OrderedDict()
        #: (uid, sv, digest) -> canonical frozen mask (content dedup)
        self._canonical: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        self.max_programs = max_programs
        self.max_masks = max_masks
        self.reset_stats()

    # --- stats ----------------------------------------------------------

    def reset_stats(self) -> None:
        with self._lock:
            self.hits = 0            # evaluated-mask cache hits
            self.misses = 0          # evaluations performed
            self.program_compiles = 0
            self.fallbacks = 0       # per-eval Python-builder fallbacks
            self.dynamic_applies = 0  # epilogue copies (distinct/csi/..)

    def note_fallback(self) -> None:
        with self._lock:
            self.fallbacks += 1

    def note_dynamic(self) -> None:
        with self._lock:
            self.dynamic_applies += 1

    def hit_ratio(self) -> float:
        with self._lock:
            total = self.hits + self.misses + self.fallbacks
            return self.hits / total if total else 0.0

    def snapshot(self) -> Dict:
        with self._lock:
            total = self.hits + self.misses + self.fallbacks
            return {
                "hits": self.hits,
                "misses": self.misses,
                "fallbacks": self.fallbacks,
                "program_compiles": self.program_compiles,
                "dynamic_applies": self.dynamic_applies,
                "hit_ratio": round(self.hits / total, 4) if total else 0.0,
                "cached_programs": len(self._programs),
                "cached_masks": len(self._masks),
            }

    # --- programs -------------------------------------------------------

    def program_for(self, job, tg) -> Optional[MaskProgram]:
        """Compiled program for the (job, tg) tree, or None when the
        tree is uncompilable (the caller falls back to the Python
        builder per eval). The signature is computed first so equal
        trees share one compile — and one None, so fallback trees
        don't recompile either."""
        from nomad_tpu.feasibility.compiler import program_signature

        sig = program_signature(job, tg)
        with self._lock:
            if sig in self._programs:
                self._programs.move_to_end(sig)
                return self._programs[sig]
        program = compile_program(job, tg)
        with self._lock:
            if sig not in self._programs:
                self._programs[sig] = program
                self.program_compiles += 1
                while len(self._programs) > self.max_programs:
                    self._programs.popitem(last=False)
            return self._programs[sig]

    # --- evaluated masks ------------------------------------------------

    def _mask_key(self, program: MaskProgram, cluster, usage) -> Tuple:
        if usage is not None and getattr(usage, "uid", ""):
            return (usage.uid, usage.structure_version, program.signature)
        return ("cluster-id", id(cluster), program.signature)

    def entry_for(self, program: MaskProgram, cluster, snapshot,
                  usage=None) -> MaskEntry:
        """Evaluated static mask for (program, node structure); cached.
        Misses evaluate OUTSIDE the lock (the regex/semver work), with
        a double-check so racing evals share the winner's entry."""
        key = self._mask_key(program, cluster, usage)
        identity_key = key[0] == "cluster-id"

        def valid(ent: Optional[MaskEntry]) -> bool:
            if ent is None:
                return False
            if identity_key and ent.cluster_ref is not cluster:
                return False
            return (ent.cluster_n == cluster.n_real
                    and len(ent.mask) == cluster.n_pad)

        with self._lock:
            got = self._masks.get(key)
            if valid(got):
                self._masks.move_to_end(key)
                self.hits += 1
                return got
        from nomad_tpu.feasibility.runtime import evaluate_program

        entry = evaluate_program(program, cluster, snapshot, usage)
        if identity_key:
            entry.cluster_ref = cluster
        with self._lock:
            got = self._masks.get(key)
            if valid(got):
                self.hits += 1
                return got
            entry.mask = self._dedupe_locked(key, entry.mask)
            self._masks[key] = entry
            self.misses += 1
            while len(self._masks) > self.max_masks:
                self._masks.popitem(last=False)
            return entry

    # graft: frozen
    def _dedupe_locked(self, key: Tuple,
                       mask: np.ndarray) -> np.ndarray:
        """Canonicalize equal masks of one node structure onto one
        frozen array: identity is the wave launcher's sharing contract,
        so equal-but-distinct masks would stack the whole job-sharing
        group for nothing."""
        digest = (key[0], key[1], hash(mask.tobytes()))
        canon = self._canonical.get(digest)
        if canon is not None and np.array_equal(canon, mask):
            self._canonical.move_to_end(digest)
            return canon
        mask.setflags(write=False)
        self._canonical[digest] = mask
        while len(self._canonical) > self.max_masks:
            self._canonical.popitem(last=False)
        return mask


#: process-wide cache (the stack's compiled-mask path; exported via
#: telemetry/exporter.py and reset with telemetry.reset())
default_mask_cache = MaskProgramCache()
