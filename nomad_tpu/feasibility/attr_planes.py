"""Interned node-attribute planes: the compiler's vectorized columns.

Constraint targets (``${attr.kernel.name}``, ``${meta.rack}``,
``${node.datacenter}``, ...) resolve to one string per node. Evaluating
a predicate per node re-resolves and re-parses that string every time —
regex/semver predicates in particular pay their full cost per node per
eval in the Python builder. This module flattens each target ONCE per
node structure into an interned column:

- ``codes[i]``: i32 index of node i's value in the column's value
  table, -1 when the target does not resolve on the node;
- ``values``: the (small) table of distinct strings.

A predicate then runs once per DISTINCT value (a lookup table over the
vocabulary) and broadcasts to nodes with one numpy gather — the regex
compiles once and matches |vocabulary| times instead of |nodes| times.

Column sets are keyed by the usage index's ``(uid, structure_version)``
— the same generation key the incremental ClusterTensors cache and the
device-resident cluster state use — and advance across structure forks
by re-interning ONLY the rows the ``UsagePlanes.node_events`` change
log proves dirty, exactly like ``ClusterTensors.rebuild_delta``. An
unprovable log (poisoned, trimmed) or majority churn falls back to a
fresh build, which is always correct.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from nomad_tpu.structs.constraints import resolve_target

__all__ = ["AttrPlane", "AttrPlaneSet", "AttrPlaneCache",
           "default_attr_plane_cache"]


class AttrPlane:
    """One interned target column over the cluster's node rows."""

    __slots__ = ("target", "codes", "values", "index")

    def __init__(self, target: str, codes: np.ndarray,
                 values: List[str], index: Dict[str, int]) -> None:
        self.target = target
        self.codes = codes          # i32[n_real], -1 = unresolved
        self.values = values        # code -> string
        self.index = index          # string -> code

    def lut_mask(self, predicate) -> np.ndarray:
        """bool[n_real] of ``predicate(value, found)`` per node, with
        the predicate invoked once per distinct value (and once for
        the unresolved case)."""
        lut = np.empty(len(self.values) + 1, bool)
        lut[0] = bool(predicate(None, False))           # code -1
        for code, val in enumerate(self.values):
            lut[code + 1] = bool(predicate(val, True))
        return lut[self.codes + 1]


class AttrPlaneSet:
    """Lazily-built columns for one cluster build (one node structure)."""

    def __init__(self, cluster) -> None:
        self.cluster = cluster
        self._lock = threading.Lock()
        self._planes: Dict[str, AttrPlane] = {}

    def column(self, target: str) -> AttrPlane:
        got = self._planes.get(target)
        if got is not None:
            return got
        with self._lock:
            got = self._planes.get(target)
            if got is not None:
                return got
            got = self._build(target)
            self._planes[target] = got
            return got

    def _node(self, i: int):
        c = self.cluster
        return c.nodes_by_id.get(c.node_ids[i])

    def _build(self, target: str) -> AttrPlane:
        c = self.cluster
        codes = np.full(c.n_real, -1, np.int32)
        values: List[str] = []
        index: Dict[str, int] = {}
        for i in range(c.n_real):
            node = self._node(i)
            if node is None:
                continue
            val, ok = resolve_target(target, node)
            if not ok or val is None:
                continue
            code = index.get(val)
            if code is None:
                code = len(values)
                index[val] = code
                values.append(val)
            codes[i] = code
        codes.setflags(write=False)
        return AttrPlane(target, codes, values, index)

    def fork(self, cluster, changed_ids) -> "AttrPlaneSet":
        """A new set for ``cluster`` (a later structure_version),
        re-interning only rows whose node ids are in ``changed_ids``
        (plus rows whose position moved); every other code is gathered
        from this set."""
        out = AttrPlaneSet(cluster)
        base_index = self.cluster.index
        n = cluster.n_real
        stale: List[int] = []
        perm = np.zeros(n, np.int64)
        for j, nid in enumerate(cluster.node_ids):
            i = base_index.get(nid, -1)
            if i < 0 or nid in changed_ids:
                stale.append(j)
            else:
                perm[j] = i
        with self._lock:
            planes = dict(self._planes)
        for target, base in planes.items():
            codes = base.codes[perm].copy() if n else np.zeros(0, np.int32)
            values = list(base.values)
            index = dict(base.index)
            for j in stale:
                codes[j] = -1
                node = out._node(j)
                if node is None:
                    continue
                val, ok = resolve_target(target, node)
                if not ok or val is None:
                    continue
                code = index.get(val)
                if code is None:
                    code = len(values)
                    index[val] = code
                    values.append(val)
                codes[j] = code
            codes.setflags(write=False)
            out._planes[target] = AttrPlane(target, codes, values, index)
        return out


class AttrPlaneCache:
    """(uid, structure_version) -> AttrPlaneSet, LRU-bounded, advanced
    across structure forks by the node-events dirty set."""

    def __init__(self, max_entries: int = 8) -> None:
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, AttrPlaneSet]" = OrderedDict()
        #: uid -> newest cached structure_version (the fork base)
        self._latest: Dict[str, Optional[int]] = {}
        self.max_entries = max_entries
        self.forks = 0
        self.builds = 0

    def get(self, cluster, usage=None) -> AttrPlaneSet:
        key = self._key(cluster, usage)
        with self._lock:
            got = self._entries.get(key)
            if got is not None and got.cluster is cluster:
                self._entries.move_to_end(key)
                return got
            base = None
            if usage is not None and getattr(usage, "uid", ""):
                base_sv = self._latest.get(usage.uid)
                if base_sv is not None and base_sv < usage.structure_version:
                    base = self._entries.get((usage.uid, base_sv))
        built = None
        if base is not None:
            from nomad_tpu.tensors.schema import IncrementalClusterCache

            changed = IncrementalClusterCache._changed_since(
                getattr(usage, "node_events", ()), base_sv)
            if changed is not None and len(changed) <= max(
                    cluster.n_real // 2, 8):
                built = base.fork(cluster, changed)
                self.forks += 1
        if built is None:
            built = AttrPlaneSet(cluster)
            self.builds += 1
        with self._lock:
            got = self._entries.get(key)
            if got is not None and got.cluster is cluster:
                return got
            self._entries[key] = built
            if usage is not None and getattr(usage, "uid", ""):
                if usage.structure_version >= (
                        self._latest.get(usage.uid) or -1):
                    self._latest[usage.uid] = usage.structure_version
            while len(self._entries) > self.max_entries:
                old_key, _ = self._entries.popitem(last=False)
                if self._latest.get(old_key[0]) == old_key[1]:
                    self._latest.pop(old_key[0], None)
        return built

    @staticmethod
    def _key(cluster, usage) -> Tuple:
        if usage is not None and getattr(usage, "uid", ""):
            return (usage.uid, usage.structure_version)
        # usage-less states (bare test harnesses): cluster identity
        return ("cluster-id", id(cluster))


#: process-wide column cache (the mask-program runtime's vocabulary)
default_attr_plane_cache = AttrPlaneCache()
