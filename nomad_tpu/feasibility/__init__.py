"""The feasibility compiler: constraint trees -> cached mask programs.

The one scheduling stage that never left Python — the per-eval
``FeasibilityBuilder.base_mask`` walk over constraints, drivers,
volumes and distinct rules — compiled once per distinct constraint
tree and evaluated once per node structure:

- ``attr_planes``: interned node-attribute vocabulary (per-node code
  planes), advanced incrementally from the state store's node-change
  logs;
- ``compiler``: (job, tg) constraint trees -> ``MaskProgram`` IR,
  keyed by a structural signature so equal specs share one program;
- ``cache``: program + evaluated-mask LRUs keyed by the usage index's
  (uid, structure_version) generations, with content dedup so equal
  masks share one frozen array (the wave-sharing identity contract);
- ``runtime``: the evaluation engine (bit-identical to the Python
  builder by reusing its helpers) and the per-eval epilogue that
  replays metrics/eligibility and applies dynamic rules.

See docs/PERF.md (feasibility compiler) and docs/PARITY.md.
"""

from nomad_tpu.feasibility.attr_planes import (  # noqa: F401
    AttrPlaneCache,
    AttrPlaneSet,
    default_attr_plane_cache,
)
from nomad_tpu.feasibility.cache import (  # noqa: F401
    MaskEntry,
    MaskProgramCache,
    default_mask_cache,
)
from nomad_tpu.feasibility.compiler import (  # noqa: F401
    MaskProgram,
    compile_program,
    program_signature,
)
from nomad_tpu.feasibility.runtime import (  # noqa: F401
    apply_program,
    evaluate_program,
)

__all__ = [
    "AttrPlaneCache", "AttrPlaneSet", "default_attr_plane_cache",
    "MaskEntry", "MaskProgramCache", "default_mask_cache",
    "MaskProgram", "compile_program", "program_signature",
    "apply_program", "evaluate_program",
]
