"""Compile a (job, task group) constraint tree into a mask program.

The per-eval Python ``FeasibilityBuilder.base_mask`` re-walks every
constraint, driver, volume and distinct rule per evaluation. But almost
none of that depends on the evaluation: for a fixed node structure the
result is a pure function of the constraint tree. This module compiles
the tree ONCE per distinct tree (keyed by a structural signature, so
two jobs with equal specs share one program) into a ``MaskProgram`` —
an ordered list of phase ops mirroring the Python builder's phases
exactly:

- ``dc``: ready/datacenter/node-pool mask (readyNodesInDCs, incl. DC
  glob patterns);
- ``class``: job- then tg-level constraint + driver + device-existence
  checks evaluated once per computed node class on a representative
  (the EvalEligibility memoization, feasible.go:1050), applied to the
  class's rows vectorized;
- ``escaped``: constraints on unique properties escape the class cache
  — the whole merged set is evaluated per node, vectorized over the
  interned attribute vocabulary (attr_planes.py) so regex/semver parse
  once per DISTINCT value;
- ``volumes``: host-volume presence per node.

Proposed-alloc-dependent rules (distinct_hosts/distinct_property) and
snapshot-claim-dependent CSI checks cannot be compiled into the cached
mask; the program carries them as DYNAMIC flags the per-eval epilogue
(runtime.apply_program) applies on top.

``compile_program`` returns None for trees the compiler cannot express
(today: escaped sets whose right-hand targets are themselves node
interpolations — the value-pair case the vocabulary LUT cannot
vectorize). The caller falls back to the Python builder, and the
fallback is property-tested bit-identical (tests/
test_feasibility_compiler.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from nomad_tpu.scheduler.feasible import (
    merged_tg_constraints,
    required_drivers,
)
from nomad_tpu.structs import consts

__all__ = ["MaskProgram", "compile_program", "program_signature"]

#: operands the vectorized escaped path evaluates through the interned
#: vocabulary LUT (everything check_constraint handles; distinct_* pass
#: through it as always-true exactly like checkConstraint does)
_DISTINCT_OPERANDS = (consts.CONSTRAINT_DISTINCT_HOSTS,
                      consts.CONSTRAINT_DISTINCT_PROPERTY)


def _con_key(c) -> Tuple[str, str, str]:
    return (c.ltarget, c.operand, c.rtarget)


def _vol_key(req) -> Tuple:
    return (req.type, req.source, bool(req.read_only))


def _dev_key(tg) -> Tuple:
    out = []
    for task in tg.tasks:
        for d in task.resources.devices:
            out.append((d.name, int(d.count),
                        tuple(_con_key(c) for c in
                              getattr(d, "constraints", ()) or ()),
                        tuple((a.ltarget, a.operand, a.rtarget,
                               int(a.weight)) for a in
                              getattr(d, "affinities", ()) or ())))
    return tuple(out)


def program_signature(job, tg) -> Tuple:
    """Structural fingerprint of everything the cached mask depends
    on. Jobs with equal trees share one compiled program AND one
    evaluated mask per node structure — which is what pushes the
    steady-burst cache hit ratio toward 1.0 under homogeneous
    traffic."""
    return (
        tuple(job.datacenters),
        job.node_pool,
        tuple(_con_key(c) for c in job.constraints),
        tuple(_con_key(c) for c in merged_tg_constraints(tg)),
        tuple(required_drivers(tg)),
        tuple(sorted(_vol_key(r) for r in tg.volumes.values())),
        _dev_key(tg),
    )


@dataclass
class MaskProgram:
    """Compiled constraint tree for one (job, tg) shape."""

    signature: Tuple
    datacenters: Tuple[str, ...]
    node_pool: str
    job_constraints: Tuple = ()
    tg_constraints: Tuple = ()          # tg + task constraints, merged
    drivers: Tuple[str, ...] = ()
    #: a task group carrying device asks (existence checked per class
    #: rep / per node, like DeviceChecker.hasDevices)
    has_device_asks: bool = False
    #: constraints escape the class cache (unique-property targets):
    #: the merged set evaluates per node over the vocabulary planes
    escaped: bool = False
    host_volumes: Tuple = ()            # host-volume reqs (ragged objs)
    #: DYNAMIC epilogue flags — per-eval state the cached mask cannot
    #: carry
    has_csi_volumes: bool = False
    distinct_hosts_job: bool = False
    distinct_hosts_tg: bool = False
    distinct_property: bool = False
    #: the live tg/job objects the evaluation phases need (ragged
    #: checks reuse the Python helpers verbatim for bit-identity)
    job: object = field(default=None, repr=False)
    tg: object = field(default=None, repr=False)


def _escapes(constraints) -> bool:
    from nomad_tpu.scheduler.context import _constraints_escape

    return _constraints_escape(constraints)


def compile_program(job, tg) -> Optional[MaskProgram]:
    """Compile or refuse (None -> Python-builder fallback)."""
    job_cons = tuple(job.constraints)
    tg_cons = tuple(merged_tg_constraints(tg))
    escaped = _escapes(job_cons) or any(
        _escapes(t.constraints) for t in [tg] + list(tg.tasks))
    if escaped:
        # the vectorized escaped path resolves the LEFT target through
        # the vocabulary; a right target that is itself a node
        # interpolation is a value-pair predicate the LUT cannot
        # express — fall back to the per-node Python builder
        for c in list(job_cons) + list(tg_cons):
            if c.operand in _DISTINCT_OPERANDS:
                continue
            if c.rtarget.startswith("${"):
                return None
    host_vols = tuple(r for r in tg.volumes.values() if r.type == "host")
    has_csi = any(r.type == "csi" for r in tg.volumes.values())
    has_devs = any(t.resources.devices for t in tg.tasks)
    return MaskProgram(
        signature=program_signature(job, tg),
        datacenters=tuple(job.datacenters),
        node_pool=job.node_pool,
        job_constraints=job_cons,
        tg_constraints=tg_cons,
        drivers=tuple(required_drivers(tg)),
        has_device_asks=has_devs,
        escaped=escaped,
        host_volumes=host_vols,
        has_csi_volumes=has_csi,
        distinct_hosts_job=any(
            c.operand == consts.CONSTRAINT_DISTINCT_HOSTS
            for c in job.constraints),
        distinct_hosts_tg=any(
            c.operand == consts.CONSTRAINT_DISTINCT_HOSTS
            for c in tg.constraints),
        distinct_property=any(
            c.operand == consts.CONSTRAINT_DISTINCT_PROPERTY
            for c in list(job.constraints) + list(tg.constraints)),
        job=job,
        tg=tg,
    )
