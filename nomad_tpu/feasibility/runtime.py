"""Mask-program evaluation + the per-eval epilogue.

``evaluate_program`` runs a compiled ``MaskProgram`` against one node
structure and produces the cached ``MaskEntry``: the static feasibility
plane, the per-reason filter tallies, and the per-class eligibility the
Python builder would have produced eval by eval. Phase order and the
predicate implementations are the Python builder's own helpers
(``eligible_in_dcs``, ``node_meets_constraints``, ``driver_ok``,
``devices_exist``, ``host_volumes_ok``) invoked per class
representative or per distinct interned value — bit-identity with
``FeasibilityBuilder.base_mask`` is by construction, and property-
tested in tests/test_feasibility_compiler.py.

``apply_program`` is the per-eval hot path: a cache lookup, a metrics/
eligibility tally replay, and — only when the eval actually needs them
— the dynamic epilogue (exclude rows, CSI claims, distinct_hosts/
distinct_property). An eval with no dynamic state returns the cached
FROZEN mask itself: every member of a wave then carries the same array
by identity, the wave launcher ships it unbatched (one plane per wave,
parallel/coalesce job-sharing group), and the device-resident state's
frozen registry uploads it once per (structure, signature) ever — the
wave's base masks are produced by one broadcast on device instead of B
host builds.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from nomad_tpu.feasibility.attr_planes import default_attr_plane_cache
from nomad_tpu.feasibility.cache import MaskEntry, default_mask_cache
from nomad_tpu.feasibility.compiler import MaskProgram
from nomad_tpu.scheduler.feasible import (
    FILTER_CONSTRAINT_CSI_PLUGINS,
    FILTER_CONSTRAINT_HOST_VOLUMES,
    csi_ok,
    devices_exist,
    driver_ok,
    eligible_in_dcs,
    host_volumes_ok,
)
from nomad_tpu.structs.constraints import check_constraint

__all__ = ["evaluate_program", "apply_program"]


def _nodes_by_id(cluster, snapshot):
    return cluster.nodes_by_id or {
        nid: snapshot.node_by_id(nid) for nid in cluster.node_ids
    }


def _escaped_constraint_mask(program: MaskProgram, cluster,
                             usage) -> np.ndarray:
    """Per-node merged-constraint mask over the interned vocabulary:
    each constraint's predicate runs once per DISTINCT left value
    (regex compiles once, matches |vocab| times) through the exact
    ``check_constraint`` operand evaluation."""
    planes = default_attr_plane_cache.get(cluster, usage)
    mask = np.ones(cluster.n_real, bool)
    for con in list(program.job_constraints) + list(program.tg_constraints):
        col = planes.column(con.ltarget)
        op, rt = con.operand, con.rtarget
        mask &= col.lut_mask(
            lambda val, found, op=op, rt=rt:
            check_constraint(op, val, rt, found, True))
        if not mask.any():
            break
    return mask


def evaluate_program(program: MaskProgram, cluster, snapshot,
                     usage=None) -> MaskEntry:
    """One full static evaluation (the cache-miss path)."""
    from nomad_tpu.telemetry.trace import tracer

    with tracer.span("feas.evaluate"):
        return _evaluate(program, cluster, snapshot, usage)


def _evaluate(program: MaskProgram, cluster, snapshot,
              usage=None) -> MaskEntry:
    c = cluster
    mask = eligible_in_dcs(c, list(program.datacenters),
                           program.node_pool)
    filter_counts = []
    class_job = {}
    class_tg = {}
    nodes_by_id = _nodes_by_id(c, snapshot)
    tg = program.tg

    def tally(rows, reason) -> None:
        # replicate metrics.filter_node per dropped node, aggregated
        # by node_class (the dict key the AllocMetric tallies use)
        by_class = {}
        for i in rows:
            node = nodes_by_id.get(c.node_ids[i])
            cls = node.node_class if node is not None else ""
            by_class[cls] = by_class.get(cls, 0) + 1
        for cls, n in by_class.items():
            filter_counts.append((reason, cls, n))

    if not program.escaped:
        # class-memoized phase: representative-based, exactly the
        # Python builder's walk (one rep per computed class)
        for cls, rows in c.class_rows().items():
            live = [i for i in rows if i < c.n_real and mask[i]]
            if not live:
                continue
            rep = nodes_by_id.get(c.node_ids[live[0]])
            if rep is None:
                for i in live:
                    mask[i] = False
                continue
            ok = _job_ok(program, rep)
            class_job[cls] = ok
            if not ok:
                for i in live:
                    mask[i] = False
                tally(live, "job constraints")
                continue
            ok_tg = _tg_ok(program, rep)
            class_tg[cls] = ok_tg
            if not ok_tg:
                for i in live:
                    mask[i] = False
                tally(live, "task group constraints")
    else:
        # escaped phase: every check per node. Constraints run
        # vectorized over the vocabulary; drivers/devices per node
        # (they read ragged node state the vocabulary doesn't carry).
        con_mask = _escaped_constraint_mask(program, c, usage)
        dropped = []
        for i in range(c.n_real):
            if not mask[i]:
                continue
            node = nodes_by_id.get(c.node_ids[i])
            if node is None or not con_mask[i] \
                    or not driver_ok(node, list(program.drivers)) \
                    or (program.has_device_asks
                        and not devices_exist(node, tg)):
                mask[i] = False
                if node is not None:
                    dropped.append(i)
        tally(dropped, "constraints")

    # per-node ragged volume phase (host volumes only: CSI claims are
    # snapshot state, applied by the dynamic epilogue)
    if program.host_volumes:
        dropped = []
        for i in range(c.n_real):
            if not mask[i]:
                continue
            node = nodes_by_id.get(c.node_ids[i])
            if node is None:
                mask[i] = False
                continue
            if not host_volumes_ok(node, tg):
                mask[i] = False
                dropped.append(i)
        tally(dropped, FILTER_CONSTRAINT_HOST_VOLUMES)

    return MaskEntry(mask, filter_counts, class_job, class_tg, c)


def _job_ok(program: MaskProgram, rep) -> bool:
    from nomad_tpu.structs.constraints import node_meets_constraints

    return node_meets_constraints(rep, list(program.job_constraints))


def _tg_ok(program: MaskProgram, rep) -> bool:
    from nomad_tpu.structs.constraints import node_meets_constraints

    return (node_meets_constraints(rep, list(program.tg_constraints))
            and driver_ok(rep, list(program.drivers))
            and (not program.has_device_asks
                 or devices_exist(rep, program.tg)))


def apply_program(program: MaskProgram, cluster, snapshot, ctx,
                  job, tg, job_allocs_by_node, exclude,
                  feas_builder) -> np.ndarray:
    """The per-eval entry: cached static mask + metrics/eligibility
    replay + dynamic epilogue. Returns the FROZEN cached array itself
    when the eval has no dynamic state (identity is the wave-sharing
    and device-residency contract); any dynamic state copies first.

    ``feas_builder`` supplies the distinct-constraint epilogue (the
    proposed-alloc-dependent masks stay the Python implementation —
    they are per-eval by nature)."""
    cache = default_mask_cache
    usage = getattr(snapshot, "usage", None)
    entry = cache.entry_for(program, cluster, snapshot, usage)

    # ALL fallible work (the dynamic epilogue) runs before anything
    # mutates ctx state: an exception here falls back to the Python
    # builder in stack._base_mask, and a half-replayed tally would
    # then double-count the same filtered nodes in the eval's
    # AllocMetric. CSI drops are staged for the same reason.
    mask = entry.mask
    dynamic = (exclude.any() or program.has_csi_volumes
               or program.distinct_hosts_job or program.distinct_hosts_tg
               or program.distinct_property)
    csi_dropped = []
    if dynamic:
        mask = mask.copy()
        mask &= ~exclude
        if program.has_csi_volumes:
            c = cluster
            nodes_by_id = _nodes_by_id(c, snapshot)
            for i in range(c.n_real):
                if not mask[i]:
                    continue
                node = nodes_by_id.get(c.node_ids[i])
                if node is None:
                    mask[i] = False
                    continue
                if not csi_ok(node, tg, snapshot, job.namespace):
                    mask[i] = False
                    csi_dropped.append(node)
        if program.distinct_hosts_job or program.distinct_hosts_tg \
                or program.distinct_property:
            feas_builder._apply_distinct(
                mask, job, tg, job_allocs_by_node,
                _nodes_by_id(cluster, snapshot))
        cache.note_dynamic()

    # metrics + eligibility replay (what the per-eval builder tallied)
    metrics = ctx.metrics()
    for reason, cls, n in entry.filter_counts:
        metrics.nodes_filtered += n
        if cls:
            metrics.class_filtered[cls] = \
                metrics.class_filtered.get(cls, 0) + n
        if reason:
            metrics.constraint_filtered[reason] = \
                metrics.constraint_filtered.get(reason, 0) + n
    if not program.escaped:
        elig = ctx.eligibility
        for cls, ok in entry.class_job_elig.items():
            elig.set_job_eligibility(ok, cls)
        for cls, ok in entry.class_tg_elig.items():
            elig.set_tg_eligibility(ok, tg.name, cls)
    for node in csi_dropped:
        metrics.filter_node(node, FILTER_CONSTRAINT_CSI_PLUGINS)
    return mask
