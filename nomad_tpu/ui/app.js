"use strict";

/* ---------- plumbing ---------- */

const $view = document.getElementById("view");
let refreshTimer = null;

function token() { return localStorage.getItem("nomad_token") || ""; }
function namespaceQS() {
  const ns = localStorage.getItem("nomad_namespace") || "";
  return ns ? `namespace=${encodeURIComponent(ns)}` : "";
}

async function api(path, opts = {}) {
  const headers = Object.assign({}, opts.headers);
  if (token()) headers["X-Nomad-Token"] = token();
  const sep = path.includes("?") ? "&" : "?";
  const ns = namespaceQS();
  const url = ns && path.startsWith("/v1/") && !path.includes("namespace=")
    ? path + sep + ns : path;
  const resp = await fetch(url, Object.assign({}, opts, { headers }));
  if (!resp.ok) {
    let msg = `HTTP ${resp.status}`;
    try { msg = (await resp.json()).error || msg; } catch (e) { /* raw */ }
    throw new Error(msg);
  }
  const text = await resp.text();
  return text ? JSON.parse(text) : null;
}
const get = (p) => api(p);
const post = (p, body) => api(p, { method: "POST", body: JSON.stringify(body || {}) });
const del = (p) => api(p, { method: "DELETE" });

function esc(s) {
  return String(s ?? "").replace(/[&<>"']/g,
    (c) => ({ "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;" }[c]));
}
/* for values inside inline-handler JS string literals: percent-encode,
   INCLUDING the characters encodeURIComponent leaves alone that could
   terminate a single-quoted literal or call a function — ' ( ) ! * —
   (the HTML parser entity-decodes attribute values BEFORE the JS
   engine sees them, so esc() alone is not enough there); handlers
   decode via arg(). A CSI volume id is attacker-controlled free text,
   so this is a stored-XSS boundary, not cosmetics. */
function jsArg(s) {
  return encodeURIComponent(String(s ?? "")).replace(/[!'()*]/g,
    (c) => "%" + c.charCodeAt(0).toString(16).padStart(2, "0"));
}
/* fs path -> hash-route segment: encode everything except the
   directory separators the router splits on */
function hashPath(p) { return encodeURIComponent(p).replace(/%2F/g, "/"); }
function arg(s) { return decodeURIComponent(s); }
function shortId(id) { return esc(String(id || "").slice(0, 8)); }
function fmtTime(ns) {
  if (!ns) return "—";
  return new Date(ns / 1e6).toLocaleString();
}
function fmtMB(mb) { return mb >= 1024 ? (mb / 1024).toFixed(1) + " GiB" : mb + " MiB"; }

/* status → {class, label}; icon dot + text so state never rides color alone */
const STATUS = {
  running: "good", ready: "good", complete: "good", successful: "good",
  alive: "good", healthy: "good", eligible: "good",
  pending: "warning", initializing: "warning", starting: "warning",
  queued: "warning", paused: "warning", ineligible: "warning",
  blocked: "serious", draining: "serious", unknown: "serious", lost: "serious",
  cancelled: "serious", canceled: "serious",
  failed: "critical", down: "critical", dead: "neutral", stopped: "neutral",
  "left": "neutral",
};
function badge(status) {
  const cls = STATUS[String(status || "").toLowerCase()] || "neutral";
  return `<span class="badge ${cls}"><span class="dot"></span>${esc(status || "—")}</span>`;
}
function render(html) { $view.innerHTML = html; }
function renderError(e) {
  $view.innerHTML += `<div class="error-banner">request failed: ${esc(e.message || e)}</div>`;
}

/* every list view re-fetches on an interval; navigation cancels it */
function autoRefresh(fn, ms = 4000) {
  clearInterval(refreshTimer);
  refreshTimer = setInterval(() => fn().catch(() => {}), ms);
}

/* ---------- view contract ----------
The machine-checked route -> endpoint -> field manifest. The Python
harness (ui/harness.py) extracts this JSON and (a) walks every
declared field path against the REAL seeded API — a field the API
does not return fails the suite — and (b) cross-checks that every
PascalCase member access in each view function below is declared
here (API fields are PascalCase, JS locals are camelCase), so a view
cannot silently read an undeclared — and therefore unwalked — field.
Path DSL: "." descends; leading "[]" = response is a list (check the
first element); "KEY[]" = list-valued field; "*" = every dict value;
a "?" prefix marks a field the API may legitimately omit.
__VIEW_CONTRACT_START__
{
  "viewOverview": {"endpoints": {"jobs": "/v1/jobs", "nodes": "/v1/nodes", "allocs": "/v1/allocations", "evals": "/v1/evaluations", "leader": "/v1/status/leader"},
    "uses": ["evalTable"],
    "walk": {"jobs": ["[].Status"], "nodes": ["[].Status"], "allocs": ["[].ClientStatus"], "evals": ["@evalTable"]}},
  "viewJobs": {"endpoints": {"jobs": "/v1/jobs"},
    "walk": {"jobs": ["[].ID", "[].Name", "[].Priority", "[].Status", "[].Stop", "[].Type", "[].Version"]}},
  "viewJobDetail": {"endpoints": {"job": "/v1/job/{job}", "summary": "/v1/job/{job}/summary", "allocs": "/v1/job/{job}/allocations", "evals": "/v1/job/{job}/evaluations", "deploys": "/v1/job/{job}/deployments", "versions": "/v1/job/{job}/versions"},
    "uses": ["allocTable", "evalTable", "deployTable", "versionsTable"],
    "walk": {"job": ["ID", "Name", "Type", "Priority", "Status", "Stop", "Version", "Datacenters", "TaskGroups[].Name", "TaskGroups[].Count"],
             "summary": ["Summary.*.Queued", "Summary.*.Starting", "Summary.*.Running", "Summary.*.Failed", "Summary.*.Complete", "Summary.*.Lost"],
             "allocs": ["@allocTable"], "evals": ["@evalTable"], "deploys": ["@deployTable"],
             "versions": ["Versions[].Version", "Versions[].Stable", "Versions[].Stop", "Versions[].Status"]}},
  "viewClients": {"endpoints": {"nodes": "/v1/nodes"},
    "walk": {"nodes": ["[].ID", "[].Name", "[].Datacenter", "?[].NodeClass", "?[].NodePool", "[].Status", "[].SchedulingEligibility", "[].Drain"]}},
  "viewClientDetail": {"endpoints": {"node": "/v1/node/{node}", "allocs": "/v1/node/{node}/allocations"},
    "uses": ["allocTable"],
    "walk": {"node": ["ID", "Name", "Datacenter", "?NodePool", "Status", "SchedulingEligibility", "Drain", "NodeResources.CPU.CPUShares", "NodeResources.Memory.MemoryMB", "NodeResources.Disk.DiskMB", "Attributes", "Drivers.*.Detected", "Drivers.*.Healthy"],
             "allocs": ["@allocTable"]}},
  "viewAllocs": {"endpoints": {"allocs": "/v1/allocations"},
    "walk": {"allocs": ["[].ID", "[].JobID", "[].TaskGroup", "[].NodeID", "?[].NodeName", "[].DesiredStatus", "[].ClientStatus", "[].ModifyTime"]}},
  "viewAllocDetail": {"endpoints": {"alloc": "/v1/allocation/{alloc}"},
    "uses": ["placementMetrics"],
    "walk": {"alloc": ["ID", "Name", "JobID", "NodeID", "?NodeName", "ClientStatus", "DesiredStatus", "TaskGroup", "?EvalID", "?DeploymentID", "?CreateTime", "?CreateTimeNs", "TaskStates.*.State", "TaskStates.*.Events[].Type", "?TaskStates.*.Events[].Time", "?TaskStates.*.Events[].TimeNs", "?TaskStates.*.Events[].DisplayMessage", "?TaskStates.*.Events[].Message", "Metrics.NodesEvaluated", "Metrics.NodesFiltered", "Metrics.NodesExhausted", "?Metrics.ScoreMeta"]}},
  "viewEvals": {"endpoints": {"evals": "/v1/evaluations"}, "uses": ["evalTable"],
    "walk": {"evals": ["@evalTable"]}},
  "viewDeployments": {"endpoints": {"deploys": "/v1/deployments"}, "uses": ["deployTable"],
    "walk": {"deploys": ["@deployTable"]}},
  "viewServices": {"endpoints": {"groups": "/v1/services", "insts": "/v1/service/{service}"},
    "walk": {"groups": ["[].Namespace", "[].Services[].ServiceName", "?[].Services[].Tags"],
             "insts": ["[].ID", "?[].AllocID", "[].NodeID", "?[].Address", "?[].Port"]}},
  "viewVolumes": {"endpoints": {"vols": "/v1/volumes", "plugins": "/v1/plugins"},
    "walk": {"vols": ["[].ID", "?[].Name", "[].PluginID", "[].Schedulable", "?[].AccessMode", "?[].CurrentReaders", "?[].CurrentWriters"],
             "plugins": ["[].ID", "?[].Provider", "?[].ControllersHealthy", "?[].ControllersExpected", "?[].NodesHealthy", "?[].NodesExpected"]}},
  "viewVolumeDetail": {"endpoints": {"vol": "/v1/volume/csi/{volume}"},
    "walk": {"vol": ["ID", "?Name", "?Namespace", "PluginID", "Schedulable", "?AccessMode", "?AttachmentMode", "?CurrentReaders", "?CurrentWriters", "?ReadAllocs[].ID", "?ReadAllocs[].ClientStatus", "?WriteAllocs[].ID", "?WriteAllocs[].ClientStatus"]}},
  "viewPluginDetail": {"endpoints": {"plugin": "/v1/plugin/csi/{plugin}"},
    "walk": {"plugin": ["ID", "?Provider", "?Version", "?ControllersHealthy", "?ControllersExpected", "?NodesHealthy", "?NodesExpected"]}},
  "viewACL": {"endpoints": {"policies": "/v1/acl/policies", "tokens": "/v1/acl/tokens"},
    "walk": {"policies": ["[].Name", "?[].Description"],
             "tokens": ["[].Name", "[].Type", "[].AccessorID", "?[].Policies", "?[].Global"]}},
  "viewACLPolicy": {"endpoints": {"policy": "/v1/acl/policy/{policy}"},
    "walk": {"policy": ["Name", "?Description", "Rules"]}},
  "viewTopology": {"endpoints": {"nodes": "/v1/nodes?resources=true", "allocs": "/v1/allocations?resources=true"},
    "walk": {"nodes": ["[].ID", "[].Name", "[].Datacenter", "[].Status", "[].Drain", "[].NodeResources.CPU", "[].NodeResources.MemoryMB"],
             "allocs": ["[].ClientStatus", "[].NodeID", "[].AllocatedResources.CPU", "[].AllocatedResources.MemoryMB"]}},
  "viewServers": {"endpoints": {"members": "/v1/agent/members", "raft": "/v1/operator/raft/configuration", "health": "/v1/operator/autopilot/health"},
    "walk": {"members": ["ServerRegion", "Members[].Name", "Members[].Addr", "Members[].Status", "?Members[].Tags"],
             "raft": ["Servers[].ID", "Servers[].Address", "Servers[].Leader", "Servers[].Voter"],
             "health": ["Healthy", "FailureTolerance"]}},
  "viewSettings": {"endpoints": {"self": "/v1/agent/self"}, "walk": {"self": []}},
  "viewAllocFs": {"endpoints": {"ls": "/v1/client/fs/ls/{alloc}?path=/"},
    "walk": {"ls": ["[].Name", "[].IsDir", "[].Size", "[].ModTime"]}},
  "viewAllocFile": {"endpoints": {"stat": "/v1/client/fs/stat/{alloc}?path={file}", "read": "/v1/client/fs/readat/{alloc}?path={file}&offset=0&limit=64"},
    "walk": {"stat": ["Size", "Name", "IsDir", "ModTime"], "read": ["Data"]}},
  "viewAllocLogs": {"endpoints": {"logs": "/v1/client/fs/logs/{alloc}?task={task}&type=stdout"},
    "walk": {"logs": ["Data"]}},
  "helpers": {
    "allocTable": ["[].ID", "[].TaskGroup", "[].NodeID", "?[].NodeName", "[].DesiredStatus", "[].ClientStatus", "?[].CreateTime", "?[].CreateTimeNs"],
    "evalTable": ["[].ID", "[].JobID", "[].Type", "[].TriggeredBy", "[].Status", "?[].StatusDescription"],
    "deployTable": ["[].ID", "[].JobID", "[].Status", "?[].StatusDescription"],
    "versionsTable": ["[].Version", "[].Stable", "[].Stop", "[].Status"],
    "placementMetrics": ["NodesEvaluated", "NodesFiltered", "NodesExhausted", "?ScoreMeta"]
  }
}
__VIEW_CONTRACT_END__ */

/* ---------- views ---------- */

async function viewOverview() {
  const [jobs, nodes, allocs, evals, leader] = await Promise.all([
    get("/v1/jobs"), get("/v1/nodes"), get("/v1/allocations"),
    get("/v1/evaluations"), get("/v1/status/leader").catch(() => "n/a"),
  ]);
  const count = (xs, f) => xs.filter(f).length;
  render(`
    <h1>Cluster overview</h1>
    <p class="sub">leader: <code>${esc(leader)}</code></p>
    <div class="tiles">
      <div class="tile"><div class="v">${jobs.length}</div><div class="k">jobs</div></div>
      <div class="tile"><div class="v">${count(jobs, j => j.Status === "running")}</div><div class="k">jobs running</div></div>
      <div class="tile"><div class="v">${nodes.length}</div><div class="k">clients</div></div>
      <div class="tile"><div class="v">${count(nodes, n => n.Status === "ready")}</div><div class="k">clients ready</div></div>
      <div class="tile"><div class="v">${count(allocs, a => a.ClientStatus === "running")}</div><div class="k">allocs running</div></div>
      <div class="tile"><div class="v">${count(allocs, a => a.ClientStatus === "failed")}</div><div class="k">allocs failed</div></div>
      <div class="tile"><div class="v">${count(evals, e => e.Status === "pending" || e.Status === "blocked")}</div><div class="k">evals queued</div></div>
    </div>
    <h2>Recent evaluations</h2>
    ${evalTable(evals.slice(-8).reverse())}
  `);
}

async function viewJobs() {
  const jobs = await get("/v1/jobs");
  render(`
    <div class="toolbar"><div><h1>Jobs</h1>
    <p class="sub">${jobs.length} job(s) in namespace ${esc(localStorage.getItem("nomad_namespace") || "default")}</p></div></div>
    <table><thead><tr><th>Name</th><th>Type</th><th>Priority</th><th>Status</th><th>Version</th></tr></thead><tbody>
    ${jobs.map(j => `<tr class="rowlink" onclick="location.hash='#/jobs/${encodeURIComponent(j.ID)}'">
      <td><a href="#/jobs/${encodeURIComponent(j.ID)}">${esc(j.Name)}</a><br><span class="muted mono">${esc(j.ID)}</span></td>
      <td>${esc(j.Type)}</td><td>${j.Priority}</td><td>${badge(j.Stop ? "stopped" : j.Status)}</td>
      <td>v${j.Version}</td></tr>`).join("")}
    </tbody></table>`);
}

async function viewJobDetail(id) {
  const [job, summary, allocs, evals, deploys, vresp] = await Promise.all([
    get(`/v1/job/${encodeURIComponent(id)}`),
    get(`/v1/job/${encodeURIComponent(id)}/summary`).catch(() => null),
    get(`/v1/job/${encodeURIComponent(id)}/allocations`).catch(() => []),
    get(`/v1/job/${encodeURIComponent(id)}/evaluations`).catch(() => []),
    get(`/v1/job/${encodeURIComponent(id)}/deployments`).catch(() => []),
    get(`/v1/job/${encodeURIComponent(id)}/versions`).catch(() => null),
  ]);
  const versions = (vresp && vresp.Versions) || [];
  const sum = (summary && summary.Summary) || {};
  render(`
    <h1>${esc(job.Name)} ${badge(job.Stop ? "stopped" : job.Status)}</h1>
    <p class="sub mono">${esc(job.ID)} · ${esc(job.Type)} · priority ${job.Priority} · v${job.Version} · dc [${(job.Datacenters || []).map(esc).join(", ")}]</p>
    <div class="actions">
      <button onclick="jobAction('stop','${jsArg(id)}')" class="danger">Stop job</button>
      <button onclick="jobAction('purge','${jsArg(id)}')" class="danger">Purge</button>
    </div>
    <h2>Task groups</h2>
    <table><thead><tr><th>Group</th><th>Count</th><th>Queued</th><th>Starting</th><th>Running</th><th>Failed</th><th>Complete</th><th>Lost</th><th>Scale</th></tr></thead><tbody>
    ${(job.TaskGroups || []).map(tg => {
      const s = sum[tg.Name] || {};
      return `<tr><td>${esc(tg.Name)}</td><td>${tg.Count}</td>
        <td>${s.Queued ?? 0}</td><td>${s.Starting ?? 0}</td><td>${s.Running ?? 0}</td>
        <td>${s.Failed ?? 0}</td><td>${s.Complete ?? 0}</td><td>${s.Lost ?? 0}</td>
        <td><button onclick="scaleGroup('${jsArg(id)}','${jsArg(tg.Name)}',${(tg.Count | 0) - 1})">−</button>
            <button onclick="scaleGroup('${jsArg(id)}','${jsArg(tg.Name)}',${(tg.Count | 0) + 1})">+</button></td></tr>`;
    }).join("")}
    </tbody></table>
    <h2>Allocations (${allocs.length})</h2>
    ${allocTable(allocs)}
    <h2>Deployments</h2>
    ${deployTable(deploys)}
    <h2>Versions</h2>
    ${versionsTable(id, versions, job.Version)}
    <h2>Evaluations</h2>
    ${evalTable(evals.slice(-10).reverse())}
  `);
}

function versionsTable(jobId, versions, current) {
  if (!versions || !versions.length) return `<p class="muted">none</p>`;
  return `<table><thead><tr><th>Version</th><th>Stable</th><th>Status</th><th></th></tr></thead><tbody>
  ${versions.map(v => `<tr>
    <td>v${v.Version}${v.Version === current ? ' <span class="muted">(current)</span>' : ""}</td>
    <td>${v.Stable ? "yes" : ""}</td>
    <td>${badge(v.Stop ? "stopped" : v.Status)}</td>
    <td>${v.Version === current ? "" :
      `<button onclick="jobRevert('${jsArg(jobId)}',${v.Version | 0})">Revert to</button>`}</td>
  </tr>`).join("")}</tbody></table>`;
}

window.jobRevert = async (id, version) => {
  id = arg(id);
  if (!confirm(`revert ${id} to version ${version}?`)) return;
  try {
    await post(`/v1/job/${encodeURIComponent(id)}/revert`,
               { JobID: id, JobVersion: version });
    route();
  } catch (e) { renderError(e); }
};

window.jobAction = async (verb, id) => {
  id = arg(id);
  if (!confirm(`${verb} job ${id}?`)) return;
  try {
    await del(`/v1/job/${encodeURIComponent(id)}` + (verb === "purge" ? "?purge=true" : ""));
    route();
  } catch (e) { renderError(e); }
};
window.scaleGroup = async (id, group, count) => {
  id = arg(id); group = arg(group);
  if (count < 0) return;
  try {
    await post(`/v1/job/${encodeURIComponent(id)}/scale`,
      { Target: { Group: group }, Count: count, Message: "scaled from web UI" });
    route();
  } catch (e) { renderError(e); }
};

function allocTable(allocs) {
  if (!allocs.length) return `<p class="muted">none</p>`;
  return `<table><thead><tr><th>ID</th><th>Task group</th><th>Node</th><th>Desired</th><th>Client status</th><th>Created</th></tr></thead><tbody>
  ${allocs.map(a => `<tr class="rowlink" onclick="location.hash='#/allocations/${jsArg(a.ID)}'">
    <td class="mono"><a href="#/allocations/${jsArg(a.ID)}">${shortId(a.ID)}</a></td>
    <td>${esc(a.TaskGroup)}</td>
    <td class="mono"><a href="#/clients/${jsArg(a.NodeID)}" onclick="event.stopPropagation()">${esc(a.NodeName || shortId(a.NodeID))}</a></td>
    <td>${badge(a.DesiredStatus)}</td><td>${badge(a.ClientStatus)}</td>
    <td class="muted">${fmtTime(a.CreateTime || a.CreateTimeNs)}</td></tr>`).join("")}
  </tbody></table>`;
}
function evalTable(evals) {
  if (!evals.length) return `<p class="muted">none</p>`;
  return `<table><thead><tr><th>ID</th><th>Job</th><th>Type</th><th>Triggered by</th><th>Status</th></tr></thead><tbody>
  ${evals.map(e => `<tr>
    <td class="mono">${shortId(e.ID)}</td>
    <td class="mono"><a href="#/jobs/${encodeURIComponent(e.JobID)}">${esc(e.JobID || "—")}</a></td>
    <td>${esc(e.Type)}</td><td>${esc(e.TriggeredBy)}</td>
    <td>${badge(e.Status)}${e.StatusDescription ? ` <span class="muted">${esc(e.StatusDescription)}</span>` : ""}</td></tr>`).join("")}
  </tbody></table>`;
}
function deployTable(ds) {
  if (!ds || !ds.length) return `<p class="muted">none</p>`;
  return `<table><thead><tr><th>ID</th><th>Job</th><th>Status</th><th>Description</th><th></th></tr></thead><tbody>
  ${ds.map(d => `<tr>
    <td class="mono">${shortId(d.ID)}</td>
    <td class="mono"><a href="#/jobs/${encodeURIComponent(d.JobID || "")}">${esc(d.JobID || "—")}</a></td>
    <td>${badge(d.Status)}</td><td class="muted">${esc(d.StatusDescription || "")}</td>
    <td>${d.Status === "running" ? `
      <button onclick="deployAction('promote','${jsArg(d.ID)}')">Promote</button>
      <button onclick="deployAction('fail','${jsArg(d.ID)}')" class="danger">Fail</button>` : ""}</td></tr>`).join("")}
  </tbody></table>`;
}
window.deployAction = async (verb, id) => {
  try {
    await post(`/v1/deployment/${verb}/${id}`, verb === "promote" ? { All: true } : {});
    route();
  } catch (e) { renderError(e); }
};

async function viewClients() {
  const nodes = await get("/v1/nodes");
  render(`
    <h1>Clients</h1>
    <p class="sub">${nodes.length} node(s)</p>
    <table><thead><tr><th>Name</th><th>Datacenter</th><th>Class</th><th>Pool</th><th>Status</th><th>Eligibility</th><th>Drain</th></tr></thead><tbody>
    ${nodes.map(n => `<tr class="rowlink" onclick="location.hash='#/clients/${jsArg(n.ID)}'">
      <td><a href="#/clients/${jsArg(n.ID)}">${esc(n.Name)}</a><br><span class="muted mono">${shortId(n.ID)}</span></td>
      <td>${esc(n.Datacenter)}</td><td>${esc(n.NodeClass || "—")}</td><td>${esc(n.NodePool || "default")}</td>
      <td>${badge(n.Status)}</td><td>${badge(n.SchedulingEligibility)}</td>
      <td>${n.Drain ? badge("draining") : '<span class="muted">—</span>'}</td></tr>`).join("")}
    </tbody></table>`);
}

async function viewClientDetail(id) {
  const [node, allocs] = await Promise.all([
    get(`/v1/node/${id}`), get(`/v1/node/${id}/allocations`).catch(() => []),
  ]);
  const nr = node.NodeResources || {};
  const cpu = (nr.CPU || {}).CPUShares || 0;
  const mem = (nr.Memory || {}).MemoryMB || 0;
  const disk = (nr.Disk || {}).DiskMB || 0;
  const attrs = node.Attributes || {};
  const drivers = node.Drivers || {};
  const eligible = node.SchedulingEligibility === "eligible";
  render(`
    <h1>${esc(node.Name)} ${badge(node.Status)}</h1>
    <p class="sub mono">${esc(node.ID)} · ${esc(node.Datacenter)} · pool ${esc(node.NodePool || "default")}</p>
    <div class="actions">
      <button onclick="nodeDrain('${jsArg(node.ID)}', ${node.Drain ? "false" : "true"})" ${node.Drain ? "" : 'class="danger"'}>
        ${node.Drain ? "Stop drain" : "Drain node"}</button>
      <button onclick="nodeElig('${jsArg(node.ID)}', '${eligible ? "ineligible" : "eligible"}')">
        Mark ${eligible ? "ineligible" : "eligible"}</button>
    </div>
    <div class="tiles">
      <div class="tile"><div class="v">${cpu}</div><div class="k">CPU MHz</div></div>
      <div class="tile"><div class="v">${fmtMB(mem)}</div><div class="k">memory</div></div>
      <div class="tile"><div class="v">${fmtMB(disk)}</div><div class="k">disk</div></div>
      <div class="tile"><div class="v">${allocs.length}</div><div class="k">allocations</div></div>
    </div>
    <h2>Drivers</h2>
    <table><thead><tr><th>Driver</th><th>Detected</th><th>Healthy</th></tr></thead><tbody>
      ${Object.entries(drivers).map(([name, d]) => `<tr><td>${esc(name)}</td>
        <td>${d.Detected ? "yes" : "no"}</td><td>${badge(d.Healthy ? "healthy" : "unhealthy")}</td></tr>`).join("")}
    </tbody></table>
    <h2>Allocations</h2>
    ${allocTable(allocs.map(a => ({ ...a, ID: a.ID || a.id, NodeID: id })))}
    <h2>Attributes</h2>
    <dl class="kv">${Object.entries(attrs).sort().map(([k, v]) =>
      `<dt class="mono">${esc(k)}</dt><dd class="mono">${esc(v)}</dd>`).join("")}</dl>
  `);
}
window.nodeDrain = async (id, enable) => {
  try {
    await post(`/v1/node/${id}/drain`,
      enable === "true" || enable === true ? { DrainSpec: { Deadline: 3600e9 } } : { DrainSpec: null });
    route();
  } catch (e) { renderError(e); }
};
window.nodeElig = async (id, elig) => {
  try { await post(`/v1/node/${id}/eligibility`, { Eligibility: elig }); route(); }
  catch (e) { renderError(e); }
};

async function viewAllocs() {
  const allocs = await get("/v1/allocations");
  render(`
    <h1>Allocations</h1>
    <p class="sub">${allocs.length} allocation(s)</p>
    <table><thead><tr><th>ID</th><th>Job</th><th>Task group</th><th>Node</th><th>Desired</th><th>Client status</th><th>Modified</th></tr></thead><tbody>
    ${allocs.map(a => `<tr class="rowlink" onclick="location.hash='#/allocations/${jsArg(a.ID)}'">
      <td class="mono"><a href="#/allocations/${jsArg(a.ID)}">${shortId(a.ID)}</a></td>
      <td class="mono"><a href="#/jobs/${encodeURIComponent(a.JobID)}" onclick="event.stopPropagation()">${esc(a.JobID)}</a></td>
      <td>${esc(a.TaskGroup)}</td>
      <td>${esc(a.NodeName || shortId(a.NodeID))}</td>
      <td>${badge(a.DesiredStatus)}</td><td>${badge(a.ClientStatus)}</td>
      <td class="muted">${fmtTime(a.ModifyTime)}</td></tr>`).join("")}
    </tbody></table>`);
}

async function viewAllocDetail(id) {
  const a = await get(`/v1/allocation/${id}`);
  const states = a.TaskStates || {};
  render(`
    <h1>Allocation ${shortId(a.ID)} ${badge(a.ClientStatus)}</h1>
    <p class="sub mono">${esc(a.Name || a.ID)} · job <a href="#/jobs/${encodeURIComponent(a.JobID)}">${esc(a.JobID)}</a>
      · node <a href="#/clients/${jsArg(a.NodeID)}">${esc(a.NodeName || shortId(a.NodeID))}</a></p>
    <div class="actions">
      <a href="#/allocations/${jsArg(a.ID)}/fs"><button>Files</button></a>
      <button onclick="allocStop('${jsArg(a.ID)}')" class="danger">Stop allocation</button>
    </div>
    <dl class="kv">
      <dt>Desired status</dt><dd>${badge(a.DesiredStatus)}</dd>
      <dt>Task group</dt><dd>${esc(a.TaskGroup)}</dd>
      <dt>Eval</dt><dd class="mono">${esc(a.EvalID || "—")}</dd>
      <dt>Deployment</dt><dd class="mono">${esc(a.DeploymentID || "—")}</dd>
      <dt>Created</dt><dd>${fmtTime(a.CreateTime || a.CreateTimeNs)}</dd>
    </dl>
    <h2>Tasks</h2>
    ${Object.keys(states).length ? Object.entries(states).map(([name, st]) => `
      <h2 class="mono" style="font-size:13.5px">${esc(name)} ${badge(st.State)}
        <a href="#/allocations/${jsArg(a.ID)}/logs/${jsArg(name)}"><button>Logs</button></a>
        ${st.State === "running" ? `<a href="#/allocations/${jsArg(a.ID)}/exec/${jsArg(name)}"><button>Exec</button></a>` : ""}
      </h2>
      <table><thead><tr><th>Time</th><th>Type</th><th>Message</th></tr></thead><tbody>
      ${(st.Events || []).map(ev => `<tr>
        <td class="muted">${fmtTime(ev.Time || ev.TimeNs)}</td><td>${esc(ev.Type)}</td>
        <td>${esc(ev.DisplayMessage || ev.Message || "")}</td></tr>`).join("")}
      </tbody></table>`).join("") : `<p class="muted">no task state reported yet</p>`}
    <h2>Placement metrics</h2>
    ${placementMetrics(a.Metrics)}
  `);
}
function placementMetrics(m) {
  if (!m) return `<p class="muted">none</p>`;
  /* ScoreMeta entries are [nodeID, {score-name: value}, normScore]
     (AllocMetric top-K node scores via kheap) */
  const scores = m.ScoreMeta || [];
  return `<dl class="kv">
    <dt>Nodes evaluated</dt><dd>${m.NodesEvaluated ?? "—"}</dd>
    <dt>Nodes filtered</dt><dd>${m.NodesFiltered ?? "—"}</dd>
    <dt>Nodes exhausted</dt><dd>${m.NodesExhausted ?? "—"}</dd>
  </dl>
  ${scores.length ? `<table><thead><tr><th>Node</th><th>Norm score</th><th>Scores</th></tr></thead><tbody>
    ${scores.slice(0, 8).map(([nodeId, byName, norm]) => `<tr><td class="mono">${shortId(nodeId)}</td>
      <td>${(+norm || 0).toFixed(4)}</td>
      <td class="muted">${esc(Object.entries(byName || {}).map(([k, v]) => `${k}=${(+v).toFixed(3)}`).join(" "))}</td>
    </tr>`).join("")}</tbody></table>` : ""}`;
}
window.allocStop = async (id) => {
  if (!confirm(`stop allocation ${id.slice(0, 8)}?`)) return;
  try { await post(`/v1/allocation/${id}/stop`); route(); }
  catch (e) { renderError(e); }
};

async function viewEvals() {
  const evals = await get("/v1/evaluations");
  render(`<h1>Evaluations</h1>
    <p class="sub">${evals.length} evaluation(s)</p>
    ${evalTable(evals.slice().reverse())}`);
}

async function viewDeployments() {
  const ds = await get("/v1/deployments");
  render(`<h1>Deployments</h1>
    <p class="sub">${ds.length} deployment(s)</p>
    ${deployTable(ds.slice().reverse())}`);
}

async function viewServices() {
  const groups = await get("/v1/services");
  const specs = [];
  for (const g of groups) {
    for (const svc of (g.Services || [])) {
      specs.push({ ns: g.Namespace, name: svc.ServiceName,
                   tags: svc.Tags || [] });
    }
  }
  // one parallel fetch per service, pinned to the group's namespace
  // (the list can span namespaces; instance lookup is exact-match)
  const rows = await Promise.all(specs.map(async (spec) => ({
    ...spec,
    insts: await get(
      `/v1/service/${encodeURIComponent(spec.name)}` +
      `?namespace=${encodeURIComponent(spec.ns)}`).catch(() => []),
  })));
  render(`
    <h1>Services</h1>
    <p class="sub">${rows.length} service(s) (native service discovery)</p>
    ${rows.length ? rows.map(r => `
      <h2>${esc(r.name)} <span class="muted">${esc(r.tags.join(", "))}</span></h2>
      <table><thead><tr><th>ID</th><th>Alloc</th><th>Node</th><th>Address</th><th>Port</th></tr></thead><tbody>
      ${(r.insts || []).map(i => `<tr>
        <td class="mono">${shortId(i.ID)}</td>
        <td class="mono">${i.AllocID
          ? `<a href="#/allocations/${jsArg(i.AllocID)}">${shortId(i.AllocID)}</a>`
          : '<span class="muted">—</span>'}</td>
        <td class="mono">${shortId(i.NodeID)}</td>
        <td class="mono">${esc(i.Address || "")}</td><td>${i.Port ?? ""}</td></tr>`).join("")}
      </tbody></table>`).join("") : `<p class="muted">no registered services</p>`}
  `);
}

async function viewVolumes() {
  const [vols, plugins] = await Promise.all([
    get("/v1/volumes").catch(() => []),
    get("/v1/plugins").catch(() => []),
  ]);
  render(`
    <h1>Volumes</h1>
    <p class="sub">${vols.length} CSI volume(s)</p>
    ${vols.length ? `<table><thead><tr><th>ID</th><th>Name</th><th>Plugin</th><th>Schedulable</th><th>Access</th><th>Allocs</th></tr></thead><tbody>
    ${vols.map(v => `<tr class="rowlink" onclick="location.hash='#/volumes/${jsArg(v.ID)}'">
      <td class="mono"><a href="#/volumes/${jsArg(v.ID)}">${esc(v.ID)}</a></td><td>${esc(v.Name || "")}</td>
      <td class="mono"><a href="#/plugins/${jsArg(v.PluginID || "")}">${esc(v.PluginID || "")}</a></td>
      <td>${badge(v.Schedulable ? "ready" : "unavailable")}</td>
      <td class="muted">${esc(v.AccessMode || "")}</td>
      <td>${(v.CurrentReaders ?? 0) + (v.CurrentWriters ?? 0)}</td></tr>`).join("")}
    </tbody></table>` : `<p class="muted">none</p>`}
    <h2>Plugins</h2>
    ${plugins.length ? `<table><thead><tr><th>ID</th><th>Provider</th><th>Controllers</th><th>Nodes</th></tr></thead><tbody>
    ${plugins.map(p => `<tr class="rowlink" onclick="location.hash='#/plugins/${jsArg(p.ID)}'">
      <td class="mono"><a href="#/plugins/${jsArg(p.ID)}">${esc(p.ID)}</a></td><td>${esc(p.Provider || "")}</td>
      <td>${p.ControllersHealthy ?? 0}/${p.ControllersExpected ?? 0}</td>
      <td>${p.NodesHealthy ?? 0}/${p.NodesExpected ?? 0}</td></tr>`).join("")}
    </tbody></table>` : `<p class="muted">none</p>`}
  `);
}

async function viewVolumeDetail(id) {
  const v = await get(`/v1/volume/csi/${encodeURIComponent(id)}`);
  const allocRow = (a, mode) => `<tr class="rowlink"
      onclick="location.hash='#/allocations/${jsArg(a.ID)}'">
    <td class="mono"><a href="#/allocations/${jsArg(a.ID)}">${shortId(a.ID)}</a></td>
    <td>${esc(mode)}</td><td>${badge(a.ClientStatus)}</td></tr>`;
  render(`
    <h1>${esc(v.Name || v.ID)}</h1>
    <p class="sub mono">${esc(v.ID)}</p>
    <div class="tiles">
      <div class="tile"><div class="v">${badge(v.Schedulable ? "ready" : "unavailable")}</div><div class="k">schedulable</div></div>
      <div class="tile"><div class="v">${esc(v.AccessMode || "—")}</div><div class="k">access mode</div></div>
      <div class="tile"><div class="v">${esc(v.AttachmentMode || "—")}</div><div class="k">attachment</div></div>
      <div class="tile"><div class="v">${(v.CurrentReaders ?? 0)}/${(v.CurrentWriters ?? 0)}</div><div class="k">readers/writers</div></div>
    </div>
    <p class="sub">plugin <a class="mono" href="#/plugins/${jsArg(v.PluginID || "")}">${esc(v.PluginID || "—")}</a>
       · namespace ${esc(v.Namespace || "default")}</p>
    <h2>Claims</h2>
    <table><thead><tr><th>Alloc</th><th>Mode</th><th>Status</th></tr></thead><tbody>
      ${(v.ReadAllocs || []).map(a => allocRow(a, "read")).join("")}
      ${(v.WriteAllocs || []).map(a => allocRow(a, "write")).join("")}
    </tbody></table>
    <button class="danger" onclick="detachVolume('${jsArg(v.ID)}')">Detach all</button>
  `);
}
async function detachVolume(id) {
  try {
    await post(`/v1/volume/csi/${encodeURIComponent(arg(id))}/detach`, {});
    route();
  } catch (e) { renderError(e); }
}

async function viewPluginDetail(id) {
  const p = await get(`/v1/plugin/csi/${encodeURIComponent(id)}`);
  render(`
    <h1>Plugin ${esc(p.ID)}</h1>
    <p class="sub">provider ${esc(p.Provider || "—")} ${esc(p.Version || "")}</p>
    <div class="tiles">
      <div class="tile"><div class="v">${p.ControllersHealthy ?? 0}/${p.ControllersExpected ?? 0}</div><div class="k">controllers healthy</div></div>
      <div class="tile"><div class="v">${p.NodesHealthy ?? 0}/${p.NodesExpected ?? 0}</div><div class="k">nodes healthy</div></div>
    </div>
  `);
}

/* ---------- ACL management (reference ui/ policies + tokens) ---------- */

async function viewACL() {
  const [policies, tokens] = await Promise.all([
    get("/v1/acl/policies").catch(() => []),
    get("/v1/acl/tokens").catch(() => []),
  ]);
  render(`
    <div class="toolbar"><div><h1>Access control</h1>
    <p class="sub">${policies.length} policy(ies), ${tokens.length} token(s)</p></div>
    <button onclick="location.hash='#/acl/policies/_new'">New policy</button></div>
    <h2>Policies</h2>
    ${policies.length ? `<table><thead><tr><th>Name</th><th>Description</th></tr></thead><tbody>
    ${policies.map(p => `<tr class="rowlink" onclick="location.hash='#/acl/policies/${jsArg(p.Name)}'">
      <td><a href="#/acl/policies/${jsArg(p.Name)}">${esc(p.Name)}</a></td>
      <td class="muted">${esc(p.Description || "")}</td></tr>`).join("")}
    </tbody></table>` : `<p class="muted">no policies (ACLs may be disabled)</p>`}
    <h2>Tokens</h2>
    ${tokens.length ? `<table><thead><tr><th>Name</th><th>Type</th><th>Accessor</th><th>Policies</th><th>Global</th></tr></thead><tbody>
    ${tokens.map(t => `<tr>
      <td>${esc(t.Name || "")}</td><td>${esc(t.Type)}</td>
      <td class="mono">${shortId(t.AccessorID)}</td>
      <td class="mono">${esc((t.Policies || []).join(", "))}</td>
      <td>${t.Global ? "yes" : ""}</td></tr>`).join("")}
    </tbody></table>` : `<p class="muted">no tokens visible</p>`}
  `);
}

async function viewACLPolicy(name) {
  const fresh = name === "_new";
  let p = { Name: "", Description: "", Rules: "" };
  if (!fresh) p = await get(`/v1/acl/policy/${encodeURIComponent(name)}`);
  render(`
    <h1>${fresh ? "New policy" : `Policy ${esc(p.Name)}`}</h1>
    <div class="form">
      <label>Name <input id="pol-name" value="${esc(p.Name)}" ${fresh ? "" : "readonly"}></label>
      <label>Description <input id="pol-desc" value="${esc(p.Description || "")}"></label>
      <label>Rules (HCL)<textarea id="pol-rules" rows="14" class="mono">${esc(p.Rules || "")}</textarea></label>
      <div class="toolbar">
        <button onclick="savePolicy()">Save</button>
        ${fresh ? "" : `<button class="danger" onclick="deletePolicy('${jsArg(p.Name)}')">Delete</button>`}
      </div>
    </div>
  `);
}
async function savePolicy() {
  const name = document.getElementById("pol-name").value.trim();
  if (!name) { renderError(new Error("policy name required")); return; }
  try {
    await post(`/v1/acl/policy/${encodeURIComponent(name)}`, {
      Name: name,
      Description: document.getElementById("pol-desc").value,
      Rules: document.getElementById("pol-rules").value,
    });
    location.hash = "#/acl";
  } catch (e) { renderError(e); }
}
async function deletePolicy(name) {
  try {
    await del(`/v1/acl/policy/${encodeURIComponent(arg(name))}`);
    location.hash = "#/acl";
  } catch (e) { renderError(e); }
}

async function viewTopology() {
  // both stubs carry flattened resources (?resources=true) so the
  // whole view is two list calls regardless of cluster size
  const [nodes, allocs] = await Promise.all([
    get("/v1/nodes?resources=true"), get("/v1/allocations?resources=true"),
  ]);
  const byNode = {};
  for (const a of allocs) {
    if (a.ClientStatus !== "running" && a.ClientStatus !== "pending") continue;
    const r = a.AllocatedResources || {};
    const agg = byNode[a.NodeID] || (byNode[a.NodeID] = { cpu: 0, mem: 0, n: 0 });
    agg.cpu += r.CPU || 0; agg.mem += r.MemoryMB || 0; agg.n += 1;
  }
  /* topo-viz analog (ui/app/components/topo-viz): one cell per node,
     grouped by datacenter, area ∝ memory capacity, fill height =
     allocated memory share, fill hue = allocated cpu share; hover for
     exact numbers, click through to the client. Scales to thousands
     of nodes where per-node meter cards cannot. */
  const byDC = {};
  for (const n of nodes) (byDC[n.Datacenter] || (byDC[n.Datacenter] = [])).push(n);
  const maxMem = Math.max(1, ...nodes.map(n => (n.NodeResources || {}).MemoryMB || 0));
  const cell = (node) => {
    const nr = node.NodeResources || {};
    const used = byNode[node.ID] || { cpu: 0, mem: 0, n: 0 };
    const memPct = nr.MemoryMB ? Math.min(100, 100 * used.mem / nr.MemoryMB) : 0;
    const cpuPct = nr.CPU ? Math.min(100, 100 * used.cpu / nr.CPU) : 0;
    /* green (idle) -> amber -> red (cpu-saturated) */
    const hue = Math.round(120 - 1.2 * cpuPct);
    const side = Math.round(22 + 26 * Math.sqrt((nr.MemoryMB || 0) / maxMem));
    const down = node.Status !== "ready";
    const title = `${node.Name} · ${node.Status}${node.Drain ? " draining" : ""}
cpu ${used.cpu}/${nr.CPU || 0} MHz (${cpuPct.toFixed(0)}%)
mem ${fmtMB(used.mem)}/${fmtMB(nr.MemoryMB || 0)} (${memPct.toFixed(0)}%)
${used.n} alloc(s)`;
    return `<div class="topo-cell${down ? " down" : ""}" title="${esc(title)}"
      onclick="location.hash='#/clients/${jsArg(node.ID)}'"
      style="width:${side}px;height:${side}px">
      <div class="fill" style="height:${memPct.toFixed(0)}%;background:hsl(${hue},65%,45%)"></div>
      ${node.Drain ? '<div class="drainmark">◢</div>' : ""}
    </div>`;
  };
  render(`
    <h1>Topology</h1>
    <p class="sub">${nodes.length} node(s) · ${allocs.length} allocation(s) —
      cell area ∝ memory capacity, fill = allocated memory, color = allocated cpu
      (green idle → red saturated); hatched = down, ◢ = draining</p>
    <style>
      .topo-dc { margin: 14px 0; }
      .topo-grid { display: flex; flex-wrap: wrap; gap: 4px; align-items: flex-end; }
      .topo-cell { position: relative; border: 1px solid var(--border,#444);
        border-radius: 3px; overflow: hidden; cursor: pointer;
        background: var(--panel,#1a1a1a); }
      .topo-cell .fill { position: absolute; bottom: 0; left: 0; right: 0; }
      .topo-cell.down { background: repeating-linear-gradient(45deg,
        transparent, transparent 3px, rgba(255,80,80,.45) 3px,
        rgba(255,80,80,.45) 6px); }
      .topo-cell .drainmark { position: absolute; top: 0; right: 2px;
        font-size: 9px; color: #fff; text-shadow: 0 0 2px #000; }
    </style>
    ${Object.keys(byDC).sort().map(dc => `
      <div class="topo-dc">
        <h2>${esc(dc)} <span class="muted" style="font-size:12px">${byDC[dc].length} node(s)</span></h2>
        <div class="topo-grid">${byDC[dc].map(cell).join("")}</div>
      </div>`).join("")}`);
}

async function viewServers() {
  const [members, raft, health] = await Promise.all([
    get("/v1/agent/members").catch(() => ({ Members: [] })),
    get("/v1/operator/raft/configuration").catch(() => null),
    get("/v1/operator/autopilot/health").catch(() => null),
  ]);
  render(`
    <h1>Servers</h1>
    <p class="sub">region ${esc(members.ServerRegion || "—")}</p>
    <table><thead><tr><th>Name</th><th>Address</th><th>Status</th><th>Tags</th></tr></thead><tbody>
    ${(members.Members || []).map(m => `<tr>
      <td>${esc(m.Name)}</td><td class="mono">${esc(m.Addr)}</td><td>${badge(m.Status)}</td>
      <td class="muted mono">${esc(Object.entries(m.Tags || {}).map(([k, v]) => `${k}=${v}`).join(" "))}</td></tr>`).join("")}
    </tbody></table>
    ${raft && raft.Servers ? `<h2>Raft configuration</h2>
    <table><thead><tr><th>ID</th><th>Address</th><th>Leader</th><th>Voter</th></tr></thead><tbody>
    ${raft.Servers.map(s => `<tr><td class="mono">${esc(s.ID)}</td><td class="mono">${esc(s.Address)}</td>
      <td>${s.Leader ? "yes" : ""}</td><td>${s.Voter ? "yes" : ""}</td></tr>`).join("")}
    </tbody></table>` : ""}
    ${health ? `<h2>Autopilot</h2><dl class="kv">
      <dt>Healthy</dt><dd>${badge(health.Healthy ? "healthy" : "unhealthy")}</dd>
      <dt>Failure tolerance</dt><dd>${health.FailureTolerance ?? "—"}</dd></dl>` : ""}
  `);
}

async function viewSettings() {
  render(`
    <h1>Settings</h1>
    <h2>ACL token</h2>
    <p class="sub">sent as <code>X-Nomad-Token</code> on every request; stored in this browser only</p>
    <div class="actions">
      <input type="password" id="tok" placeholder="Secret ID" value="${esc(token())}">
      <button onclick="localStorage.setItem('nomad_token', document.getElementById('tok').value); route();">Save</button>
      <button onclick="localStorage.removeItem('nomad_token'); route();" class="danger">Clear</button>
    </div>
    <h2>Namespace</h2>
    <div class="actions">
      <input type="text" id="ns" placeholder="default" value="${esc(localStorage.getItem("nomad_namespace") || "")}">
      <button onclick="localStorage.setItem('nomad_namespace', document.getElementById('ns').value); route();">Save</button>
    </div>
    <h2>Agent</h2>
    <pre class="mono" id="agent-self" style="white-space:pre-wrap"></pre>
  `);
  try {
    const self = await get("/v1/agent/self");
    document.getElementById("agent-self").textContent = JSON.stringify(self, null, 2).slice(0, 4000);
  } catch (e) { /* agent info is best-effort */ }
}

/* ---------- alloc filesystem browser (ui fs-browser analog) -------- */

async function viewAllocFs(allocId, path) {
  path = path || "/";
  const entries = await get(
    `/v1/client/fs/ls/${jsArg(allocId)}?path=${encodeURIComponent(path)}`);
  const parts = path.split("/").filter(Boolean);
  let acc = "";
  const crumbs = [`<a href="#/allocations/${jsArg(allocId)}/fs">/</a>`]
    .concat(parts.map(p => {
      acc += "/" + p;
      return `<a href="#/allocations/${jsArg(allocId)}/fs${hashPath(acc)}">${esc(p)}</a>`;
    })).join(" / ");
  render(`
    <h1>Files <span class="mono" style="font-size:14px">${shortId(allocId)}</span></h1>
    <p class="sub mono">${crumbs}
      (<a href="#/allocations/${jsArg(allocId)}">back to allocation</a>)</p>
    <table id="fs-table"><thead><tr><th>Name</th><th>Size</th><th>Modified</th></tr></thead><tbody>
    ${entries.map(e => {
      const target = (path.endsWith("/") ? path : path + "/") + e.Name;
      const href = e.IsDir
        ? `#/allocations/${jsArg(allocId)}/fs${hashPath(target)}`
        : `#/allocations/${jsArg(allocId)}/cat${hashPath(target)}`;
      return `<tr class="rowlink" onclick="location.hash='${href}'">
        <td class="mono"><a href="${href}">${e.IsDir ? "&#128193; " : ""}${esc(e.Name)}${e.IsDir ? "/" : ""}</a></td>
        <td>${e.IsDir ? "—" : e.Size}</td>
        <td class="muted">${new Date(e.ModTime * 1000).toLocaleString()}</td></tr>`;
    }).join("")}
    </tbody></table>`);
}

async function viewAllocFile(allocId, path) {
  const st = await get(
    `/v1/client/fs/stat/${jsArg(allocId)}?path=${encodeURIComponent(path)}`);
  const dir = path.replace(/\/[^/]*$/, "") || "/";
  const limit = 256 * 1024;
  const resp = await get(
    `/v1/client/fs/readat/${jsArg(allocId)}?path=${encodeURIComponent(path)}` +
    `&offset=${Math.max(0, st.Size - limit)}&limit=${limit}`);
  render(`
    <h1>File <span class="mono" style="font-size:14px">${esc(path)}</span></h1>
    <p class="sub mono">${st.Size} bytes
      (<a href="#/allocations/${jsArg(allocId)}/fs${hashPath(dir)}">back to ${esc(dir)}</a>)
      ${st.Size > limit ? `· showing last ${limit} bytes` : ""}</p>
    <pre class="mono" style="background:var(--panel,#111);border:1px solid var(--border,#333);border-radius:8px;max-height:65vh;overflow:auto;padding:12px;white-space:pre-wrap">${esc(resp.Data || "")}</pre>`);
}

/* ---------- log tailing (ui task logs analog) ---------------------- */

const LOG_ROUTE = /^#\/allocations\/[^/]+\/logs\//;
let logAbort = null;
function logCleanup() {
  if (logAbort) { try { logAbort.abort(); } catch (e) {} logAbort = null; }
}
async function viewAllocLogs(allocId, task, logtype) {
  logCleanup();
  logtype = logtype || "stdout";
  const other = logtype === "stdout" ? "stderr" : "stdout";
  render(`
    <h1>Logs <span class="mono" style="font-size:14px">${shortId(allocId)}/${esc(task)}</span></h1>
    <p class="sub">
      <strong>${logtype}</strong> ·
      <a href="#/allocations/${jsArg(allocId)}/logs/${jsArg(task)}/${other}">${other}</a> ·
      <label><input type="checkbox" id="log-follow" checked> follow</label>
      (<a href="#/allocations/${jsArg(allocId)}">back to allocation</a>)</p>
    <pre id="logpane" class="mono" style="background:var(--panel,#111);border:1px solid var(--border,#333);border-radius:8px;min-height:320px;max-height:65vh;overflow:auto;padding:12px;white-space:pre-wrap"></pre>`);
  const pane = document.getElementById("logpane");
  const follow = document.getElementById("log-follow");
  const append = (text) => {
    pane.textContent += text;
    if (follow.checked) pane.scrollTop = pane.scrollHeight;
  };
  /* follow via the chunked ?follow=true stream (fs_endpoint.go Logs);
     falls back to a one-shot read when streaming is unavailable */
  const headers = {};
  if (token()) headers["X-Nomad-Token"] = token();
  logAbort = new AbortController();
  const qs = new URLSearchParams({ task, type: logtype, follow: "true" });
  try {
    const resp = await fetch(
      `/v1/client/fs/logs/${encodeURIComponent(allocId)}?${qs}`,
      { headers, signal: logAbort.signal });
    if (!resp.ok || !resp.body) throw new Error(`HTTP ${resp.status}`);
    const reader = resp.body.getReader();
    const decoder = new TextDecoder();
    for (;;) {
      const { value, done } = await reader.read();
      if (done) break;
      append(decoder.decode(value, { stream: true }));
    }
    append("\n[log stream ended]\n");
  } catch (e) {
    if (e.name === "AbortError") return;
    try {
      const one = await get(
        `/v1/client/fs/logs/${jsArg(allocId)}?task=${jsArg(task)}&type=${logtype}`);
      append(one.Data || "");
    } catch (e2) { renderError(e2); }
  }
}

/* ---------- exec terminal (ui/app/components/exec analog) ---------- */

let execSocket = null;
function execCleanup() {
  if (execSocket) { try { execSocket.close(); } catch (e) {} execSocket = null; }
}
async function viewExec(allocId, task) {
  execCleanup();
  render(`
    <h1>Exec <span class="mono" style="font-size:14px">${shortId(allocId)}/${esc(task)}</span></h1>
    <p class="sub">interactive session via the agent websocket
      (<a href="#/allocations/${jsArg(allocId)}">back to allocation</a>)</p>
    <div class="actions">
      <input type="text" id="exec-cmd" class="mono" value="/bin/sh" style="width:260px">
      <button id="exec-start">Start</button>
      <button id="exec-stop" class="danger" disabled>Close</button>
      <span id="exec-status" class="muted"></span>
    </div>
    <pre id="term" class="mono" style="background:var(--panel,#111);border:1px solid var(--border,#333);border-radius:8px;min-height:320px;max-height:60vh;overflow:auto;padding:12px;white-space:pre-wrap"></pre>
    <div class="actions">
      <span class="mono muted">stdin&gt;</span>
      <input type="text" id="exec-stdin" class="mono" style="flex:1;width:60%" disabled>
    </div>
  `);
  const term = document.getElementById("term");
  const status = document.getElementById("exec-status");
  const stdin = document.getElementById("exec-stdin");
  const startBtn = document.getElementById("exec-start");
  const stopBtn = document.getElementById("exec-stop");
  const append = (text) => {
    term.textContent += text;
    term.scrollTop = term.scrollHeight;
  };
  const b64decode = (d) => {
    try { return atob(d); } catch (e) { return ""; }
  };
  startBtn.onclick = () => {
    execCleanup();
    term.textContent = "";
    const cmdText = document.getElementById("exec-cmd").value.trim() || "/bin/sh";
    /* shell-ish split: quoted args stay whole */
    const cmd = cmdText.match(/(?:[^\s"]+|"[^"]*")+/g).map(w => w.replace(/^"|"$/g, ""));
    const proto = location.protocol === "https:" ? "wss:" : "ws:";
    const qs = new URLSearchParams({
      task, tty: "false", command: JSON.stringify(cmd),
    });
    if (token()) qs.set("x_nomad_token", token());
    const ns = localStorage.getItem("nomad_namespace") || "";
    if (ns) qs.set("namespace", ns);
    const url = `${proto}//${location.host}/v1/client/allocation/${encodeURIComponent(allocId)}/exec?${qs}`;
    const sock = new WebSocket(url);
    execSocket = sock;
    status.textContent = "connecting…";
    sock.onopen = () => {
      status.textContent = "connected";
      stdin.disabled = false; stopBtn.disabled = false; stdin.focus();
    };
    sock.onmessage = (ev) => {
      let frame;
      try { frame = JSON.parse(ev.data); } catch (e) { return; }
      for (const key of ["stdout", "stderr"]) {
        const d = (frame[key] || {}).data;
        if (d) append(b64decode(d));
      }
      if (frame.exited) {
        const r = frame.result || {};
        append(`\n[session exited: code ${r.exit_code ?? "?"}]\n`);
        status.textContent = "exited";
        stdin.disabled = true; stopBtn.disabled = true;
      }
    };
    sock.onclose = () => {
      if (status.textContent !== "exited") status.textContent = "closed";
      stdin.disabled = true; stopBtn.disabled = true;
    };
    sock.onerror = () => { status.textContent = "error"; };
  };
  stopBtn.onclick = () => { execCleanup(); };
  stdin.onkeydown = (ev) => {
    if (ev.key !== "Enter" || !execSocket) return;
    const line = stdin.value + "\n";
    append(line);
    execSocket.send(JSON.stringify({ stdin: { data: btoa(line) } }));
    stdin.value = "";
  };
}

/* ---------- event-driven live updates ---------- */

/* The event stream (/v1/event/stream, NDJSON) drives list refreshes
   the way the reference UI's blocking queries do; polling remains as
   the fallback cadence when the stream is down. */
let eventStreamHealthy = false;
let eventRefreshTimer = null;
function startEventStream() {
  const headers = {};
  if (token()) headers["X-Nomad-Token"] = token();
  fetch("/v1/event/stream", { headers }).then(async (resp) => {
    if (!resp.ok || !resp.body) throw new Error("stream unavailable");
    eventStreamHealthy = true;
    const reader = resp.body.getReader();
    const decoder = new TextDecoder();
    let buf = "";
    for (;;) {
      const { value, done } = await reader.read();
      if (done) break;
      buf += decoder.decode(value, { stream: true });
      let nl;
      let sawEvent = false;
      while ((nl = buf.indexOf("\n")) >= 0) {
        const line = buf.slice(0, nl).trim();
        buf = buf.slice(nl + 1);
        if (line && line !== "{}") sawEvent = true;  // {} is heartbeat
      }
      if (sawEvent) {
        /* debounce: a plan commit emits bursts */
        clearTimeout(eventRefreshTimer);
        eventRefreshTimer = setTimeout(() => {
          const hash = location.hash || "#/";
          if (hash !== "#/settings" && !hash.includes("/exec/")
              && !LOG_ROUTE.test(hash)) route();
        }, 300);
      }
    }
    throw new Error("stream ended");
  }).catch(() => {
    eventStreamHealthy = false;
    setTimeout(startEventStream, 5000);   // reconnect with backoff
  });
}
startEventStream();

/* ---------- router ---------- */

const routes = [
  [/^#?\/?$/, viewOverview],
  [/^#\/jobs$/, viewJobs],
  [/^#\/jobs\/(.+)$/, (m) => viewJobDetail(decodeURIComponent(m[1]))],
  [/^#\/clients$/, viewClients],
  [/^#\/clients\/(.+)$/, (m) => viewClientDetail(m[1])],
  [/^#\/allocations$/, viewAllocs],
  [/^#\/allocations\/([^/]+)\/exec\/(.+)$/,
    (m) => viewExec(decodeURIComponent(m[1]), decodeURIComponent(m[2]))],
  [/^#\/allocations\/([^/]+)\/fs(\/.*)?$/,
    (m) => viewAllocFs(decodeURIComponent(m[1]),
                       decodeURIComponent(m[2] || "/"))],
  [/^#\/allocations\/([^/]+)\/cat(\/.+)$/,
    (m) => viewAllocFile(decodeURIComponent(m[1]),
                         decodeURIComponent(m[2]))],
  [/^#\/allocations\/([^/]+)\/logs\/([^/]+)(?:\/(stdout|stderr))?$/,
    (m) => viewAllocLogs(decodeURIComponent(m[1]),
                         decodeURIComponent(m[2]), m[3])],
  [/^#\/allocations\/(.+)$/, (m) => viewAllocDetail(m[1])],
  [/^#\/evaluations$/, viewEvals],
  [/^#\/deployments$/, viewDeployments],
  [/^#\/services$/, viewServices],
  [/^#\/volumes$/, viewVolumes],
  [/^#\/volumes\/(.+)$/, (m) => viewVolumeDetail(decodeURIComponent(m[1]))],
  [/^#\/plugins\/(.+)$/, (m) => viewPluginDetail(decodeURIComponent(m[1]))],
  [/^#\/acl$/, viewACL],
  [/^#\/acl\/policies\/(.+)$/, (m) => viewACLPolicy(decodeURIComponent(m[1]))],
  [/^#\/topology$/, viewTopology],
  [/^#\/servers$/, viewServers],
  [/^#\/settings$/, viewSettings],
];

async function route() {
  const hash = location.hash || "#/";
  if (!LOG_ROUTE.test(hash)) logCleanup();   // leaving a log tail
  for (const a of document.querySelectorAll("nav a")) {
    a.classList.toggle("active",
      a.getAttribute("href") === hash ||
      (a.getAttribute("href") !== "#/" && hash.startsWith(a.getAttribute("href") + "/")));
  }
  for (const [re, fn] of routes) {
    const m = hash.match(re);
    if (m) {
      clearInterval(refreshTimer);
      const run = async () => { await fn(m); };
      try { await run(); } catch (e) { render("<h1>error</h1>"); renderError(e); }
      // detail pages refresh too, but more gently; settings never
      // refreshes (it holds form inputs the re-render would wipe) and
      // the exec terminal never re-renders (it owns a live socket).
      // With a healthy event stream driving refreshes, polling drops
      // to a slow safety net.
      if (hash !== "#/settings" && !hash.includes("/exec/")
          && !LOG_ROUTE.test(hash)) {   // a log tail owns a stream
        const base = hash.split("/").length > 2 ? 6000 : 4000;
        autoRefresh(run, eventStreamHealthy ? 30000 : base);
      }
      return;
    }
  }
  render(`<h1>not found</h1><p class="sub">${esc(hash)}</p>`);
}

window.addEventListener("hashchange", route);
document.getElementById("theme-toggle").onclick = () => {
  const cur = document.documentElement.dataset.theme ||
    (matchMedia("(prefers-color-scheme: dark)").matches ? "dark" : "light");
  const next = cur === "dark" ? "light" : "dark";
  document.documentElement.dataset.theme = next;
  localStorage.setItem("nomad_theme", next);
};
if (localStorage.getItem("nomad_theme")) {
  document.documentElement.dataset.theme = localStorage.getItem("nomad_theme");
}
get("/v1/agent/members").then((m) => {
  document.getElementById("nav-region").textContent = m.ServerRegion || "";
}).catch(() => {});
route();
