"""UI test harness: a deterministic fake cluster behind the REAL /v1.

Reference behavior: ui/mirage/config.js + factories — the Ember app's
dev/test backend fakes the API so UI flows are exercisable without a
cluster. This build can do one better: the dev agent IS an in-process
cluster, so the harness seeds it with deterministic jobs/nodes/allocs
and real running tasks, and UI tests drive the REAL HTTP surface the
SPA talks to. (The environment ships no JavaScript runtime, so tests
exercise the exact request/response contract each view consumes —
routes, shapes, field names — rather than evaluating the JS; the
SPA itself is a static module, ``ui/app.js``, servable standalone for
browser-based verification.)
"""

from __future__ import annotations

import re
import time
from typing import Dict, List, Optional, Tuple


def seed_cluster(agent, n_service_jobs: int = 2,
                 task_output: str = "ui-harness-line",
                 timeout: float = 60.0) -> Dict:
    """Populate a dev agent with deterministic workloads and wait for
    them to run (the mirage/factories analog: known ids, known output).

    Returns {"jobs": [...], "allocs": [...]} of the seeded state.
    """
    import sys

    from nomad_tpu import mock

    jobs = []
    for i in range(n_service_jobs):
        job = mock.simple_job(id=f"ui-seed-{i}")
        tg = job.task_groups[0]
        tg.count = 1
        task = tg.tasks[0]
        task.driver = "raw_exec"
        task.config = {
            "command": sys.executable,
            "args": ["-S", "-c",
                     f"import time\nprint({task_output!r}, flush=True)\n"
                     "time.sleep(600)\n"],
        }
        agent.server.job_register(job)
        jobs.append(job)

    deadline = time.time() + timeout
    allocs: List = []
    while time.time() < deadline:
        snap = agent.server.state.snapshot()
        allocs = [a for j in jobs
                  for a in snap.allocs_by_job(j.namespace, j.id)
                  if a.client_status == "running"]
        if len(allocs) >= n_service_jobs:
            break
        time.sleep(0.2)
    if len(allocs) < n_service_jobs:
        raise AssertionError("harness cluster never became ready")
    return {"jobs": jobs, "allocs": allocs}


class UIClient:
    """Drives the SPA's API contract over real HTTP — the same calls,
    in the same order, consuming the same fields the views do."""

    def __init__(self, base_url: str, token: str = "") -> None:
        self.base = base_url.rstrip("/")
        self.token = token

    def get(self, path: str):
        import json
        import urllib.request

        req = urllib.request.Request(self.base + path)
        if self.token:
            req.add_header("X-Nomad-Token", self.token)
        with urllib.request.urlopen(req, timeout=30) as r:
            body = r.read()
            ctype = r.headers.get("Content-Type", "")
            if "json" in ctype:
                return json.loads(body)
            return body

    # -- the click path a user takes (jobs -> job -> alloc -> logs) ----

    def click_jobs(self) -> List[Dict]:
        return self.get("/v1/jobs")

    def click_job(self, job_id: str) -> Dict:
        """viewJobDetail's fetch fan-out."""
        return {
            "job": self.get(f"/v1/job/{job_id}"),
            "summary": self.get(f"/v1/job/{job_id}/summary"),
            "allocs": self.get(f"/v1/job/{job_id}/allocations"),
            "evals": self.get(f"/v1/job/{job_id}/evaluations"),
        }

    def click_alloc(self, alloc_id: str) -> Dict:
        return self.get(f"/v1/allocation/{alloc_id}")

    def click_fs(self, alloc_id: str, path: str = "/") -> List[Dict]:
        from urllib.parse import quote

        return self.get(
            f"/v1/client/fs/ls/{alloc_id}?path={quote(path)}")

    def click_file(self, alloc_id: str, path: str) -> Dict:
        from urllib.parse import quote

        q = quote(path)
        st = self.get(f"/v1/client/fs/stat/{alloc_id}?path={q}")
        return self.get(
            f"/v1/client/fs/readat/{alloc_id}?path={q}"
            f"&offset=0&limit={st['Size']}")

    def click_logs(self, alloc_id: str, task: str,
                   logtype: str = "stdout") -> str:
        from urllib.parse import quote

        out = self.get(
            f"/v1/client/fs/logs/{alloc_id}"
            f"?task={quote(task)}&type={logtype}")
        return out.get("Data", "")


_REGEX_KEYWORDS = ("return", "typeof", "case", "in", "of", "new",
                   "delete", "void", "instanceof")


def _ends_with_keyword(src: str, pos: int) -> bool:
    """Does the code before ``pos`` end with a keyword after which a
    regex literal may start?"""
    head = src[:pos].rstrip()
    return any(
        head.endswith(k)
        and (len(head) == len(k) or not head[-len(k) - 1].isalnum())
        for k in _REGEX_KEYWORDS)


def lint_js(src: str) -> List[str]:
    """Structural JS lint: balanced (){}[] and properly terminated
    strings/template literals/comments (with ``${}`` nesting).

    Not a parser — but an unbalanced bracket or unterminated template
    literal is exactly the error class that bricks the WHOLE SPA (one
    syntax error aborts the module), and no JavaScript runtime ships
    in this environment to catch it. Returns a list of problems.
    """
    problems: List[str] = []
    stack: List[tuple] = []          # (char, line)
    # modes: code | squote | dquote | template | linecomment | comment
    # | regex | regexclass
    mode = "code"
    template_depth: List[int] = []   # brace depth at each ${ entry
    line = 1
    last_sig = ""                    # last significant code char
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if c == "\n":
            line += 1
            if mode == "linecomment":
                mode = "code"
            elif mode in ("squote", "dquote"):
                problems.append(f"line {line - 1}: unterminated string")
                mode = "code"
            i += 1
            continue
        if mode == "linecomment":
            i += 1
            continue
        if mode == "comment":
            if c == "*" and nxt == "/":
                mode = "code"
                i += 2
                continue
            i += 1
            continue
        if mode in ("regex", "regexclass"):
            if c == "\\":
                i += 2
                continue
            if mode == "regex" and c == "[":
                mode = "regexclass"
            elif mode == "regexclass" and c == "]":
                mode = "regex"
            elif mode == "regex" and c == "/":
                mode = "code"
                last_sig = "/"
            i += 1
            continue
        if mode in ("squote", "dquote", "template"):
            if c == "\\":
                i += 2
                continue
            if mode == "squote" and c == "'":
                mode = "code"
            elif mode == "dquote" and c == '"':
                mode = "code"
            elif mode == "template":
                if c == "`":
                    mode = "code"
                elif c == "$" and nxt == "{":
                    template_depth.append(len(stack))
                    stack.append(("{", line))
                    mode = "code"
                    i += 2
                    continue
            i += 1
            continue
        # code mode
        if c == "/" and nxt == "/":
            mode = "linecomment"
            i += 2
            continue
        if c == "/" and nxt == "*":
            mode = "comment"
            i += 2
            continue
        if c == "/":
            # regex vs division: a regex can only FOLLOW an operator,
            # opener, separator, or keyword boundary (the standard
            # restricted-production heuristic)
            if last_sig == "" or last_sig in "(,=:[!&|?{};~^%*+-<>" \
                    or _ends_with_keyword(src, i):
                mode = "regex"
                i += 1
                continue
            last_sig = c
            i += 1
            continue
        if c == "'":
            mode = "squote"
        elif c == '"':
            mode = "dquote"
        elif c == "`":
            mode = "template"
        elif c in "({[":
            stack.append((c, line))
        elif c in ")}]":
            want = {")": "(", "}": "{", "]": "["}[c]
            if not stack or stack[-1][0] != want:
                problems.append(f"line {line}: unmatched '{c}'")
            else:
                stack.pop()
                if c == "}" and template_depth and \
                        len(stack) == template_depth[-1]:
                    template_depth.pop()
                    mode = "template"
        if not c.isspace():
            last_sig = c
        i += 1
    if mode == "template":
        problems.append("unterminated template literal at EOF")
    if mode == "comment":
        problems.append("unterminated block comment at EOF")
    for ch, ln in stack:
        problems.append(f"line {ln}: unclosed '{ch}'")
    return problems


#: SPA-referenced paths the static check cannot resolve: websocket
#: upgrades with dynamic construction, and templates whose FIRST
#: dynamic segment expands to literal route words (deployment
#: promote/pause/fail verbs)
_NON_GET = {"/v1/client/allocation/_/exec", "/v1/deployment/_"}


def referenced_api_paths(app_js: str) -> List[str]:
    """Every /v1 path literal the SPA references (the contract the
    route table must serve). Template expressions normalize to a
    placeholder segment."""
    paths = set()
    for m in re.finditer(r"/v1/[A-Za-z0-9_${}()./-]*", app_js):
        p = m.group(0)
        p = re.sub(r"\$\{[^}]*\}", "_", p)
        p = p.split("?")[0].rstrip("/.")
        if p and p != "/v1":
            paths.add(p)
    return sorted(paths)


def route_table_patterns(http_agent) -> List:
    return [(method, pattern) for method, pattern, _fn
            in http_agent._routes]


def extract_view_contract(app_js: str) -> Dict:
    """The machine-checked route -> endpoint -> field manifest embedded
    in app.js (between __VIEW_CONTRACT_START__/__VIEW_CONTRACT_END__).
    Raises if missing or not strict JSON — the contract IS the test
    surface, so a parse failure must fail loudly."""
    import json

    m = re.search(r"__VIEW_CONTRACT_START__\n(.*?)\n__VIEW_CONTRACT_END__",
                  app_js, re.S)
    if m is None:
        raise AssertionError("app.js has no __VIEW_CONTRACT__ block")
    return json.loads(m.group(1))


def function_field_accesses(app_js: str) -> Dict[str, List[str]]:
    """PascalCase member accesses per top-level function.

    API response fields are PascalCase while JS locals/methods are
    camelCase, so `.Foo` inside a view function is, by construction,
    a read of an API field — the set the view CONSUMES. The harness
    requires every one of them to be declared in the view contract,
    which in turn is walked against the live API: a view can therefore
    not read a field the API does not return without a test failing."""
    out: Dict[str, List[str]] = {}
    parts = re.split(r"(?=^(?:async )?function \w+)", app_js, flags=re.M)
    for p in parts:
        m = re.match(r"(?:async )?function (\w+)", p)
        if m is None:
            continue
        fields = sorted(set(re.findall(r"\.([A-Z][A-Za-z0-9]*)\b", p)))
        if fields:
            out[m.group(1)] = fields
    return out


def _path_field_names(paths, helpers=None) -> set:
    """Field NAMES a set of walk paths mention (expanding @helper
    refs) — the one segment parser both declaration checks share."""
    helpers = helpers or {}
    names: set = set()
    for path in paths:
        if path.startswith("@"):
            names |= _path_field_names(helpers.get(path[1:], ()), helpers)
            continue
        for seg in path.lstrip("?").split("."):
            seg = seg.replace("[]", "")
            if seg and seg != "*":
                names.add(seg)
    return names


def _contract_fields(contract: Dict, view: str) -> set:
    """Flat set of field NAMES a view's walk paths (plus its helpers')
    mention — the declared consumption set."""
    helpers = contract.get("helpers", {})
    spec = contract.get(view, {})
    names: set = set()
    for paths in spec.get("walk", {}).values():
        names |= _path_field_names(paths, helpers)
    return names


def resolve_path(data, path: str):
    """Walk one contract path; returns (ok, reason).

    DSL: "." descends dicts; a leading "[]" means the response is a
    list (first element is checked); "KEY[]" means KEY holds a list;
    "*" fans out over every dict value; a "?" prefix marks the field
    as omittable (absence passes, a non-dict parent still fails)."""
    optional = path.startswith("?")
    segs = path.lstrip("?").split(".")

    def walk(cur, i) -> Tuple[bool, str]:
        if i == len(segs):
            return True, ""
        seg = segs[i]
        if seg == "[]" or seg == "":
            if not isinstance(cur, list):
                return False, f"expected list at {'.'.join(segs[:i])!r}"
            if not cur:
                return optional, "empty list"
            return walk(cur[0], i + 1)
        if seg == "*":
            if not isinstance(cur, dict):
                return False, f"expected dict at {'.'.join(segs[:i])!r}"
            if not cur:
                return optional, "empty dict"
            for v in cur.values():
                ok, why = walk(v, i + 1)
                if not ok:
                    return ok, why
            return True, ""
        is_list = seg.endswith("[]")
        key = seg[:-2] if is_list else seg
        if not isinstance(cur, dict):
            return False, f"expected object before {key!r}"
        if key not in cur:
            return optional, f"missing field {key!r}"
        nxt = cur[key]
        if is_list:
            if nxt is None or not isinstance(nxt, list):
                return optional, f"{key!r} is not a list"
            if not nxt:
                return optional, f"{key!r} empty"
            nxt = nxt[0]
        elif nxt is None and i + 1 < len(segs):
            return optional, f"{key!r} is null"
        return walk(nxt, i + 1)

    return walk(data, 0)


def walk_view_contract(ui: "UIClient", contract: Dict,
                       params: Dict[str, str]) -> List[str]:
    """Fetch every view's endpoints against the REAL API and resolve
    every declared field path. Returns failures (empty = pass).

    ``params`` substitutes the {job}/{node}/{alloc}/... placeholders
    with ids from the seeded cluster; a view whose placeholder has no
    param is reported as unexercised (a missing seed is a harness bug,
    not a pass)."""
    from urllib.parse import quote

    helpers = contract.get("helpers", {})
    failures: List[str] = []
    for view, spec in contract.items():
        if view == "helpers":
            continue
        for key, path in spec.get("endpoints", {}).items():
            tmpl = path
            missing_param = None
            for ph in re.findall(r"\{(\w+)\}", path):
                if ph not in params:
                    missing_param = ph
                    break
                tmpl = tmpl.replace("{" + ph + "}",
                                    quote(str(params[ph]), safe=""))
            if missing_param is not None:
                failures.append(
                    f"{view}.{key}: no seed param {missing_param!r}")
                continue
            try:
                resp = ui.get(tmpl)
            except Exception as e:               # noqa: BLE001
                failures.append(f"{view}.{key}: GET {tmpl} -> {e}")
                continue
            paths = list(spec.get("walk", {}).get(key, ()))
            expanded: List[str] = []
            for p in paths:
                if p.startswith("@"):
                    expanded.extend(helpers.get(p[1:], ()))
                else:
                    expanded.append(p)
            for p in expanded:
                ok, why = resolve_path(resp, p)
                if not ok:
                    failures.append(f"{view}.{key}: {p} ({why})")
    return failures


def undeclared_field_reads(app_js: str) -> Dict[str, List[str]]:
    """view/helper function -> PascalCase reads NOT declared in its
    contract entry (merged with its "uses" helpers'). Non-empty means
    a renderer consumes an API field the walk never checks — the gap
    this harness exists to close."""
    contract = extract_view_contract(app_js)
    accesses = function_field_accesses(app_js)
    helpers = contract.get("helpers", {})

    out: Dict[str, List[str]] = {}
    for fn, fields in accesses.items():
        if fn in contract:
            allowed = _contract_fields(contract, fn)
            for h in contract[fn].get("uses", ()):
                allowed |= _path_field_names(helpers.get(h, ()), helpers)
        elif fn in helpers:
            allowed = _path_field_names(helpers.get(fn, ()), helpers)
        else:
            continue   # non-view plumbing (actions, router, streams)
        extra = [f for f in fields if f not in allowed]
        if extra:
            out[fn] = extra
    return out


def unrouted_paths(app_js: str, http_agent,
                   extra_ignored: Optional[set] = None) -> List[str]:
    """SPA-referenced paths with no registered route under ANY method —
    the breakage class this harness exists to catch (a renamed
    endpoint silently 404s in the UI)."""
    ignored = set(_NON_GET) | (extra_ignored or set())
    patterns = [p for _m, p in route_table_patterns(http_agent)]
    missing = []
    for path in referenced_api_paths(app_js):
        if any(path.startswith(ig) for ig in ignored):
            continue
        probe = path.replace("/_", "/xxxx")
        if not any(p.fullmatch(probe) or p.fullmatch(path)
                   for p in patterns):
            missing.append(path)
    return missing
