"""Scheduler interface and factory registry.

Reference behavior: scheduler/scheduler.go -- ``BuiltinSchedulers``
(:24-38), ``NewScheduler`` factory (:42-61), the ``State`` (:67) and
``Planner`` (:105) interfaces that decouple the scheduler from the
server. The TPU build registers the same four builtin types plus
``xla-binpack`` (the BASELINE.json north star): the generic scheduler
*is* the XLA path, so ``xla-binpack`` is an alias that forces the
batched kernel; the host fallback is available for differential tests.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Protocol, Tuple

from nomad_tpu.structs import consts
from nomad_tpu.structs.eval_plan import Evaluation, Plan, PlanResult


class SchedulerState(Protocol):
    """Immutable snapshot the scheduler reads (scheduler.go:67-103)."""

    def nodes(self) -> List: ...
    def node_by_id(self, node_id: str): ...
    def job_by_id(self, namespace: str, job_id: str): ...
    def allocs_by_job(self, namespace: str, job_id: str, anyCreateIndex: bool = True) -> List: ...
    def allocs_by_node(self, node_id: str) -> List: ...
    def latest_deployment_by_job_id(self, namespace: str, job_id: str): ...
    def latest_index(self) -> int: ...


class Planner(Protocol):
    """How the scheduler submits work (scheduler.go:105-141)."""

    def submit_plan(self, plan: Plan) -> Tuple[Optional[PlanResult], Optional[SchedulerState]]: ...
    def update_eval(self, eval: Evaluation) -> None: ...
    def create_eval(self, eval: Evaluation) -> None: ...
    def reblock_eval(self, eval: Evaluation) -> None: ...
    def serve_rs_meet_minimum_version(self) -> bool: ...


class SetStatusError(Exception):
    """Terminal scheduling failure carrying the eval status to set
    (reference scheduler/util.go SetStatusError)."""

    def __init__(self, status: str, desc: str) -> None:
        super().__init__(desc)
        self.eval_status = status
        self.desc = desc


class Scheduler:
    """Base interface (scheduler.go:51-61)."""

    def process(self, evaluation: Evaluation) -> None:
        raise NotImplementedError


SchedulerFactory = Callable[..., Scheduler]

BUILTIN_SCHEDULERS: Dict[str, SchedulerFactory] = {}


def register_scheduler(name: str, factory: SchedulerFactory) -> None:
    BUILTIN_SCHEDULERS[name] = factory


def new_scheduler(name: str, state: SchedulerState, planner: Planner, **kw) -> Scheduler:
    """scheduler.go:42 NewScheduler."""
    factory = BUILTIN_SCHEDULERS.get(name)
    if factory is None:
        raise ValueError(f"unknown scheduler '{name}'")
    return factory(state=state, planner=planner, **kw)


def retry_max(limit: int, fn: Callable[[], Tuple[bool, Optional[Exception]]],
              reset: Optional[Callable[[], bool]] = None) -> None:
    """Run fn up to `limit` times, resetting attempts on progress
    (reference scheduler/util.go:391 retryMax)."""
    attempts = 0
    while attempts < limit:
        done, err = fn()
        if err is not None:
            raise err
        if done:
            return
        if reset is not None and reset():
            attempts = 0
        else:
            attempts += 1
    raise SetStatusError(
        consts.EVAL_STATUS_FAILED,
        f"maximum attempts reached ({limit})",
    )


def progress_made(result: Optional[PlanResult]) -> bool:
    """scheduler/util.go progressMade."""
    return result is not None and (
        bool(result.node_update)
        or bool(result.node_allocation)
        or result.deployment is not None
        or bool(result.deployment_updates)
    )
