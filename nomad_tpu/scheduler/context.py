"""Evaluation context: shared caches, proposed-alloc algebra, metrics.

Reference behavior: scheduler/context.go -- ``EvalContext`` (:127),
``ProposedAllocs`` (:173: existing - stopped/preempted + planned per
node), ``EvalEligibility`` class-level feasibility memoization (:254).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from nomad_tpu.structs.alloc import AllocMetric, Allocation, remove_allocs
from nomad_tpu.structs.eval_plan import Plan


# EvalEligibility tri-state (context.go:243-251)
ELIGIBILITY_UNKNOWN = 0
ELIGIBLE = 1
INELIGIBLE = 2


class EvalEligibility:
    """Tracks feasibility per computed node class so whole classes are
    checked once per eval (context.go:254; feasible.go:1050)."""

    def __init__(self) -> None:
        self.job: Dict[str, int] = {}           # computed class -> tri-state
        self.tgs: Dict[str, Dict[str, int]] = {}  # tg -> class -> tri-state
        self._has_escaped = False               # constraint not class-checkable
        self.quota_reached = ""

    def set_job(self, job) -> None:
        """Determine if the job + tgs contain 'escaping' constraints --
        ones on unique (per-node) properties that the class cache cannot
        memoize (context.go SetJob)."""
        self._has_escaped = _constraints_escape(job.constraints)
        for tg in job.task_groups:
            esc = _constraints_escape(tg.constraints)
            for task in tg.tasks:
                esc = esc or _constraints_escape(task.constraints)
            if esc:
                self._has_escaped = True

    def has_escaped(self) -> bool:
        return self._has_escaped

    def get_classes(self) -> Dict[str, bool]:
        """Merged class eligibility for blocked evals (context.go GetClasses)."""
        out: Dict[str, bool] = {}
        for cls, st in self.job.items():
            if st == INELIGIBLE:
                out[cls] = False
            elif st == ELIGIBLE:
                out[cls] = True
        for tg_classes in self.tgs.values():
            for cls, st in tg_classes.items():
                if st == INELIGIBLE and cls not in out:
                    out[cls] = False
                elif st == ELIGIBLE:
                    out[cls] = True
        return out

    def job_status(self, cls: str) -> int:
        if not cls:
            return ELIGIBILITY_UNKNOWN
        return self.job.get(cls, ELIGIBILITY_UNKNOWN)

    def set_job_eligibility(self, eligible: bool, cls: str) -> None:
        if cls:
            self.job[cls] = ELIGIBLE if eligible else INELIGIBLE

    def tg_status(self, tg: str, cls: str) -> int:
        if not cls:
            return ELIGIBILITY_UNKNOWN
        return self.tgs.get(tg, {}).get(cls, ELIGIBILITY_UNKNOWN)

    def set_tg_eligibility(self, eligible: bool, tg: str, cls: str) -> None:
        if cls:
            self.tgs.setdefault(tg, {})[cls] = ELIGIBLE if eligible else INELIGIBLE


def _constraints_escape(constraints) -> bool:
    for c in constraints:
        for target in (c.ltarget, c.rtarget):
            if "${node.unique." in target or "${attr.unique." in target or "${meta.unique." in target:
                return True
    return False


class PortCollisionEvent:
    """Operator-visible scheduler-state inconsistency (context.go:81;
    emitted from binpack when the NetworkIndex collides on node state,
    rank.go:213-236)."""

    def __init__(self, reason: str, node=None, allocations=None) -> None:
        self.reason = reason
        self.node = node
        self.allocations = allocations or []


class EvalContext:
    """Per-evaluation context (context.go:127)."""

    def __init__(self, state, plan: Plan, logger=None, events_cb=None,
                 kernel_launch=None) -> None:
        self.state = state
        self.plan = plan
        self.logger = logger
        self.events_cb = events_cb
        self.eligibility = EvalEligibility()
        self.metrics_obj = AllocMetric()
        # per-eval decorrelation seed for stochastic dynamic-port
        # assignment (network.go:598); None = precise selection
        self.port_seed: Optional[int] = None
        # the placement-kernel dispatch point: defaults to the direct
        # candidate-set/full dispatcher; a batching worker injects a
        # LaunchCoalescer so concurrent evals share one joint launch
        # (parallel/coalesce.py)
        if kernel_launch is None:
            from nomad_tpu.ops.kernel import default_kernel_launch

            kernel_launch = default_kernel_launch
        self.kernel_launch = kernel_launch

    def metrics(self) -> AllocMetric:
        return self.metrics_obj

    def reset_metrics(self) -> None:
        self.metrics_obj = AllocMetric()

    def send_event(self, event) -> None:
        if self.events_cb is not None:
            self.events_cb(event)

    def proposed_allocs(self, node_id: str) -> List[Allocation]:
        """Allocs expected on the node after this plan applies
        (context.go:173): existing non-terminal, minus plan stops and
        preemptions, plus plan placements."""
        existing = [
            a for a in self.state.allocs_by_node(node_id)
            if not a.terminal_status()
        ]
        stopping = self.plan.node_update.get(node_id, [])
        preempting = self.plan.node_preemptions.get(node_id, [])
        proposed = remove_allocs(existing, list(stopping) + list(preempting))
        # index by ID so an in-place update (same ID in state and in
        # plan.node_allocation) overrides instead of double counting
        # (context.go:193-207)
        by_id = {a.id: a for a in proposed}
        for a in self.plan.node_allocation.get(node_id, []):
            by_id[a.id] = a
        return list(by_id.values())
