"""Scheduler package: the capability layer around the TPU kernel.

Reference: scheduler/ (~40k LoC Go). The iterator hot loop lives on
device (nomad_tpu.ops.kernel); this package provides everything around
it with the reference's interfaces: the Scheduler factory registry
(scheduler.go:24-61), the reconciler (reconcile.go), the placement
stacks (stack.go), host-side feasibility/eligibility caching
(feasible.go), preemption, and the test harness (testing.go).
"""

from nomad_tpu.scheduler.scheduler import (  # noqa: F401
    BUILTIN_SCHEDULERS,
    Planner,
    Scheduler,
    SchedulerState,
    SetStatusError,
    new_scheduler,
)
from nomad_tpu.scheduler.generic import GenericScheduler  # noqa: F401
from nomad_tpu.scheduler.system import SystemScheduler  # noqa: F401
