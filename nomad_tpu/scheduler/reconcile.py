"""The allocation reconciler: desired state vs existing allocations.

Reference behavior: scheduler/reconcile.go (allocReconciler.Compute :204,
computeGroup :387) and reconcile_util.go (allocSet algebra,
filterByTainted :219, filterByRescheduleable :356, allocNameIndex :591).
Pure host-side set algebra -- not a hot path; placements it emits are
batched into the TPU kernel by the caller.

Round-1 scope notes (each tracked for later rounds):
- disconnect/reconnect: disconnecting allocs become 'unknown' updates
  with timeout follow-up evals and lost handling; the score-based
  keep-reconnecting-vs-replacement tiebreak (computeStopByReconnecting)
  prefers the replacement unless the reconnecting alloc is same-version.
- multiregion: regions beyond the strategy's first max_parallel wave
  create their deployment in the 'blocked' state and make no rollout
  progress until an earlier region's success unblocks them
  (structs.go:4133; the deployment watcher performs the cross-region
  kick over the federation layer).
"""

from __future__ import annotations

import threading as _threading
import time as _time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from nomad_tpu.structs import consts
from nomad_tpu.structs.alloc import Allocation
from nomad_tpu.structs.eval_plan import Deployment, DeploymentState, Evaluation, new_deployment

# Status descriptions (reference reconcile.go:16-60 alloc* constants)
ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_RECONNECTED = "alloc not needed due to reconnect"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_UPDATING = "alloc is being updated due to job update"
ALLOC_LOST = "alloc lost since its node is down"
ALLOC_UNKNOWN = "alloc is unknown since its node is disconnected"
ALLOC_IN_PLACE = "alloc updating in-place"
ALLOC_NODE_TAINTED = "alloc not needed as node is tainted"
ALLOC_RESCHEDULED = "alloc was rescheduled because it failed"

# batched reschedule window (reconcile.go:46 rescheduleWindowSize)
RESCHEDULE_WINDOW_S = 1.0

AllocSet = Dict[str, Allocation]


# ---------------------------------------------------------------------------
# allocSet algebra (reconcile_util.go)
# ---------------------------------------------------------------------------


def alloc_set(allocs) -> AllocSet:
    return {a.id: a for a in allocs}


def union(*sets: AllocSet) -> AllocSet:
    out: AllocSet = {}
    for s in sets:
        out.update(s)
    return out


def difference(a: AllocSet, *others: AllocSet) -> AllocSet:
    drop = set()
    for o in others:
        drop |= o.keys()
    return {k: v for k, v in a.items() if k not in drop}


def from_keys(a: AllocSet, keys) -> AllocSet:
    return {k: a[k] for k in keys if k in a}


def filter_by_terminal(a: AllocSet) -> AllocSet:
    return {k: v for k, v in a.items() if not v.terminal_status()}


def name_order(a: AllocSet) -> List[Allocation]:
    return sorted(a.values(), key=lambda x: (x.index(), x.id))


def new_alloc_matrix(job, allocs: List[Allocation]) -> Dict[str, AllocSet]:
    """allocMatrix: group name -> allocSet (reconcile_util.go:106)."""
    m: Dict[str, AllocSet] = {}
    for a in allocs:
        m.setdefault(a.task_group, {})[a.id] = a
    if job is not None and not job.stopped():
        for tg in job.task_groups:
            m.setdefault(tg.name, {})
    return m


def filter_by_tainted(
    a: AllocSet, tainted_nodes: Dict[str, object], supports_disconnected: bool,
    now: float,
) -> Tuple[AllocSet, AllocSet, AllocSet, AllocSet, AllocSet, AllocSet]:
    """(untainted, migrate, lost, disconnecting, reconnecting, ignore)
    -- reconcile_util.go:219."""
    untainted: AllocSet = {}
    migrate: AllocSet = {}
    lost: AllocSet = {}
    disconnecting: AllocSet = {}
    reconnecting: AllocSet = {}
    ignore: AllocSet = {}

    for aid, alloc in a.items():
        supports = supports_disconnected and _alloc_supports_disconnect(alloc)
        reconnected = False
        expired = False
        if supports and alloc.client_status in (
            consts.ALLOC_CLIENT_UNKNOWN,
            consts.ALLOC_CLIENT_RUNNING,
            consts.ALLOC_CLIENT_FAILED,
        ):
            reconnected, expired = _alloc_reconnected(alloc, now)

        if supports and reconnected and alloc.desired_status == consts.ALLOC_DESIRED_RUN \
                and alloc.client_status == consts.ALLOC_CLIENT_FAILED:
            reconnecting[aid] = alloc
            continue

        node = tainted_nodes.get(alloc.node_id)
        node_is_tainted = alloc.node_id in tainted_nodes
        if node is not None:
            if node.status == consts.NODE_STATUS_DISCONNECTED:
                if supports:
                    if alloc.client_status == consts.ALLOC_CLIENT_RUNNING:
                        disconnecting[aid] = alloc
                        continue
                    if alloc.client_status == consts.ALLOC_CLIENT_PENDING:
                        lost[aid] = alloc
                        continue
                else:
                    lost[aid] = alloc
                    continue
            elif node.status == consts.NODE_STATUS_READY and reconnected:
                if expired:
                    lost[aid] = alloc
                else:
                    reconnecting[aid] = alloc
                continue

        if alloc.terminal_status() and not reconnected:
            untainted[aid] = alloc
            continue
        if alloc.desired_transition.should_migrate():
            migrate[aid] = alloc
            continue
        if supports and _alloc_expired(alloc, now):
            lost[aid] = alloc
            continue
        if supports and alloc.client_status == consts.ALLOC_CLIENT_UNKNOWN \
                and alloc.desired_status == consts.ALLOC_DESIRED_RUN:
            ignore[aid] = alloc
            continue
        if supports and reconnected and alloc.client_status == consts.ALLOC_CLIENT_FAILED \
                and alloc.desired_status == consts.ALLOC_DESIRED_STOP:
            ignore[aid] = alloc
            continue
        if not node_is_tainted:
            if reconnected:
                if expired:
                    lost[aid] = alloc
                else:
                    reconnecting[aid] = alloc
                continue
            untainted[aid] = alloc
            continue
        if node is None or node.terminal_status():
            lost[aid] = alloc
        else:
            untainted[aid] = alloc

    return untainted, migrate, lost, disconnecting, reconnecting, ignore


def _alloc_supports_disconnect(alloc) -> bool:
    job = alloc.job
    if job is None:
        return False
    tg = job.lookup_task_group(alloc.task_group)
    return tg is not None and tg.max_client_disconnect_s is not None


def _alloc_reconnected(alloc, now: float) -> Tuple[bool, bool]:
    """structs.go Allocation.Reconnected: has a reconnect event and
    whether the disconnect window expired."""
    last_disconnect = None
    last_reconnect = None
    for ts in alloc.task_states.values():
        for e in ts.events:
            if e.type == "Disconnected":
                last_disconnect = max(last_disconnect or 0, e.time_ns)
            if e.type == "Reconnected":
                last_reconnect = max(last_reconnect or 0, e.time_ns)
    if last_reconnect is None:
        return False, False
    reconnected = last_disconnect is None or last_reconnect >= last_disconnect
    return reconnected, _alloc_expired(alloc, now)


def _alloc_expired(alloc, now: float) -> bool:
    if alloc.client_status != consts.ALLOC_CLIENT_UNKNOWN:
        return False
    job = alloc.job
    if job is None:
        return False
    tg = job.lookup_task_group(alloc.task_group)
    if tg is None or tg.max_client_disconnect_s is None:
        return False
    last_unknown = None
    for ts in alloc.task_states.values():
        for e in ts.events:
            if e.type == "Disconnected":
                last_unknown = max(last_unknown or 0, e.time_ns)
    if last_unknown is None:
        return False
    return (last_unknown / 1e9) + tg.max_client_disconnect_s < now


# ---------------------------------------------------------------------------
# per-(job, tg) reconcile invariants (the PR5 scaffold-cache idea applied
# to the reconciler): everything below depends only on the job SPEC, so
# re-deriving it per alloc per eval (task-group scans, reschedule-policy
# copies) is pure reconcile-slice overhead. Identity-keyed like
# scheduler/scaffold.py — store job rows are immutable and shared.
# ---------------------------------------------------------------------------


class _TGReconcileInfo:
    __slots__ = ("supports_disconnect", "max_client_disconnect_s",
                 "stop_after_client_disconnect_s", "policy",
                 "policy_enabled")

    def __init__(self, job, tg_name: str) -> None:
        tg = job.lookup_task_group(tg_name)
        self.supports_disconnect = (
            tg is not None and tg.max_client_disconnect_s is not None)
        self.max_client_disconnect_s = (
            tg.max_client_disconnect_s if tg is not None else None)
        self.stop_after_client_disconnect_s = (
            tg.stop_after_client_disconnect_s if tg is not None else None)
        # reschedule_policy_for returns a fresh DEFAULT copy per call;
        # the reconciler only READS the policy, so one shared instance
        # per (job, tg) is sound
        policy = job.reschedule_policy_for(tg_name)
        self.policy = policy
        self.policy_enabled = policy is not None and policy.enabled()


_RECON_INFO_MAX = 2048
_RECON_INFO: "OrderedDict[Tuple[int, str], Tuple[object, _TGReconcileInfo]]" \
    = OrderedDict()
_RECON_INFO_LOCK = _threading.Lock()


def reconcile_info_for(job, tg_name: str) -> _TGReconcileInfo:
    """The (job, tg) reconcile invariants, memoized per job OBJECT
    (entries pin the job and re-check identity, so a recycled ``id()``
    can never alias a dead job version)."""
    key = (id(job), tg_name)
    ent = _RECON_INFO.get(key)
    if ent is not None and ent[0] is job:
        return ent[1]
    built = _TGReconcileInfo(job, tg_name)
    with _RECON_INFO_LOCK:
        ent = _RECON_INFO.get(key)
        if ent is not None and ent[0] is job:
            return ent[1]
        _RECON_INFO[key] = (job, built)
        _RECON_INFO.move_to_end(key)
        while len(_RECON_INFO) > _RECON_INFO_MAX:
            _RECON_INFO.popitem(last=False)
    return built


def should_filter(alloc, is_batch: bool) -> Tuple[bool, bool]:
    """(untainted, ignore) -- reconcile_util.go:415 shouldFilter."""
    if is_batch:
        if alloc.desired_status == consts.ALLOC_DESIRED_STOP:
            if alloc.ran_successfully():
                return True, False
            return False, True
        if alloc.desired_status == consts.ALLOC_DESIRED_EVICT:
            return False, True
        if alloc.client_status != consts.ALLOC_CLIENT_FAILED:
            return True, False
        return False, False

    if alloc.desired_status in (consts.ALLOC_DESIRED_STOP, consts.ALLOC_DESIRED_EVICT):
        return False, True
    if alloc.client_status in (consts.ALLOC_CLIENT_COMPLETE, consts.ALLOC_CLIENT_LOST):
        return False, True
    return False, False


@dataclass
class DelayedRescheduleInfo:
    alloc_id: str
    alloc: Allocation
    reschedule_time_s: float


def filter_by_rescheduleable(
    a: AllocSet, is_batch: bool, is_disconnecting: bool, now: float,
    eval_id: str, deployment: Optional[Deployment],
) -> Tuple[AllocSet, AllocSet, List[DelayedRescheduleInfo]]:
    """reconcile_util.go:356."""
    untainted: AllocSet = {}
    reschedule_now: AllocSet = {}
    reschedule_later: List[DelayedRescheduleInfo] = []

    for aid, alloc in a.items():
        if is_disconnecting and alloc.client_status == consts.ALLOC_CLIENT_UNKNOWN:
            continue
        if alloc.next_allocation and alloc.terminal_status():
            continue
        is_untainted, ignore = should_filter(alloc, is_batch)
        if is_untainted and not is_disconnecting:
            untainted[aid] = alloc
        if is_untainted or ignore:
            continue

        eligible_now, eligible_later, resched_time = _update_by_reschedulable(
            alloc, now, eval_id, deployment, is_disconnecting
        )
        if not is_disconnecting and not eligible_now:
            untainted[aid] = alloc
            if eligible_later:
                reschedule_later.append(DelayedRescheduleInfo(aid, alloc, resched_time))
        else:
            reschedule_now[aid] = alloc
    return untainted, reschedule_now, reschedule_later


def _update_by_reschedulable(
    alloc, now: float, eval_id: str, d: Optional[Deployment], is_disconnecting: bool
) -> Tuple[bool, bool, float]:
    """reconcile_util.go:457 updateByReschedulable."""
    if d is not None and alloc.deployment_id == d.id and d.active() \
            and not alloc.desired_transition.reschedule:
        return False, False, 0.0
    if alloc.desired_transition.should_force_reschedule():
        return True, False, 0.0

    job = alloc.job
    policy = job.reschedule_policy_for(alloc.task_group) if job else None
    if policy is None or not policy.enabled():
        return False, False, 0.0
    fail_time = now if is_disconnecting else (alloc.modify_time_ns / 1e9)
    if not alloc.reschedule_eligible(policy, fail_time):
        return False, False, 0.0
    num_prior = len(alloc.reschedule_tracker.events) if alloc.reschedule_tracker else 0
    resched_time = fail_time + alloc._next_delay(policy, num_prior)
    eligible = alloc.client_status == consts.ALLOC_CLIENT_FAILED or is_disconnecting
    if not eligible:
        return False, False, 0.0
    if alloc.follow_up_eval_id == eval_id or (resched_time - now) <= RESCHEDULE_WINDOW_S:
        return True, False, resched_time
    if not alloc.follow_up_eval_id:
        return False, True, resched_time
    return False, False, 0.0


# ---------------------------------------------------------------------------
# fused group classification (the reconcile fast path)
#
# The legacy pipeline walks every alloc of a group FOUR times
# (filter_by_tainted -> should_filter -> filter_by_rescheduleable ->
# _update_by_reschedulable) and rebuilds an AllocSet dict per stage.
# ``classify_group`` computes each alloc's full disposition in ONE pass
# over one stable table, using the memoized per-(job, tg) invariants —
# bit-identical to the legacy composition (property-tested in
# tests/test_reconcile_fast.py, including result-list ORDER, which the
# dict insertion orders here reproduce exactly).
# ---------------------------------------------------------------------------


@dataclass
class GroupClassification:
    """One group's alloc dispositions, computed in a single pass."""

    untainted: AllocSet
    migrate: AllocSet
    lost: AllocSet
    disconnecting: AllocSet
    reconnecting: AllocSet
    ignore: int
    reschedule_now: AllocSet
    reschedule_later: List[DelayedRescheduleInfo]


def _alloc_expired_info(alloc, now: float, info) -> bool:
    """``_alloc_expired`` with the (job, tg) lookup memoized away."""
    if alloc.client_status != consts.ALLOC_CLIENT_UNKNOWN:
        return False
    if info is None or info.max_client_disconnect_s is None:
        return False
    last_unknown = None
    for ts in alloc.task_states.values():
        for e in ts.events:
            if e.type == "Disconnected":
                last_unknown = max(last_unknown or 0, e.time_ns)
    if last_unknown is None:
        return False
    return (last_unknown / 1e9) + info.max_client_disconnect_s < now


def _alloc_reconnected_info(alloc, now: float, info) -> Tuple[bool, bool]:
    """``_alloc_reconnected`` with the memoized invariants."""
    last_disconnect = None
    last_reconnect = None
    for ts in alloc.task_states.values():
        for e in ts.events:
            if e.type == "Disconnected":
                last_disconnect = max(last_disconnect or 0, e.time_ns)
            if e.type == "Reconnected":
                last_reconnect = max(last_reconnect or 0, e.time_ns)
    if last_reconnect is None:
        return False, False
    reconnected = last_disconnect is None or last_reconnect >= last_disconnect
    return reconnected, _alloc_expired_info(alloc, now, info)


def _update_by_reschedulable_info(
    alloc, now: float, eval_id: str, d: Optional[Deployment],
    d_active: bool, is_disconnecting: bool, info,
) -> Tuple[bool, bool, float]:
    """``_update_by_reschedulable`` riding the memoized policy."""
    if d is not None and alloc.deployment_id == d.id and d_active \
            and not alloc.desired_transition.reschedule:
        return False, False, 0.0
    if alloc.desired_transition.force_reschedule:
        return True, False, 0.0
    if not is_disconnecting \
            and alloc.client_status != consts.ALLOC_CLIENT_FAILED:
        # every remaining branch of the reference ends at the
        # ``eligible`` check, which needs FAILED-or-disconnecting —
        # the policy/eligibility/delay walk below cannot change this
        # alloc's (False, False, 0.0) outcome, and it is the entire
        # per-alloc cost of the steady RUNNING population
        return False, False, 0.0
    if info is None or not info.policy_enabled:
        return False, False, 0.0
    policy = info.policy
    fail_time = now if is_disconnecting else (alloc.modify_time_ns / 1e9)
    if not alloc.reschedule_eligible(policy, fail_time):
        return False, False, 0.0
    num_prior = len(alloc.reschedule_tracker.events) if alloc.reschedule_tracker else 0
    resched_time = fail_time + alloc._next_delay(policy, num_prior)
    eligible = alloc.client_status == consts.ALLOC_CLIENT_FAILED or is_disconnecting
    if not eligible:
        return False, False, 0.0
    if alloc.follow_up_eval_id == eval_id or (resched_time - now) <= RESCHEDULE_WINDOW_S:
        return True, False, resched_time
    if not alloc.follow_up_eval_id:
        return False, True, resched_time
    return False, False, 0.0


def classify_group(
    a: AllocSet, tainted_nodes: Dict[str, object], supports_disconnected: bool,
    now: float, is_batch: bool, eval_id: str, deployment: Optional[Deployment],
) -> GroupClassification:
    """The fused single pass: filter_by_tainted + both
    filter_by_rescheduleable calls + their union, with one disposition
    computation per alloc."""
    untainted: AllocSet = {}
    migrate: AllocSet = {}
    lost: AllocSet = {}
    disconnecting: AllocSet = {}
    reconnecting: AllocSet = {}
    n_ignore = 0
    # reschedule_now's legacy order: the untainted-pass entries first
    # (in untainted order), then the disconnecting-pass entries (the
    # union(reschedule_now, resched_disc) semantics)
    resched_unt: AllocSet = {}
    resched_disc: AllocSet = {}
    later: List[DelayedRescheduleInfo] = []

    d_active = deployment is not None and deployment.active()
    any_tainted = bool(tainted_nodes)
    # per-call memo over the module cache: within one group the allocs
    # share a handful of job objects (task_group is constant — the
    # matrix groups by it), so the common lookup is one dict hit
    info_cache: Dict[int, object] = {}

    for aid, alloc in a.items():
        job = alloc.job
        if job is None:
            info = None
        else:
            jkey = id(job)
            info = info_cache.get(jkey)
            if info is None:
                info = info_cache[jkey] = reconcile_info_for(
                    job, alloc.task_group)
        supports = supports_disconnected and info is not None \
            and info.supports_disconnect

        # ---- the filter_by_tainted disposition, verbatim ----
        reconnected = False
        expired = False
        if supports and alloc.client_status in (
            consts.ALLOC_CLIENT_UNKNOWN,
            consts.ALLOC_CLIENT_RUNNING,
            consts.ALLOC_CLIENT_FAILED,
        ):
            reconnected, expired = _alloc_reconnected_info(alloc, now, info)

        if supports and reconnected \
                and alloc.desired_status == consts.ALLOC_DESIRED_RUN \
                and alloc.client_status == consts.ALLOC_CLIENT_FAILED:
            reconnecting[aid] = alloc
            continue

        if any_tainted:
            node = tainted_nodes.get(alloc.node_id)
            node_is_tainted = alloc.node_id in tainted_nodes
        else:
            node = None
            node_is_tainted = False
        if node is not None:
            if node.status == consts.NODE_STATUS_DISCONNECTED:
                if supports:
                    if alloc.client_status == consts.ALLOC_CLIENT_RUNNING:
                        # -> disconnecting (kept in the set AND run
                        # through the disc-side reschedule filter below)
                        disconnecting[aid] = alloc
                        # disc-side reschedule filter: client status is
                        # RUNNING here, so the is_disconnecting UNKNOWN
                        # skip can never hit; every survivor of the
                        # shared early filters joins reschedule_now
                        # regardless of policy eligibility (legacy
                        # filter_by_rescheduleable(is_disconnecting=True))
                        if alloc.next_allocation and alloc.terminal_status():
                            continue
                        is_unt, ign = should_filter(alloc, is_batch)
                        if is_unt or ign:
                            continue
                        resched_disc[aid] = alloc
                        continue
                    if alloc.client_status == consts.ALLOC_CLIENT_PENDING:
                        lost[aid] = alloc
                        continue
                else:
                    lost[aid] = alloc
                    continue
            elif node.status == consts.NODE_STATUS_READY and reconnected:
                if expired:
                    lost[aid] = alloc
                else:
                    reconnecting[aid] = alloc
                continue

        if alloc.terminal_status() and not reconnected:
            pass        # -> untainted (reschedule filter below)
        elif alloc.desired_transition.migrate:
            migrate[aid] = alloc
            continue
        elif supports and _alloc_expired_info(alloc, now, info):
            lost[aid] = alloc
            continue
        elif supports and alloc.client_status == consts.ALLOC_CLIENT_UNKNOWN \
                and alloc.desired_status == consts.ALLOC_DESIRED_RUN:
            n_ignore += 1
            continue
        elif supports and reconnected \
                and alloc.client_status == consts.ALLOC_CLIENT_FAILED \
                and alloc.desired_status == consts.ALLOC_DESIRED_STOP:
            n_ignore += 1
            continue
        elif not node_is_tainted:
            if reconnected:
                if expired:
                    lost[aid] = alloc
                else:
                    reconnecting[aid] = alloc
                continue
            # -> untainted
        elif node is None or node.terminal_status():
            lost[aid] = alloc
            continue
        # else -> untainted

        # ---- the untainted-side reschedule filter, verbatim ----
        if alloc.next_allocation and alloc.terminal_status():
            continue
        is_unt, ign = should_filter(alloc, is_batch)
        if is_unt:
            untainted[aid] = alloc
            continue
        if ign:
            continue
        eligible_now, eligible_later, resched_time = \
            _update_by_reschedulable_info(
                alloc, now, eval_id, deployment, d_active, False, info)
        if not eligible_now:
            untainted[aid] = alloc
            if eligible_later:
                later.append(DelayedRescheduleInfo(aid, alloc, resched_time))
        else:
            resched_unt[aid] = alloc

    if resched_disc:
        resched_unt.update(resched_disc)
    return GroupClassification(
        untainted=untainted, migrate=migrate, lost=lost,
        disconnecting=disconnecting, reconnecting=reconnecting,
        ignore=n_ignore, reschedule_now=resched_unt,
        reschedule_later=later,
    )


# ---------------------------------------------------------------------------
# allocNameIndex (reconcile_util.go:591)
# ---------------------------------------------------------------------------


class AllocNameIndex:
    """Tracks which "<job>.<group>[i]" indexes are in use.

    ``in_use`` accepts an AllocSet dict or any iterable of allocs —
    callers with several sets chain them instead of building a union
    dict just to read the indexes out of it.
    """

    def __init__(self, job_id: str, group: str, count: int, in_use) -> None:
        self.job_id = job_id
        self.group = group
        self.count = count
        self.taken: set = set()
        values = in_use.values() if hasattr(in_use, "values") else in_use
        for a in values:
            idx = a.index()
            if idx >= 0:
                self.taken.add(idx)

    def _name(self, idx: int) -> str:
        return f"{self.job_id}.{self.group}[{idx}]"

    def next(self, n: int) -> List[str]:
        """Claim the n lowest unused indexes (reconcile_util.go:737)."""
        out = []
        idx = 0
        while len(out) < n:
            if idx not in self.taken:
                out.append(self._name(idx))
                self.taken.add(idx)
            idx += 1
        return out

    def highest(self, n: int) -> set:
        """Names of the n highest used indexes (reconcile_util.go:647)."""
        out = set()
        for idx in sorted(self.taken, reverse=True):
            if len(out) >= n:
                break
            out.add(self._name(idx))
        return out

    def unset_index(self, idx: int) -> None:
        self.taken.discard(idx)

    def next_canaries(self, n: int, existing: AllocSet, destructive: AllocSet) -> List[str]:
        """reconcile_util.go:682: prefer replacing destructive names."""
        existing_names = {a.name for a in existing.values()}
        out = []
        for a in name_order(destructive):
            if len(out) >= n:
                break
            if a.name not in existing_names:
                out.append(a.name)
                existing_names.add(a.name)
        idx = 0
        while len(out) < n:
            name = self._name(idx)
            if name not in existing_names:
                out.append(name)
                existing_names.add(name)
            idx += 1
        return out


# ---------------------------------------------------------------------------
# Results
# ---------------------------------------------------------------------------


@dataclass
class AllocPlaceResult:
    """reconcile_util.go allocPlaceResult."""

    name: str = ""
    canary: bool = False
    task_group: Optional[object] = None
    previous_alloc: Optional[Allocation] = None
    reschedule: bool = False
    lost: bool = False
    downgrade_non_canary: bool = False
    min_job_version: int = 0

    def stop_previous_alloc(self) -> Tuple[bool, str]:
        return False, ""


@dataclass
class AllocDestructiveResult:
    place_name: str = ""
    place_task_group: Optional[object] = None
    stop_alloc: Optional[Allocation] = None
    stop_status_description: str = ""

    @property
    def name(self) -> str:
        return self.place_name

    @property
    def task_group(self):
        return self.place_task_group

    @property
    def previous_alloc(self):
        return self.stop_alloc

    def stop_previous_alloc(self) -> Tuple[bool, str]:
        return True, self.stop_status_description


@dataclass
class AllocStopResult:
    alloc: Allocation
    client_status: str = ""
    status_description: str = ""
    followup_eval_id: str = ""


@dataclass
class DesiredUpdates:
    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0
    canary: int = 0
    preemptions: int = 0


@dataclass
class ReconcileResults:
    """reconcile.go reconcileResults."""

    deployment: Optional[Deployment] = None
    deployment_updates: List[Dict] = field(default_factory=list)
    place: List[AllocPlaceResult] = field(default_factory=list)
    destructive_update: List[AllocDestructiveResult] = field(default_factory=list)
    inplace_update: List[Allocation] = field(default_factory=list)
    stop: List[AllocStopResult] = field(default_factory=list)
    attribute_updates: Dict[str, Allocation] = field(default_factory=dict)
    disconnect_updates: Dict[str, Allocation] = field(default_factory=dict)
    reconnect_updates: Dict[str, Allocation] = field(default_factory=dict)
    desired_tg_updates: Dict[str, DesiredUpdates] = field(default_factory=dict)
    desired_followup_evals: Dict[str, List[Evaluation]] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# The reconciler
# ---------------------------------------------------------------------------


class AllocReconciler:
    """reconcile.go allocReconciler."""

    def __init__(
        self,
        alloc_update_fn: Callable,
        batch: bool,
        job_id: str,
        job,
        deployment: Optional[Deployment],
        existing_allocs: List[Allocation],
        tainted_nodes: Dict[str, object],
        eval_id: str,
        eval_priority: int,
        supports_disconnected_clients: bool = True,
        now: Optional[float] = None,
        use_legacy_filters: bool = False,
    ) -> None:
        self.alloc_update_fn = alloc_update_fn
        self.batch = batch
        self.job_id = job_id
        self.job = job
        self.deployment = deployment.copy() if deployment else None
        self.old_deployment: Optional[Deployment] = None
        self.existing_allocs = existing_allocs
        self.tainted_nodes = tainted_nodes
        self.eval_id = eval_id
        self.eval_priority = eval_priority
        self.supports_disconnected = supports_disconnected_clients
        self.now = now if now is not None else _time.time()
        # False = the fused single-pass classifier (classify_group);
        # True = the reference multi-pass composition it is
        # property-tested bit-identical against
        self.use_legacy_filters = use_legacy_filters
        self.deployment_paused = False
        self.deployment_failed = False
        self.result = ReconcileResults()

    # -- top level (reconcile.go:204) ------------------------------------

    def compute(self) -> ReconcileResults:
        m = new_alloc_matrix(self.job, self.existing_allocs)
        self._cancel_unneeded_deployments()

        if self.job.stopped():
            self._handle_stop(m)
            return self.result

        self._compute_deployment_paused()
        complete = True
        for group, allocs in m.items():
            complete = self._compute_group(group, allocs) and complete
        self._compute_deployment_updates(complete)
        return self.result

    def _compute_deployment_updates(self, deployment_complete: bool) -> None:
        if self.deployment is not None and deployment_complete:
            self.result.deployment_updates.append(
                {
                    "deployment_id": self.deployment.id,
                    "status": consts.DEPLOYMENT_STATUS_SUCCESSFUL,
                    "status_description": "Deployment completed successfully",
                }
            )
        d = self.result.deployment
        if d is not None and d.requires_promotion():
            if d.has_auto_promote():
                d.status_description = "Deployment is running pending automatic promotion"
            else:
                d.status_description = "Deployment is running but requires manual promotion"

    def _compute_deployment_paused(self) -> None:
        if self.deployment is None \
                and not getattr(self, "_version_deployed", False) \
                and self.job.multiregion \
                and self.job.multiregion_starts_blocked():
            # a gated region's FIRST eval for this job version: there
            # is no deployment row yet, but initial placements must
            # wait for the earlier region — treat as paused from the
            # start (the blocked deployment row is created below so
            # the unblock kick has something to release). Once this
            # version has a successful deployment here, replacement
            # evals must NOT re-engage the gate.
            self.deployment_paused = True
            return
        if self.deployment is not None:
            # blocked multiregion deployments behave like paused ones:
            # no rollout progress until an earlier region unblocks them
            self.deployment_paused = self.deployment.status in (
                consts.DEPLOYMENT_STATUS_PAUSED,
                consts.DEPLOYMENT_STATUS_PENDING,
                consts.DEPLOYMENT_STATUS_BLOCKED,
            )
            self.deployment_failed = (
                self.deployment.status == consts.DEPLOYMENT_STATUS_FAILED
            )

    def _cancel_unneeded_deployments(self) -> None:
        if self.job.stopped():
            if self.deployment is not None and self.deployment.active():
                self.result.deployment_updates.append(
                    {
                        "deployment_id": self.deployment.id,
                        "status": consts.DEPLOYMENT_STATUS_CANCELLED,
                        "status_description": "Cancelled because job is stopped",
                    }
                )
            self.old_deployment = self.deployment
            self.deployment = None
            return
        d = self.deployment
        if d is None:
            return
        if d.job_create_index != self.job.create_index or d.job_version != self.job.version:
            if d.active():
                self.result.deployment_updates.append(
                    {
                        "deployment_id": d.id,
                        "status": consts.DEPLOYMENT_STATUS_CANCELLED,
                        "status_description": "Cancelled due to newer version of job",
                    }
                )
            self.old_deployment = d
            self.deployment = None
        elif d.status == consts.DEPLOYMENT_STATUS_SUCCESSFUL:
            self.old_deployment = d
            self.deployment = None
            # this job version already rolled out here: the multiregion
            # gate must not re-engage for replacement evals
            self._version_deployed = True

    def _handle_stop(self, m: Dict[str, AllocSet]) -> None:
        for group, allocs in m.items():
            allocs = filter_by_terminal(allocs)
            du = DesiredUpdates()
            du.stop = self._filter_and_stop_all(allocs)
            self.result.desired_tg_updates[group] = du

    def _filter_and_stop_all(self, s: AllocSet) -> int:
        untainted, migrate, lost, disconnecting, reconnecting, ignore = filter_by_tainted(
            s, self.tainted_nodes, self.supports_disconnected, self.now
        )
        self._mark_stop(untainted, "", ALLOC_NOT_NEEDED)
        self._mark_stop(migrate, "", ALLOC_NOT_NEEDED)
        self._mark_stop(lost, consts.ALLOC_CLIENT_LOST, ALLOC_LOST)
        self._mark_stop(disconnecting, "", ALLOC_NOT_NEEDED)
        self._mark_stop(reconnecting, "", ALLOC_NOT_NEEDED)
        self._mark_stop(
            {k: v for k, v in ignore.items()
             if v.client_status == consts.ALLOC_CLIENT_UNKNOWN},
            "", ALLOC_NOT_NEEDED,
        )
        return len(s)

    def _mark_stop(self, allocs: AllocSet, client_status: str, desc: str) -> None:
        for a in allocs.values():
            self.result.stop.append(
                AllocStopResult(alloc=a, client_status=client_status,
                                status_description=desc)
            )

    def _mark_delayed(self, allocs: AllocSet, client_status: str, desc: str,
                      followup: Dict[str, str]) -> None:
        for a in allocs.values():
            self.result.stop.append(
                AllocStopResult(
                    alloc=a, client_status=client_status, status_description=desc,
                    followup_eval_id=followup.get(a.id, ""),
                )
            )

    # -- per group (reconcile.go:387 computeGroup) -----------------------

    def _compute_group(self, group_name: str, all_allocs: AllocSet) -> bool:
        du = DesiredUpdates()
        self.result.desired_tg_updates[group_name] = du

        tg = self.job.lookup_task_group(group_name)
        if tg is None:
            du.stop = self._filter_and_stop_all(all_allocs)
            return True

        dstate, existing_deployment = self._init_deployment_state(group_name, tg)

        all_allocs, ignore = self._filter_old_terminal_allocs(all_allocs)
        du.ignore += len(ignore)

        canaries, all_allocs = self._cancel_unneeded_canaries(all_allocs, du)

        if self.use_legacy_filters:
            # the reference multi-pass composition: kept as the
            # semantics definition the fused pass is property-tested
            # against (tests/test_reconcile_fast.py)
            untainted, migrate, lost, disconnecting, reconnecting, ignore = \
                filter_by_tainted(
                    all_allocs, self.tainted_nodes,
                    self.supports_disconnected, self.now)
            du.ignore += len(ignore)
            untainted, reschedule_now, reschedule_later = \
                filter_by_rescheduleable(
                    untainted, self.batch, False, self.now, self.eval_id,
                    self.deployment)
            _, resched_disc, _ = filter_by_rescheduleable(
                disconnecting, self.batch, True, self.now, self.eval_id,
                self.deployment)
            reschedule_now = union(reschedule_now, resched_disc)
        else:
            cls = classify_group(
                all_allocs, self.tainted_nodes, self.supports_disconnected,
                self.now, self.batch, self.eval_id, self.deployment)
            untainted = cls.untainted
            migrate = cls.migrate
            lost = cls.lost
            disconnecting = cls.disconnecting
            reconnecting = cls.reconnecting
            du.ignore += cls.ignore
            reschedule_now = cls.reschedule_now
            reschedule_later = cls.reschedule_later

        # lost allocs with stop_after_client_disconnect delay
        lost_later = self._delay_by_stop_after_disconnect(lost)
        lost_later_evals = self._create_lost_later_evals(lost_later, tg.name)

        # disconnecting -> unknown + timeout follow-ups
        timeout_later_evals = self._create_timeout_later_evals(disconnecting, tg.name)
        lost_later_evals.update(timeout_later_evals)

        self._create_reschedule_later_evals(reschedule_later, all_allocs, tg.name)

        name_index = AllocNameIndex(
            self.job_id, group_name, tg.count,
            (a for s in (untainted, migrate, reschedule_now, lost)
             for a in s.values()),
        )

        is_canarying = (
            dstate is not None and dstate.desired_canaries != 0 and not dstate.promoted
        )
        stop, reconnecting = self._compute_stop(
            tg, name_index, untainted, migrate, lost, canaries, reconnecting,
            is_canarying, lost_later_evals,
        )
        du.stop += len(stop)
        # in-place removal (both classification paths hand this method
        # a fresh dict it owns): same content and order as
        # ``difference(untainted, stop)`` without building another dict
        for aid in stop:
            untainted.pop(aid, None)

        self._compute_reconnecting(reconnecting)
        du.ignore += len(self.result.reconnect_updates)

        ignore2, inplace, destructive = self._compute_updates(tg, untainted)
        du.ignore += len(ignore2)
        du.in_place_update += len(inplace)
        if not existing_deployment:
            dstate.desired_total += len(destructive) + len(inplace)

        if is_canarying:
            untainted = difference(untainted, canaries)

        requires_canaries = (
            tg.update is not None
            and len(destructive) != 0
            and len(canaries) < tg.update.canary
            and not (dstate is not None and dstate.promoted)
        )
        if requires_canaries:
            self._compute_canaries(tg, dstate, destructive, canaries, du, name_index)

        is_canarying = (
            dstate is not None and dstate.desired_canaries != 0 and not dstate.promoted
        )
        under_provisioned_by = self._compute_under_provisioned_by(
            tg, untainted, destructive, migrate, is_canarying
        )

        place: List[AllocPlaceResult] = []
        if not lost_later:
            place = self._compute_placements(
                tg, name_index, untainted, migrate, reschedule_now, lost,
                reconnecting, is_canarying,
            )
            if not existing_deployment:
                dstate.desired_total += len(place)

        deployment_place_ready = (
            not self.deployment_paused and not self.deployment_failed and not is_canarying
        )
        under_provisioned_by = self._compute_replacements(
            deployment_place_ready, du, place, reschedule_now, lost,
            under_provisioned_by,
        )

        if deployment_place_ready:
            self._compute_destructive_updates(destructive, under_provisioned_by, du, tg)
        else:
            du.ignore += len(destructive)

        self._compute_migrations(du, migrate, tg, is_canarying)
        self._create_deployment(
            tg.name, tg.update, existing_deployment, dstate, all_allocs, destructive
        )

        return self._is_deployment_complete(
            group_name, destructive, inplace, migrate, reschedule_now, place,
            reschedule_later, requires_canaries,
        )

    # -- helpers ---------------------------------------------------------

    def _init_deployment_state(self, group: str, tg) -> Tuple[DeploymentState, bool]:
        dstate = None
        existing = False
        if self.deployment is not None:
            dstate = self.deployment.task_groups.get(group)
            existing = dstate is not None
        if not existing:
            dstate = DeploymentState()
            if tg.update is not None and not tg.update.is_empty():
                dstate.auto_revert = tg.update.auto_revert
                dstate.auto_promote = tg.update.auto_promote
                dstate.progress_deadline_s = tg.update.progress_deadline_s
        return dstate, existing

    def _filter_old_terminal_allocs(self, all_allocs: AllocSet) -> Tuple[AllocSet, AllocSet]:
        if not self.batch:
            return all_allocs, {}
        filtered = dict(all_allocs)
        ignored = {}
        for aid, a in list(filtered.items()):
            job = a.job
            older = job is not None and (
                job.version < self.job.version or job.create_index < self.job.create_index
            )
            if older and a.terminal_status():
                del filtered[aid]
                ignored[aid] = a
        return filtered, ignored

    def _cancel_unneeded_canaries(self, all_allocs: AllocSet, du: DesiredUpdates):
        if self.old_deployment is None and self.deployment is None:
            # no deployment anywhere: no canaries can exist, and the
            # legacy fall-through would only rebuild all_allocs as an
            # identical dict (difference against nothing)
            return {}, all_allocs
        stop_ids: List[str] = []
        if self.old_deployment is not None:
            for ds in self.old_deployment.task_groups.values():
                if not ds.promoted:
                    stop_ids.extend(ds.placed_canaries)
        if self.deployment is not None and self.deployment.status == consts.DEPLOYMENT_STATUS_FAILED:
            for ds in self.deployment.task_groups.values():
                if not ds.promoted:
                    stop_ids.extend(ds.placed_canaries)
        stop_set = from_keys(all_allocs, stop_ids)
        self._mark_stop(stop_set, "", ALLOC_NOT_NEEDED)
        du.stop += len(stop_set)
        all_allocs = difference(all_allocs, stop_set)

        canaries: AllocSet = {}
        if self.deployment is not None:
            ids = []
            for ds in self.deployment.task_groups.values():
                ids.extend(ds.placed_canaries)
            canaries = from_keys(all_allocs, ids)
            untainted, migrate, lost, _, _, _ = filter_by_tainted(
                canaries, self.tainted_nodes, self.supports_disconnected, self.now
            )
            self._mark_stop(migrate, "", ALLOC_MIGRATING)
            self._mark_stop(lost, consts.ALLOC_CLIENT_LOST, ALLOC_LOST)
            canaries = untainted
            all_allocs = difference(all_allocs, migrate, lost)
        return canaries, all_allocs

    def _compute_under_provisioned_by(self, tg, untainted, destructive, migrate,
                                      is_canarying: bool) -> int:
        if tg.update is None or tg.update.is_empty() or \
                len(destructive) + len(migrate) == 0:
            return tg.count
        if self.deployment is None:
            return tg.update.max_parallel
        if self.deployment_paused or self.deployment_failed or is_canarying:
            return 0
        limit = tg.update.max_parallel
        for a in untainted.values():
            if a.deployment_id != self.deployment.id:
                continue
            if a.deployment_status is not None and a.deployment_status.is_unhealthy():
                return 0
            if a.deployment_status is None or not a.deployment_status.is_healthy():
                limit -= 1
        return max(limit, 0)

    def _compute_placements(self, tg, name_index, untainted, migrate,
                            reschedule, lost, reconnecting,
                            is_canarying: bool) -> List[AllocPlaceResult]:
        place: List[AllocPlaceResult] = []
        for a in name_order(reschedule):
            place.append(
                AllocPlaceResult(
                    name=a.name, task_group=tg, previous_alloc=a, reschedule=True,
                    canary=a.deployment_status.canary if a.deployment_status else False,
                    downgrade_non_canary=is_canarying
                    and not (a.deployment_status and a.deployment_status.canary),
                    min_job_version=a.job_version,
                )
            )
        failed_reconnects = {
            k: v for k, v in reconnecting.items()
            if v.client_status == consts.ALLOC_CLIENT_FAILED
        }
        existing = (
            len(untainted) + len(migrate) + len(reschedule) + len(reconnecting)
            - len(failed_reconnects)
        )
        for a in name_order(lost):
            if existing >= tg.count:
                break
            existing += 1
            place.append(
                AllocPlaceResult(
                    name=a.name, task_group=tg, previous_alloc=a, reschedule=False,
                    lost=True,
                    canary=a.deployment_status.canary if a.deployment_status else False,
                    downgrade_non_canary=is_canarying
                    and not (a.deployment_status and a.deployment_status.canary),
                    min_job_version=a.job_version,
                )
            )
        if existing < tg.count:
            for name in name_index.next(tg.count - existing):
                place.append(
                    AllocPlaceResult(
                        name=name, task_group=tg,
                        downgrade_non_canary=is_canarying,
                    )
                )
        return place

    def _compute_replacements(self, deployment_place_ready: bool, du, place,
                              reschedule_now, lost, under_provisioned_by: int) -> int:
        failed = {
            k: v for k, v in reschedule_now.items()
            if k not in self.result.disconnect_updates
        }
        if deployment_place_ready:
            du.place += len(place)
            self.result.place.extend(place)
            self._mark_stop(failed, "", ALLOC_RESCHEDULED)
            du.stop += len(failed)
            return max(under_provisioned_by - min(len(place), under_provisioned_by), 0)

        if lost:
            allowed = min(len(lost), len(place))
            du.place += allowed
            self.result.place.extend(place[:allowed])

        if not reschedule_now or not place:
            return under_provisioned_by

        for p in place:
            prev = p.previous_alloc
            part_of_failed = (
                self.deployment_failed and prev is not None
                and self.deployment is not None
                and self.deployment.id == prev.deployment_id
            )
            if not part_of_failed and p.reschedule:
                self.result.place.append(p)
                du.place += 1
                if prev is not None and prev.id not in self.result.disconnect_updates:
                    self.result.stop.append(
                        AllocStopResult(alloc=prev, status_description=ALLOC_RESCHEDULED)
                    )
                    du.stop += 1
        return under_provisioned_by

    def _compute_destructive_updates(self, destructive: AllocSet,
                                     under_provisioned_by: int, du, tg) -> None:
        limit = min(len(destructive), under_provisioned_by)
        du.destructive_update += limit
        du.ignore += len(destructive) - limit
        for a in name_order(destructive)[:limit]:
            self.result.destructive_update.append(
                AllocDestructiveResult(
                    place_name=a.name, place_task_group=tg, stop_alloc=a,
                    stop_status_description=ALLOC_UPDATING,
                )
            )

    def _compute_migrations(self, du, migrate: AllocSet, tg, is_canarying: bool) -> None:
        du.migrate += len(migrate)
        for a in name_order(migrate):
            self.result.stop.append(
                AllocStopResult(alloc=a, status_description=ALLOC_MIGRATING)
            )
            self.result.place.append(
                AllocPlaceResult(
                    name=a.name, task_group=tg, previous_alloc=a,
                    canary=a.deployment_status.canary if a.deployment_status else False,
                    downgrade_non_canary=is_canarying
                    and not (a.deployment_status and a.deployment_status.canary),
                    min_job_version=a.job_version,
                )
            )

    def _compute_canaries(self, tg, dstate, destructive, canaries, du, name_index) -> None:
        dstate.desired_canaries = tg.update.canary
        if not self.deployment_paused and not self.deployment_failed:
            want = tg.update.canary - len(canaries)
            du.canary += want
            for name in name_index.next_canaries(want, canaries, destructive):
                self.result.place.append(
                    AllocPlaceResult(name=name, canary=True, task_group=tg)
                )

    def _compute_stop(self, tg, name_index, untainted, migrate, lost, canaries,
                      reconnecting, is_canarying, followup_evals) -> Tuple[AllocSet, AllocSet]:
        stop: AllocSet = {}
        stop.update(lost)
        self._mark_delayed(lost, consts.ALLOC_CLIENT_LOST, ALLOC_LOST, followup_evals)

        failed_reconnects = {
            k: v for k, v in reconnecting.items()
            if v.client_status == consts.ALLOC_CLIENT_FAILED
        }
        stop.update(failed_reconnects)
        self._mark_stop(failed_reconnects, consts.ALLOC_CLIENT_FAILED, ALLOC_RESCHEDULED)
        reconnecting = difference(reconnecting, failed_reconnects)

        if is_canarying:
            untainted = difference(untainted, canaries)
        remove = len(untainted) + len(migrate) + len(reconnecting) - tg.count
        if remove <= 0:
            return stop, reconnecting

        untainted = filter_by_terminal(untainted)

        if not is_canarying and canaries:
            canary_names = {a.name for a in canaries.values()}
            for aid, a in list(difference(untainted, canaries).items()):
                if a.name in canary_names:
                    stop[aid] = a
                    self.result.stop.append(
                        AllocStopResult(alloc=a, status_description=ALLOC_NOT_NEEDED)
                    )
                    del untainted[aid]
                    remove -= 1
                    if remove == 0:
                        return stop, reconnecting

        if migrate:
            migrating_names = AllocNameIndex(self.job_id, tg.name, tg.count, migrate)
            remove_names = migrating_names.highest(remove)
            for aid, a in list(migrate.items()):
                if a.name not in remove_names:
                    continue
                self.result.stop.append(
                    AllocStopResult(alloc=a, status_description=ALLOC_NOT_NEEDED)
                )
                del migrate[aid]
                stop[aid] = a
                name_index.unset_index(a.index())
                remove -= 1
                if remove == 0:
                    return stop, reconnecting

        if reconnecting:
            remove = self._compute_stop_by_reconnecting(
                untainted, reconnecting, stop, remove
            )
            if remove == 0:
                return stop, reconnecting

        remove_names = name_index.highest(remove)
        for aid, a in list(untainted.items()):
            if a.name in remove_names:
                stop[aid] = a
                self.result.stop.append(
                    AllocStopResult(alloc=a, status_description=ALLOC_NOT_NEEDED)
                )
                del untainted[aid]
                remove -= 1
                if remove == 0:
                    return stop, reconnecting

        for aid, a in list(untainted.items()):
            stop[aid] = a
            self.result.stop.append(
                AllocStopResult(alloc=a, status_description=ALLOC_NOT_NEEDED)
            )
            del untainted[aid]
            remove -= 1
            if remove == 0:
                return stop, reconnecting
        return stop, reconnecting

    def _compute_stop_by_reconnecting(self, untainted, reconnecting, stop, remove):
        for aid, rec in list(reconnecting.items()):
            if remove == 0:
                break
            if (
                rec.desired_status != consts.ALLOC_DESIRED_RUN
                or rec.desired_transition.should_migrate()
                or rec.desired_transition.reschedule
                or rec.desired_transition.should_force_reschedule()
                or (rec.job is not None and rec.job.version < self.job.version)
                or (rec.job is not None and rec.job.create_index < self.job.create_index)
            ):
                stop[aid] = rec
                self.result.stop.append(
                    AllocStopResult(alloc=rec, status_description=ALLOC_NOT_NEEDED)
                )
                del reconnecting[aid]
                remove -= 1
                continue
            for uid, unt in list(untainted.items()):
                if unt.name != rec.name:
                    continue
                # prefer stopping the replacement unless it's newer/better
                stop_alloc, del_set, del_id = unt, untainted, uid
                desc = ALLOC_NOT_NEEDED
                if unt.job is not None and rec.job is not None and (
                    unt.job.version > rec.job.version
                    or unt.job.create_index > rec.job.create_index
                ):
                    stop_alloc, del_set, del_id = rec, reconnecting, aid
                else:
                    desc = ALLOC_RECONNECTED
                stop[stop_alloc.id] = stop_alloc
                self.result.stop.append(
                    AllocStopResult(alloc=stop_alloc, status_description=desc)
                )
                del del_set[del_id]
                remove -= 1
                if remove == 0:
                    return remove
        return remove

    def _compute_updates(self, tg, untainted: AllocSet):
        ignore: AllocSet = {}
        inplace: AllocSet = {}
        destructive: AllocSet = {}
        for aid, a in untainted.items():
            ignore_change, destructive_change, updated = self.alloc_update_fn(
                a, self.job, tg
            )
            if ignore_change:
                ignore[aid] = a
            elif destructive_change:
                destructive[aid] = a
            else:
                inplace[aid] = a
                self.result.inplace_update.append(updated)
        return ignore, inplace, destructive

    def _compute_reconnecting(self, reconnecting: AllocSet) -> None:
        """reconcile.go computeReconnecting: updates that resume allocs."""
        for aid, a in reconnecting.items():
            if a.desired_status != consts.ALLOC_DESIRED_RUN:
                continue
            if a.client_status not in (consts.ALLOC_CLIENT_RUNNING,):
                continue
            update = a.copy_skip_job()
            update.client_status = consts.ALLOC_CLIENT_RUNNING
            self.result.reconnect_updates[aid] = update

    def _delay_by_stop_after_disconnect(self, lost: AllocSet) -> List[DelayedRescheduleInfo]:
        later = []
        for a in lost.values():
            job = a.job
            tg = job.lookup_task_group(a.task_group) if job else None
            if tg is None or tg.stop_after_client_disconnect_s is None:
                continue
            if a.client_status == consts.ALLOC_CLIENT_RUNNING:
                later.append(
                    DelayedRescheduleInfo(
                        a.id, a,
                        self.now + tg.stop_after_client_disconnect_s,
                    )
                )
        return later

    def _create_lost_later_evals(self, later: List[DelayedRescheduleInfo],
                                 tg_name: str) -> Dict[str, str]:
        """Batched WaitUntil follow-up evals (reconcile.go
        createLostLaterEvals): one eval per distinct time bucket."""
        if not later:
            return {}
        out: Dict[str, str] = {}
        by_time: Dict[float, List[DelayedRescheduleInfo]] = {}
        for info in later:
            by_time.setdefault(round(info.reschedule_time_s, 0), []).append(info)
        evals = []
        for t, infos in sorted(by_time.items()):
            ev = Evaluation(
                namespace=self.job.namespace,
                priority=self.eval_priority,
                type=self.job.type,
                triggered_by=consts.EVAL_TRIGGER_RETRY_FAILED_ALLOC,
                job_id=self.job_id,
                status=consts.EVAL_STATUS_PENDING,
                wait_until_s=t,
            )
            evals.append(ev)
            for info in infos:
                out[info.alloc_id] = ev.id
        self.result.desired_followup_evals.setdefault(tg_name, []).extend(evals)
        return out

    def _create_timeout_later_evals(self, disconnecting: AllocSet, tg_name: str) -> Dict[str, str]:
        """max_client_disconnect timeout evals + unknown updates
        (reconcile.go createTimeoutLaterEvals)."""
        if not disconnecting:
            return {}
        out: Dict[str, str] = {}
        for aid, a in disconnecting.items():
            job = a.job
            tg = job.lookup_task_group(a.task_group) if job else None
            if tg is None or tg.max_client_disconnect_s is None:
                continue
            ev = Evaluation(
                namespace=self.job.namespace,
                priority=self.eval_priority,
                type=self.job.type,
                triggered_by=consts.EVAL_TRIGGER_MAX_DISCONNECT_TIMEOUT,
                job_id=self.job_id,
                status=consts.EVAL_STATUS_PENDING,
                wait_until_s=self.now + tg.max_client_disconnect_s,
            )
            self.result.desired_followup_evals.setdefault(tg_name, []).append(ev)
            out[aid] = ev.id
            update = a.copy_skip_job()
            update.client_status = consts.ALLOC_CLIENT_UNKNOWN
            update.client_description = "alloc is lost since its node is disconnected"
            update.follow_up_eval_id = ev.id
            # stamp the disconnect on every task state (structs.go
            # appends the unknown AllocState; Reconnected() compares it
            # against the client's later 'Reconnected' event)
            from nomad_tpu.structs.alloc import TaskEvent
            now_ns = int(self.now * 1e9)
            for ts in update.task_states.values():
                ts.events.append(TaskEvent(
                    type="Disconnected", time_ns=now_ns,
                    message="client missed heartbeats"))
            self.result.disconnect_updates[aid] = update
        return out

    def _create_reschedule_later_evals(self, later: List[DelayedRescheduleInfo],
                                       all_allocs: AllocSet, tg_name: str) -> None:
        mapping = self._create_lost_later_evals(later, tg_name)
        for alloc_id, eval_id in mapping.items():
            existing = all_allocs.get(alloc_id)
            if existing is None:
                continue
            updated = existing.copy_skip_job()
            updated.follow_up_eval_id = eval_id
            self.result.attribute_updates[alloc_id] = updated

    def _create_deployment(self, group_name: str, strategy, existing_deployment: bool,
                           dstate: DeploymentState, all_allocs: AllocSet,
                           destructive: AllocSet) -> None:
        if existing_deployment or strategy is None or strategy.is_empty() \
                or dstate.desired_total == 0:
            return
        updating_spec = len(destructive) != 0 or len(self.result.inplace_update) != 0
        had_running = any(
            a.job is not None
            and a.job.version == self.job.version
            and a.job.create_index == self.job.create_index
            for a in all_allocs.values()
        )
        if had_running and not updating_spec:
            return
        if self.deployment is None:
            self.deployment = new_deployment(self.job)
            # multiregion gating (structs.go:4133): regions beyond the
            # first max_parallel wave deploy blocked until an earlier
            # region's success unblocks them
            if self.job.multiregion:
                self.deployment.is_multiregion = True
                if self.job.multiregion_starts_blocked():
                    self.deployment.status = consts.DEPLOYMENT_STATUS_BLOCKED
                    self.deployment.status_description = (
                        "Deployment is blocked on an earlier region"
                    )
            self.result.deployment = self.deployment
        self.deployment.task_groups[group_name] = dstate

    def _is_deployment_complete(self, group_name, destructive, inplace, migrate,
                                reschedule_now, place, reschedule_later,
                                requires_canaries: bool) -> bool:
        complete = (
            len(destructive) + len(inplace) + len(place) + len(migrate)
            + len(reschedule_now) + len(reschedule_later) == 0
            and not requires_canaries
        )
        if not complete or self.deployment is None:
            return False
        dstate = self.deployment.task_groups.get(group_name)
        if dstate is not None:
            if dstate.healthy_allocs < max(dstate.desired_total, dstate.desired_canaries) or (
                dstate.desired_canaries > 0 and not dstate.promoted
            ):
                complete = False
        return complete
