"""Scheduler test harness.

Reference behavior: scheduler/testing.go Harness (:48-301) -- a real
StateStore plus a fake Planner that applies submitted plans directly to
the store (SubmitPlan :90), capturing plans/evals for assertions. The
whole scheduler runs against it without a server or raft.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from nomad_tpu.scheduler.scheduler import new_scheduler
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs.eval_plan import Evaluation, Plan, PlanResult


class Harness:
    def __init__(self, state: Optional[StateStore] = None) -> None:
        self.state = state or StateStore()
        self.plans: List[Plan] = []
        self.planner_calls: List[str] = []
        self.evals: List[Evaluation] = []
        self.create_evals: List[Evaluation] = []
        self.reblock_evals: List[Evaluation] = []
        self.reject_plan = False          # fault injection (testing.go:19)
        self._lock = threading.Lock()

    # -- Planner interface (testing.go:90 SubmitPlan) --------------------

    def submit_plan(self, plan: Plan) -> Tuple[Optional[PlanResult], Optional[object]]:
        plan.run_deferred()
        with self._lock:
            self.plans.append(plan)
            if self.reject_plan:
                result = PlanResult(refresh_index=self.state.latest_index())
                return result, self.state.snapshot()
            result = PlanResult(
                node_update=plan.node_update,
                node_allocation=plan.node_allocation,
                node_preemptions=plan.node_preemptions,
                deployment=plan.deployment,
                deployment_updates=plan.deployment_updates,
            )
            index = self.state.upsert_plan_results(
                0, plan, plan.node_allocation, plan.node_update,
                plan.node_preemptions, plan.deployment, plan.deployment_updates,
            )
            result.alloc_index = index
            return result, None

    def update_eval(self, evaluation: Evaluation) -> None:
        with self._lock:
            self.evals.append(evaluation)

    def create_eval(self, evaluation: Evaluation) -> None:
        with self._lock:
            self.create_evals.append(evaluation)

    def reblock_eval(self, evaluation: Evaluation) -> None:
        with self._lock:
            self.reblock_evals.append(evaluation)

    def serve_rs_meet_minimum_version(self) -> bool:
        return True

    # -- driving ---------------------------------------------------------

    def process(self, scheduler_name: str, evaluation: Evaluation) -> None:
        """testing.go Process: snapshot state, run the named scheduler."""
        snap = self.state.snapshot()
        sched = new_scheduler(scheduler_name, snap, self)
        sched.process(evaluation)

    # -- assertion helpers ----------------------------------------------

    def placed_allocs(self) -> List:
        return [
            a
            for plan in self.plans
            for allocs in plan.node_allocation.values()
            for a in allocs
        ]

    def stopped_allocs(self) -> List:
        return [
            a
            for plan in self.plans
            for allocs in plan.node_update.values()
            for a in allocs
        ]
