"""Device allocation: exact host-side assignment + kernel plane builder.

Reference behavior: scheduler/device.go (deviceAllocator, AssignDevice
:32 -- pick the feasible group with the highest normalized affinity
score, return matched weights) and feasible.go DeviceChecker (:1193).

Split of labor in the TPU build: the kernel checks *count* feasibility
via ``dev_free[N, R]`` planes (max free instances in any single matching
group per request) and scores ``dev_aff_score[N]`` (class-memoizable);
after the kernel selects a node, ``assign_devices`` performs the exact
per-instance assignment the reference does, and the stack retries with
the node masked out in the rare case exactness disagrees with the
plane approximation (overlapping requests on one group).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from nomad_tpu.structs.constraints import check_constraint
from nomad_tpu.structs.resources import (
    AllocatedDeviceResource,
    DeviceAccounter,
    NodeDeviceResource,
    RequestedDevice,
)


def resolve_device_target(target: str, dev: NodeDeviceResource):
    """feasible.go resolveDeviceTarget: ${device.attr.*} and intrinsics."""
    if target == "${device.model}":
        return dev.name, True
    if target == "${device.vendor}":
        return dev.vendor, True
    if target == "${device.type}":
        return dev.type, True
    if target.startswith("${device.attr."):
        attr = target[len("${device.attr."):].rstrip("}")
        val = dev.attributes.get(attr)
        return (val, True) if val is not None else (None, False)
    return target, True


def node_device_matches(dev: NodeDeviceResource, req: RequestedDevice) -> bool:
    """feasible.go nodeDeviceMatches: ID match + constraints."""
    if not dev.matches_request(req.name):
        return False
    for c in req.constraints:
        lval, lok = resolve_device_target(c.ltarget, dev)
        rval, rok = resolve_device_target(c.rtarget, dev)
        if not check_constraint(c.operand, lval, rval, lok, rok):
            return False
    return True


def device_affinity_score(dev: NodeDeviceResource, req: RequestedDevice) -> Tuple[float, float]:
    """Returns (normalized choice score, sum of matched weights)
    for one group vs one request (device.go:70-95)."""
    if not req.affinities:
        return 0.0, 0.0
    total = 0.0
    choice = 0.0
    matched = 0.0
    for a in req.affinities:
        lval, lok = resolve_device_target(a.ltarget, dev)
        rval, rok = resolve_device_target(a.rtarget, dev)
        total += abs(float(a.weight))
        if check_constraint(a.operand, lval, rval, lok, rok):
            choice += float(a.weight)
            matched += float(a.weight)
    if total > 0:
        choice /= total
    return choice, matched


class DeviceAllocator(DeviceAccounter):
    """Exact instance-level allocator (device.go:13)."""

    def __init__(self, node) -> None:
        super().__init__(node)
        self._groups: Dict[str, NodeDeviceResource] = {
            d.id_string(): d for d in node.node_resources.devices
        }

    def assign(self, req: RequestedDevice) -> Tuple[Optional[AllocatedDeviceResource], float, str]:
        """AssignDevice (device.go:32): returns (offer, matched_weights, err)."""
        if not self.devices:
            return None, 0.0, "no devices available"
        if req.count == 0:
            return None, 0.0, "invalid request of zero devices"

        offer = None
        offer_score = 0.0
        matched_weights = 0.0
        for dev_id, instances in self.devices.items():
            free = [iid for iid, n in instances.items() if n == 0]
            if len(free) < req.count:
                continue
            group = self._groups.get(dev_id)
            if group is None or not node_device_matches(group, req):
                continue
            choice, matched = device_affinity_score(group, req)
            if offer is not None and choice < offer_score:
                continue
            offer_score = choice
            matched_weights = matched
            offer = AllocatedDeviceResource(
                vendor=group.vendor,
                type=group.type,
                name=group.name,
                device_ids=free[: req.count],
            )
        if offer is None:
            return None, 0.0, "no devices match request"
        return offer, matched_weights, ""


def device_planes_for_node(node, proposed_allocs, requests: List[RequestedDevice]):
    """Build (free_counts per request, affinity score) for one node.

    ``free_counts[r]`` = free instances in the *best single matching
    group* (count feasibility plane); affinity score mirrors
    rank.go:549-554: sum of matched weights over all requests divided by
    the total absolute affinity weight.
    """
    alloc = DeviceAllocator(node)
    alloc.add_allocs(proposed_allocs)
    free_counts = []
    total_weight = 0.0
    sum_matched = 0.0
    for req in requests:
        best_free = 0
        best_choice = -math.inf
        best_matched = 0.0
        for a in req.affinities:
            total_weight += abs(float(a.weight))
        for dev_id, instances in alloc.devices.items():
            group = alloc._groups.get(dev_id)
            if group is None or not node_device_matches(group, req):
                continue
            free = sum(1 for n in instances.values() if n == 0)
            choice, matched = device_affinity_score(group, req)
            # prefer higher-affinity groups; among equal, more free
            if (choice, free) > (best_choice, best_free):
                best_choice, best_free, best_matched = choice, free, matched
        free_counts.append(best_free)
        sum_matched += best_matched if best_free > 0 else 0.0
    score = (sum_matched / total_weight) if total_weight > 0 else 0.0
    return free_counts, score, total_weight > 0
