"""Scheduler utilities (reference: scheduler/util.go).

taintedNodes (:427), updateNonTerminalAllocsToLost (:—), tasksUpdated
(:488), genericAllocUpdateFn (:1118), adjustQueuedAllocations,
setStatus helpers.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from nomad_tpu.structs import consts
from nomad_tpu.structs.alloc import Allocation


def tainted_nodes(state, allocs: List[Allocation]) -> Dict[str, object]:
    """Nodes (by id) that are draining/down/disconnected/missing, for the
    set of nodes hosting these allocs (util.go:427)."""
    out: Dict[str, object] = {}
    seen = set()
    for a in allocs:
        if a.node_id in seen:
            continue
        seen.add(a.node_id)
        node = state.node_by_id(a.node_id)
        if node is None:
            out[a.node_id] = None
            continue
        if node.drain or node.status in (
            consts.NODE_STATUS_DOWN, consts.NODE_STATUS_DISCONNECTED
        ):
            out[a.node_id] = node
    return out


def update_non_terminal_allocs_to_lost(plan, tainted: Dict[str, object],
                                       allocs: List[Allocation]) -> None:
    """Mark non-terminal allocs on down nodes lost (util.go
    updateNonTerminalAllocsToLost)."""
    for a in allocs:
        if a.node_id not in tainted:
            continue
        node = tainted[a.node_id]
        if node is not None and node.status != consts.NODE_STATUS_DOWN:
            continue
        if a.desired_status in (consts.ALLOC_DESIRED_STOP, consts.ALLOC_DESIRED_EVICT) \
                and a.client_status in (consts.ALLOC_CLIENT_RUNNING, consts.ALLOC_CLIENT_PENDING):
            plan.append_stopped_alloc(
                a, "alloc lost since its node is down", consts.ALLOC_CLIENT_LOST
            )


def networks_updated(a: List, b: List) -> bool:
    if len(a) != len(b):
        return True
    for an, bn in zip(a, b):
        if an.mode != bn.mode or an.mbits != bn.mbits:
            return True
        aports = [(p.label, p.value, p.to) for p in an.reserved_ports] + [
            (p.label, 0, p.to) for p in an.dynamic_ports
        ]
        bports = [(p.label, p.value, p.to) for p in bn.reserved_ports] + [
            (p.label, 0, p.to) for p in bn.dynamic_ports
        ]
        if sorted(aports) != sorted(bports):
            return True
    return False


def tasks_updated(job_a, job_b, group_name: str) -> bool:
    """Whether the group requires a destructive update (util.go:488)."""
    a = job_a.lookup_task_group(group_name)
    b = job_b.lookup_task_group(group_name)
    if a is None or b is None:
        return True
    if len(a.tasks) != len(b.tasks):
        return True
    if (a.ephemeral_disk.size_mb, a.ephemeral_disk.sticky, a.ephemeral_disk.migrate) != (
        b.ephemeral_disk.size_mb, b.ephemeral_disk.sticky, b.ephemeral_disk.migrate
    ):
        return True
    if networks_updated(a.networks, b.networks):
        return True
    # affinities/spreads at job+tg level
    if repr(job_a.affinities) != repr(job_b.affinities):
        return True
    if repr(a.affinities) != repr(b.affinities):
        return True
    if repr(job_a.spreads) != repr(job_b.spreads):
        return True
    if repr(a.spreads) != repr(b.spreads):
        return True
    if repr(a.volumes) != repr(b.volumes):
        return True
    for at in a.tasks:
        bt = b.lookup_task(at.name)
        if bt is None:
            return True
        if at.driver != bt.driver or at.user != bt.user:
            return True
        if at.config != bt.config or at.env != bt.env:
            return True
        if repr(at.artifacts) != repr(bt.artifacts):
            return True
        if repr(at.templates) != repr(bt.templates):
            return True
        if networks_updated(at.resources.networks, bt.resources.networks):
            return True
        ar, br = at.resources, bt.resources
        if (ar.cpu, ar.cores, ar.memory_mb, ar.memory_max_mb) != (
            br.cpu, br.cores, br.memory_mb, br.memory_max_mb
        ):
            return True
        if repr(ar.devices) != repr(br.devices):
            return True
        if repr(at.constraints) != repr(bt.constraints):
            return True
    return False


def generic_alloc_update_fn(ctx, stack, eval_id: str):
    """allocUpdateType factory (util.go:1118 genericAllocUpdateFn):
    decides ignore / destructive / in-place for an existing alloc vs the
    new job version.
    """

    def update_fn(existing: Allocation, new_job, new_tg) -> Tuple[bool, bool, Optional[Allocation]]:
        ejob = existing.job
        if ejob is not None and ejob.job_modify_index == new_job.job_modify_index:
            return True, False, None
        if ejob is not None and tasks_updated(new_job, ejob, new_tg.name):
            return False, True, None
        if existing.terminal_status():
            return True, False, None

        node = ctx.state.node_by_id(existing.node_id)
        if node is None:
            return False, True, None
        if node.datacenter not in new_job.datacenters:
            return False, True, None

        # In-place resource re-check (util.go:1158-1168 stages an
        # eviction then runs a single-node Select). The tensorized build
        # does the equivalent host-side with no kernel launch: the new
        # resources must fit alongside the node's proposed allocs minus
        # the alloc being updated -- networks/devices/ports carry over
        # unchanged (guarded by tasks_updated), so cpu/mem/disk/cores
        # arithmetic is the entire question.
        from nomad_tpu.scheduler.scaffold import scaffold_for
        from nomad_tpu.structs.alloc import Allocation as _Alloc
        from nomad_tpu.structs.resources import (
            AllocatedCpuResources,
            AllocatedMemoryResources,
            AllocatedResources,
            AllocatedSharedResources,
            AllocatedTaskResources,
            allocs_fit,
        )

        try:
            scaffold = scaffold_for(new_job, new_tg)
        except Exception:                       # noqa: BLE001
            # ask-limit overruns surface on the placement path, not
            # here — an in-place update stays possible without one
            scaffold = None
        ecr, euses_ports, euses_devices = existing.fit_meta()
        if scaffold is not None and scaffold.lean_assign \
                and not euses_ports \
                and not euses_devices and not ecr.reserved_cores:
            # lean in-place update (no networks/ports/devices/cores to
            # carry over): ride the (job, tg)-shared frozen skeleton —
            # this path runs once per updated alloc per eval
            _, _, new_resources = scaffold.lean_planes(False)
        else:
            new_resources = AllocatedResources(
                tasks={},
                task_lifecycles={},
                shared=AllocatedSharedResources(disk_mb=new_tg.ephemeral_disk.size_mb),
            )
            for task in new_tg.tasks:
                r = task.resources
                tr = AllocatedTaskResources(
                    cpu=AllocatedCpuResources(cpu_shares=int(r.cpu)),
                    memory=AllocatedMemoryResources(memory_mb=int(r.memory_mb)),
                )
                new_resources.tasks[task.name] = tr
                new_resources.task_lifecycles[task.name] = task.lifecycle
            if existing.allocated_resources is not None:
                for task_name, tr in new_resources.tasks.items():
                    old_tr = existing.allocated_resources.tasks.get(task_name)
                    if old_tr is not None:
                        tr.networks = [n.copy() for n in old_tr.networks]
                        tr.devices = list(old_tr.devices)
                        tr.cpu.reserved_cores = list(old_tr.cpu.reserved_cores)
                new_resources.shared.networks = list(
                    existing.allocated_resources.shared.networks
                )
                new_resources.shared.ports = list(existing.allocated_resources.shared.ports)

        proposed = [
            a for a in ctx.proposed_allocs(existing.node_id) if a.id != existing.id
        ]
        probe = _Alloc(id="_inplace_probe", allocated_resources=new_resources)
        fit, _, _ = allocs_fit(node, proposed + [probe])
        if not fit:
            return False, True, None

        new_alloc = existing.copy_skip_job()
        new_alloc.eval_id = eval_id
        new_alloc.job = None  # use the job in the plan
        new_alloc.allocated_resources = new_resources
        new_alloc.metrics = existing.metrics.copy() if existing.metrics else None
        return False, False, new_alloc

    return update_fn


def adjust_queued_allocations(result, queued: Dict[str, int]) -> None:
    """Decrement queued counts by successfully planned allocs
    (util.go adjustQueuedAllocations)."""
    if result is None:
        return
    for allocs in result.node_allocation.values():
        for a in allocs:
            if a.create_index != result.alloc_index:
                continue
            if a.task_group in queued:
                queued[a.task_group] -= 1
