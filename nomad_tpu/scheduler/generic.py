"""GenericScheduler: service + batch evaluation processing.

Reference behavior: scheduler/generic_sched.go (:94-843). Process runs
the retry loop (5 service / 2 batch attempts, :16-23), each attempt:
job + deployment lookup -> reconciler -> batched placements through the
XLA stack -> plan submit; failed placements create/reuse a blocked eval
(:219), delayed reschedules create WaitUntil follow-up evals (:63-69).

TPU deviation (the whole point): computePlacements (:499) collapses the
per-alloc Select loop into one ``select_many`` kernel launch per task
group, carrying per-placement penalty/preferred planes.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.reconcile import (
    AllocReconciler,
    AllocPlaceResult,
    ReconcileResults,
)
from nomad_tpu.scheduler.scheduler import (
    Planner,
    Scheduler,
    SchedulerState,
    SetStatusError,
    progress_made,
    register_scheduler,
    retry_max,
)
from nomad_tpu.scheduler.stack import SelectRequest, XLAGenericStack
from nomad_tpu.scheduler.util import (
    adjust_queued_allocations,
    generic_alloc_update_fn,
    tainted_nodes,
    update_non_terminal_allocs_to_lost,
)
from nomad_tpu.structs import consts
from nomad_tpu.structs.alloc import AllocMetric, Allocation, RescheduleEvent, RescheduleTracker
from nomad_tpu.structs.eval_plan import Evaluation, Plan, generate_uuid
from nomad_tpu.telemetry.trace import tracer
from nomad_tpu.tensors.schema import AskLimitError, ClusterTensors

MAX_SERVICE_ATTEMPTS = 5    # generic_sched.go:16
MAX_BATCH_ATTEMPTS = 2      # generic_sched.go:20

_VALID_TRIGGERS = frozenset({
    consts.EVAL_TRIGGER_JOB_REGISTER, consts.EVAL_TRIGGER_JOB_DEREGISTER,
    consts.EVAL_TRIGGER_NODE_DRAIN, consts.EVAL_TRIGGER_NODE_UPDATE,
    consts.EVAL_TRIGGER_ALLOC_STOP, consts.EVAL_TRIGGER_ROLLING_UPDATE,
    consts.EVAL_TRIGGER_QUEUED_ALLOCS, consts.EVAL_TRIGGER_PERIODIC_JOB,
    consts.EVAL_TRIGGER_MAX_PLAN_ATTEMPTS, consts.EVAL_TRIGGER_DEPLOYMENT_WATCHER,
    consts.EVAL_TRIGGER_RETRY_FAILED_ALLOC, consts.EVAL_TRIGGER_FAILED_FOLLOW_UP,
    consts.EVAL_TRIGGER_PREEMPTION, consts.EVAL_TRIGGER_SCALING,
    consts.EVAL_TRIGGER_MAX_DISCONNECT_TIMEOUT, consts.EVAL_TRIGGER_RECONNECT,
})
BLOCKED_EVAL_MAX_PLAN = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS = "created to place remaining allocations"


class GenericScheduler(Scheduler):
    def __init__(self, state: SchedulerState, planner: Planner, batch: bool = False,
                 events_cb=None, kernel_launch=None, cluster_provider=None) -> None:
        self.state = state
        self.planner = planner
        self.batch = batch
        self.events_cb = events_cb
        self.kernel_launch = kernel_launch
        self.cluster_provider = cluster_provider
        self.eval: Optional[Evaluation] = None
        self.job = None
        self.plan: Optional[Plan] = None
        self.plan_result = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[XLAGenericStack] = None
        self.deployment = None
        self.blocked: Optional[Evaluation] = None
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}
        self.queued_allocs: Dict[str, int] = {}
        self.followup_evals: List[Evaluation] = []
        self._cluster: Optional[ClusterTensors] = None

    # -- entry (generic_sched.go:144 Process) ----------------------------

    def process(self, evaluation: Evaluation) -> None:
        self.eval = evaluation
        if evaluation.triggered_by not in _VALID_TRIGGERS:
            self._set_status(
                consts.EVAL_STATUS_FAILED,
                f"scheduler cannot handle '{evaluation.triggered_by}' evaluation reason",
            )
            return

        limit = MAX_BATCH_ATTEMPTS if self.batch else MAX_SERVICE_ATTEMPTS
        try:
            retry_max(limit, self._process, lambda: progress_made(self.plan_result))
        except SetStatusError as e:
            # no forward progress: blocked eval + failed status
            self._create_blocked_eval(plan_failure=True)
            self._set_status(e.eval_status, e.desc)
            return
        except AskLimitError as e:
            self._set_status(consts.EVAL_STATUS_FAILED, str(e))
            return

        if self.eval.status == consts.EVAL_STATUS_BLOCKED and self.failed_tg_allocs:
            # reblock (generic_sched.go:205-215)
            e = self.ctx.eligibility
            new_eval = self.eval.copy()
            new_eval.escaped_computed_class = e.has_escaped()
            new_eval.class_eligibility = e.get_classes()
            new_eval.quota_limit_reached = e.quota_reached
            self.planner.reblock_eval(new_eval)
            return

        self._set_status(consts.EVAL_STATUS_COMPLETE, "")

    # -- one attempt (generic_sched.go:248 process) ----------------------

    def _process(self):
        self.job = self.state.job_by_id(self.eval.namespace, self.eval.job_id)
        self.queued_allocs = {}
        self.followup_evals = []
        self.plan = self.eval.make_plan(self.job)
        self.deployment = None
        if not self.batch and self.job is not None:
            self.deployment = self.state.latest_deployment_by_job_id(
                self.eval.namespace, self.eval.job_id
            )
        self.failed_tg_allocs = {}
        self.ctx = EvalContext(self.state, self.plan, events_cb=self.events_cb,
                               kernel_launch=self.kernel_launch)
        self._cluster = self._build_cluster()
        self.stack = XLAGenericStack(self.batch, self.ctx, self._cluster)
        # decorrelate concurrent evals' tie-breaking (shuffleNodes
        # util.go:464: seeded by plan id + state index) and their
        # dynamic-port picks (network.go:598 stochastic selection)
        import zlib

        seed = zlib.crc32(
            f"{self.eval.id}:{self.state.latest_index()}".encode()
        )
        self.stack.shuffle_seed = seed
        self.ctx.port_seed = seed
        if self.job is not None and not self.job.stopped():
            self.stack.set_job(self.job)

        err = self._compute_job_allocs()
        if err is not None:
            return False, err

        delay_instead = bool(self.followup_evals) and self.eval.wait_until_s == 0.0

        if (
            self.eval.status != consts.EVAL_STATUS_BLOCKED
            and self.failed_tg_allocs
            and self.blocked is None
            and not delay_instead
        ):
            self._create_blocked_eval(plan_failure=False)

        if self.plan.is_no_op() and not self.eval.annotate_plan:
            return True, None

        if delay_instead:
            for ev in self.followup_evals:
                ev.previous_eval = self.eval.id
                self.planner.create_eval(ev)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result

        adjust_queued_allocations(result, self.queued_allocs)

        if new_state is not None:
            self.state = new_state
            return False, None

        full, expected, actual = result.full_commit(self.plan)
        if not full:
            return False, None
        return True, None

    def _build_cluster(self) -> ClusterTensors:
        if self.cluster_provider is not None:
            return self.cluster_provider(self.state)
        from nomad_tpu.parallel.coalesce import default_cluster_cache

        return default_cluster_cache.get(self.state)

    # -- reconcile + placements (generic_sched.go:358,499) ---------------

    def _compute_job_allocs(self) -> Optional[Exception]:
        # the reconcile slice of sched-host, spanned on its own: the
        # largest single Python cost of the steady state post-PR9
        # (TRACE_DECOMP stage "sched-reconcile"; see docs/PERF.md
        # "The reconcile fast path")
        with tracer.span("sched.reconcile"):
            allocs = self.state.allocs_by_job(
                self.eval.namespace, self.eval.job_id)
            tainted = tainted_nodes(self.state, allocs)
            update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

            job = self.job if self.job is not None else _dead_job_stub(self.eval)
            reconciler = AllocReconciler(
                generic_alloc_update_fn(self.ctx, self.stack, self.eval.id),
                self.batch, self.eval.job_id, job, self.deployment, allocs,
                tainted, self.eval.id, self.eval.priority,
            )
            results = reconciler.compute()

        if self.eval.annotate_plan:
            from nomad_tpu.structs.eval_plan import PlanAnnotations

            self.plan.annotations = PlanAnnotations(
                desired_tg_updates=results.desired_tg_updates
            )

        self.plan.deployment = results.deployment
        self.plan.deployment_updates = results.deployment_updates
        for evals in results.desired_followup_evals.values():
            self.followup_evals.extend(evals)
        if results.deployment is not None:
            self.deployment = results.deployment

        for stop in results.stop:
            self.plan.append_stopped_alloc(
                stop.alloc, stop.status_description, stop.client_status,
                stop.followup_eval_id,
            )
        for aid, update in results.disconnect_updates.items():
            self.plan.append_alloc(update, None)
        for update in results.inplace_update:
            if self.deployment is not None and update.deployment_id != self.deployment.id:
                update.deployment_id = self.deployment.id
                update.deployment_status = None
            self.plan.append_alloc(update, None)
        for update in results.attribute_updates.values():
            self.plan.append_alloc(update, None)

        if not results.place and not results.destructive_update:
            if self.job is not None:
                for tg in self.job.task_groups:
                    self.queued_allocs[tg.name] = 0
            return None

        for p in results.place:
            self.queued_allocs[p.task_group.name] = (
                self.queued_allocs.get(p.task_group.name, 0) + 1
            )
        for p in results.destructive_update:
            self.queued_allocs[p.place_task_group.name] = (
                self.queued_allocs.get(p.place_task_group.name, 0) + 1
            )
        return self._compute_placements(results)

    def _compute_placements(self, results: ReconcileResults) -> Optional[Exception]:
        """Destructive updates first (their resources free up), then new
        placements; each task group's asks batch into one kernel call."""
        deployment_id = ""
        if self.deployment is not None and self.deployment.active():
            deployment_id = self.deployment.id

        import time as _time

        now = _time.time()

        # group placement results by task group, preserving order
        ordered = list(results.destructive_update) + list(results.place)
        by_tg: Dict[str, List] = {}
        for missing in ordered:
            tg = missing.task_group if not hasattr(missing, "place_task_group") else missing.place_task_group
            by_tg.setdefault(tg.name, []).append(missing)

        for tg_name, missings in by_tg.items():
            tg = self.job.lookup_task_group(tg_name)
            if tg is None:
                continue
            if tg_name in self.failed_tg_allocs:
                self.failed_tg_allocs[tg_name].coalesced_failures += len(missings)
                continue

            requests = []
            for missing in missings:
                prev = missing.previous_alloc if hasattr(missing, "previous_alloc") else None
                penalty: List[str] = []
                preferred = ""
                if prev is not None:
                    is_resched = getattr(missing, "reschedule", False)
                    if is_resched:
                        penalty.append(prev.node_id)
                        if prev.reschedule_tracker:
                            for ev in prev.reschedule_tracker.events:
                                if ev.prev_node_id:
                                    penalty.append(ev.prev_node_id)
                    preferred = self._find_preferred_node(tg, prev) or ""
                # destructive updates stop their previous alloc first
                stop_prev, stop_desc = missing.stop_previous_alloc()
                if stop_prev and prev is not None:
                    self.plan.append_stopped_alloc(prev, stop_desc)
                requests.append(
                    SelectRequest(
                        name=missing.name,
                        prev_alloc=prev,
                        penalty_nodes=tuple(penalty),
                        preferred_node=preferred,
                    )
                )

            options = self.stack.select_many(tg, requests)
            preempt_ok = self._preemption_enabled()

            # the alloc-construction tail is the "plan build" slice of
            # the sched-host decomposition (bench/trace_report.py)
            self._append_placements(
                tg, tg_name, missings, requests, options, preempt_ok,
                deployment_id, now)
        return None

    def _append_placements(self, tg, tg_name, missings, requests,
                           options, preempt_ok, deployment_id,
                           now) -> None:
        with tracer.span("sched.planbuild"):
            self._append_placements_inner(
                tg, tg_name, missings, requests, options, preempt_ok,
                deployment_id, now)

    def _append_placements_inner(self, tg, tg_name, missings, requests,
                                 options, preempt_ok, deployment_id,
                                 now) -> None:
        for missing, req, option in zip(missings, requests, options):
            prev = req.prev_alloc
            if option is None and preempt_ok:
                # preemption second pass (generic_sched.go:800-819
                # selectNextOption), one slot at a time INSIDE the
                # placement loop: each call sees the plan with the
                # previous slots' placements and staged evictions,
                # so freed capacity and victims are never counted
                # twice across slots
                option = self.stack.select_preempting(tg, req)
            if option is None:
                if tg_name not in self.failed_tg_allocs:
                    m = self.ctx.metrics().copy()
                    m.nodes_in_pool = self._cluster.n_real
                    self.failed_tg_allocs[tg_name] = m
                else:
                    self.failed_tg_allocs[tg_name].coalesced_failures += 1
                # back out the staged stop of the previous alloc
                stop_prev, _ = missing.stop_previous_alloc()
                if stop_prev and prev is not None:
                    updates = self.plan.node_update.get(prev.node_id, [])
                    for i in range(len(updates) - 1, -1, -1):
                        if updates[i].id == prev.id:
                            updates.pop(i)
                            break
                continue

            if option.resources is not None:
                # lean fast path: the (job, tg)-shared frozen skeleton
                # (scheduler/scaffold.py) — no per-slot struct builds
                resources = option.resources
            else:
                from nomad_tpu.structs.resources import (
                    AllocatedResources,
                    AllocatedSharedResources,
                )

                resources = AllocatedResources(
                    tasks=option.task_resources,
                    task_lifecycles=option.task_lifecycles,
                    shared=AllocatedSharedResources(
                        disk_mb=tg.ephemeral_disk.size_mb
                    ),
                )
                if option.alloc_resources is not None:
                    resources.shared.networks = \
                        option.alloc_resources.networks
                    resources.shared.ports = option.alloc_resources.ports

            alloc = Allocation(
                id=generate_uuid(),
                namespace=self.job.namespace,
                eval_id=self.eval.id,
                name=missing.name if not hasattr(missing, "place_name") else missing.place_name,
                job_id=self.job.id,
                job_version=self.job.version,
                task_group=tg.name,
                metrics=option.metrics,
                node_id=option.node_id,
                node_name=option.node.name,
                deployment_id=deployment_id,
                allocated_resources=resources,
                desired_status=consts.ALLOC_DESIRED_RUN,
                client_status=consts.ALLOC_CLIENT_PENDING,
                create_time_ns=int(now * 1e9),
                modify_time_ns=int(now * 1e9),
            )
            if prev is not None:
                alloc.previous_allocation = prev.id
                if getattr(missing, "reschedule", False):
                    _update_reschedule_tracker(alloc, prev, now)
            # handlePreemptions (generic_sched.go:821-843)
            if option.preempted_allocs:
                preempted_ids = []
                for stop in option.preempted_allocs:
                    self.plan.append_preempted_alloc(stop, alloc.id)
                    preempted_ids.append(stop.id)
                    if self.eval.annotate_plan and self.plan.annotations is not None:
                        desired = self.plan.annotations.desired_tg_updates.get(tg.name)
                        if desired is not None:
                            desired.preemptions += 1
                alloc.preempted_allocations = preempted_ids
            if getattr(missing, "canary", False) and self.deployment is not None:
                from nomad_tpu.structs.alloc import AllocDeploymentStatus

                alloc.deployment_status = AllocDeploymentStatus(canary=True)
                dstate = self.deployment.task_groups.get(tg.name)
                if dstate is not None:
                    dstate.placed_canaries.append(alloc.id)

            self.plan.append_alloc(alloc, None)

    def _preemption_enabled(self) -> bool:
        """Scheduler-config preemption toggle for this job type
        (generic_sched.go:802-812; defaults: service/batch off)."""
        sched_type = self.job.type if self.job is not None else consts.JOB_TYPE_SERVICE
        return self.state.scheduler_config.preemption_enabled(sched_type)

    def _find_preferred_node(self, tg, prev) -> Optional[str]:
        """Sticky ephemeral disk prefers the previous node
        (generic_sched.go findPreferredNode)."""
        if prev is not None and tg.ephemeral_disk.sticky and not prev.should_migrate():
            return prev.node_id
        return None

    # -- status/blocked plumbing -----------------------------------------

    def _create_blocked_eval(self, plan_failure: bool) -> None:
        e = self.ctx.eligibility
        escaped = e.has_escaped()
        class_elig = None if escaped else e.get_classes()
        self.blocked = self.eval.create_blocked_eval(
            class_elig, escaped, e.quota_reached, self.failed_tg_allocs
        )
        if plan_failure:
            self.blocked.triggered_by = consts.EVAL_TRIGGER_MAX_PLAN_ATTEMPTS
            self.blocked.status_description = BLOCKED_EVAL_MAX_PLAN
        else:
            self.blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS
        self.planner.create_eval(self.blocked)

    def _set_status(self, status: str, desc: str) -> None:
        new_eval = self.eval.copy()
        new_eval.status = status
        new_eval.status_description = desc
        if self.blocked is not None:
            new_eval.blocked_eval = self.blocked.id
        if self.failed_tg_allocs:
            new_eval.failed_tg_allocs = dict(self.failed_tg_allocs)
        if self.queued_allocs:
            new_eval.queued_allocations = dict(self.queued_allocs)
        if self.deployment is not None:
            new_eval.deployment_id = self.deployment.id
        self.planner.update_eval(new_eval)


def _update_reschedule_tracker(alloc: Allocation, prev: Allocation, now: float) -> None:
    """generic_sched.go updateRescheduleTracker: carry forward events
    within the policy interval."""
    job = prev.job
    policy = job.reschedule_policy_for(prev.task_group) if job else None
    events: List[RescheduleEvent] = []
    if policy is not None:
        interval = policy.interval_s
        if prev.reschedule_tracker:
            for ev in prev.reschedule_tracker.events:
                if policy.unlimited or (
                    interval > 0 and now - ev.reschedule_time_ns / 1e9 <= interval
                ):
                    events.append(ev)
    events.append(
        RescheduleEvent(
            reschedule_time_ns=int(now * 1e9),
            prev_alloc_id=prev.id,
            prev_node_id=prev.node_id,
        )
    )
    alloc.reschedule_tracker = RescheduleTracker(events=events)


def _dead_job_stub(evaluation: Evaluation):
    """A stopped-job stand-in when the job was purged (the reconciler
    stops everything)."""
    from nomad_tpu.structs.job import Job

    return Job(id=evaluation.job_id, namespace=evaluation.namespace, stop=True)


def _service_factory(state, planner, **kw):
    return GenericScheduler(state, planner, batch=False, **kw)


def _batch_factory(state, planner, **kw):
    return GenericScheduler(state, planner, batch=True, **kw)


register_scheduler(consts.JOB_TYPE_SERVICE, _service_factory)
register_scheduler(consts.JOB_TYPE_BATCH, _batch_factory)
# the BASELINE.json north star: the XLA-batched binpack path IS the
# generic scheduler; the name registers explicitly for API parity
register_scheduler("xla-binpack", _service_factory)
