"""Plan-skeleton cache: the per-(job, tg) scaffold evals rebuild.

Every evaluation (and every retry attempt inside one) re-derives the
same job/tg-shaped state before it can launch a kernel: the flattened
``AskTensor``, the merged constraint list, the affinity list, the
distinct-hosts flags, and (post-feasibility-compiler) the compiled
mask program. None of it depends on the evaluation — only on the job
spec — so a wave of 32 members re-deriving it 32 times is pure
sched-host overhead (ROADMAP lever #1, "cache plan skeletons").

Two-level lookup:

- identity fast path: scaffolds are memoized per TaskGroup OBJECT
  (state-store job rows are immutable and shared by every eval of the
  job, so the tg's identity is stable across wave members, retry
  attempts, and follow-up evals); entries pin the tg and re-check
  identity, so a recycled ``id()`` can never alias a dead group;
- spec-shared slow path: scaffolds key the compiled mask program by
  the structural signature, so DIFFERENT jobs with equal constraint
  trees still share one program and one cached mask.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from nomad_tpu.tensors.schema import AskTensor
from nomad_tpu.utils.witness import witness_lock

__all__ = ["TGScaffold", "scaffold_for", "MetricsSkeleton"]

_LOCK = witness_lock("scaffold._LOCK")
_CACHE: "OrderedDict[int, Tuple[object, TGScaffold]]" = OrderedDict()
_CACHE_MAX = 512


class TGScaffold:
    """Spec-derived, eval-independent state for one (job, tg)."""

    __slots__ = ("ask", "affinities", "distinct_hosts_job",
                 "distinct_hosts_tg", "has_devices", "program",
                 "program_compiled", "lean_assign", "lean_ports",
                 "static_port_mask", "_tg", "_lean_res", "_lean_lock")

    def __init__(self, job, tg) -> None:
        from nomad_tpu.structs import consts

        self.ask: AskTensor = AskTensor.build(tg)
        affinities = list(job.affinities) + list(tg.affinities)
        for task in tg.tasks:
            affinities.extend(task.affinities)
        self.affinities: List = affinities
        self.distinct_hosts_job = any(
            con.operand == consts.CONSTRAINT_DISTINCT_HOSTS
            for con in job.constraints)
        self.distinct_hosts_tg = any(
            con.operand == consts.CONSTRAINT_DISTINCT_HOSTS
            for con in tg.constraints)
        self.has_devices = any(t.resources.devices for t in tg.tasks)
        # lean assignment: no group/task networks, devices, or reserved
        # cores anywhere in the group. For such asks the exact per-node
        # assignment (_NodeAssigner.assign) is PURE struct building —
        # it reads no node state and cannot fail — so placement
        # materialization shares ONE frozen resources skeleton per
        # (job, tg) instead of rebuilding the same structs per slot
        # (the vectorized-assembly move, ISSUE 6).
        self.lean_assign = (
            not tg.networks
            and not any(t.resources.networks for t in tg.tasks)
            and not any(t.resources.devices for t in tg.tasks)
            and not any(t.resources.cores > 0 for t in tg.tasks)
        )
        # static-port lean (ISSUE 10): ONE group network asking only
        # for concrete in-range reserved ports — no dynamic ports (the
        # stochastic picker reads node state), no bandwidth, no task
        # networks/devices/cores. For such asks the exact assigner's
        # only node-dependent work is the collision re-check, which the
        # kernel's port-conflict plane + the usage index's live port
        # bitmaps already prove — so placement can skip the
        # NetworkIndex build entirely (stack.select_many) and the plan
        # applier's ports-aware group check re-validates the claim.
        # Duplicate ports in the ask stay on the exact path.
        self.lean_ports = False
        self.static_port_mask = 0
        if (not self.lean_assign and len(tg.networks) == 1
                and not any(t.resources.networks for t in tg.tasks)
                and not any(t.resources.devices for t in tg.tasks)
                and not any(t.resources.cores > 0 for t in tg.tasks)):
            net = tg.networks[0]
            vals = [p.value for p in net.reserved_ports]
            if (vals and not net.dynamic_ports and not net.mbits
                    and all(0 <= v < 65536 for v in vals)
                    and len(set(vals)) == len(vals)):
                self.lean_ports = True
                for v in vals:
                    self.static_port_mask |= 1 << v
        self._tg = tg
        self._lean_res: Dict[bool, Tuple] = {}
        self._lean_lock = witness_lock("TGScaffold._lean_lock")
        # compiled mask program (None = Python-builder fallback); the
        # program cache dedupes by signature across jobs
        from nomad_tpu.feasibility import default_mask_cache

        self.program = default_mask_cache.program_for(job, tg)
        self.program_compiled = self.program is not None

    def lean_planes(self, oversub: bool) -> Tuple:  # graft: frozen
        """(task_resources, task_lifecycles, AllocatedResources) for a
        lean placement, built once per (job, tg, oversub) and shared BY
        REFERENCE across every slot, wave member, and retry attempt.

        Sound because allocated resources are replaced, never mutated
        in place, repo-wide (the convention ``Allocation.fit_meta``'s
        identity-keyed cache already relies on); the non-lean paths
        (networks/devices/cores) keep building per-slot structs."""
        ent = self._lean_res.get(bool(oversub))
        if ent is not None:
            return ent
        from nomad_tpu.structs.resources import (
            AllocatedCpuResources,
            AllocatedMemoryResources,
            AllocatedResources,
            AllocatedSharedResources,
            AllocatedTaskResources,
        )

        tg = self._tg
        task_resources = {}
        task_lifecycles = {}
        for task in tg.tasks:
            r = task.resources
            task_resources[task.name] = AllocatedTaskResources(
                cpu=AllocatedCpuResources(cpu_shares=int(r.cpu)),
                memory=AllocatedMemoryResources(
                    memory_mb=int(r.memory_mb),
                    memory_max_mb=(int(r.memory_max_mb)
                                   if oversub else 0),
                ),
            )
            task_lifecycles[task.name] = task.lifecycle
        resources = AllocatedResources(
            tasks=task_resources,
            task_lifecycles=task_lifecycles,
            shared=AllocatedSharedResources(
                disk_mb=tg.ephemeral_disk.size_mb),
        )
        with self._lean_lock:
            return self._lean_res.setdefault(
                bool(oversub),
                (task_resources, task_lifecycles, resources))


class MetricsSkeleton:
    """One kernel launch's shared AllocMetric header + lazy top-k.

    Every slot of a ``select_many`` call reports the same header counts
    (nodes evaluated/filtered/exhausted — they come from one mask
    reduction); only score_meta differs per slot. The skeleton holds
    the header ONCE plus the launch's top-k planes (possibly still
    device-resident — coalesce._TopKSlice), and materializes per-slot
    ``AllocMetric``s cheaply: dicts are copied only when non-empty, and
    the top-k -> score_meta fill is deferred onto the plan window
    (Plan.deferred_work), where the first slot's access triggers the
    wave's single shared d2h fetch.
    """

    __slots__ = ("nodes_evaluated", "nodes_filtered", "nodes_exhausted",
                 "constraint_filtered", "dimension_exhausted",
                 "topk_idx", "topk_scores", "_host")

    def __init__(self, nodes_evaluated: int, nodes_filtered: int,
                 nodes_exhausted: int, constraint_filtered: Dict,
                 dimension_exhausted: Dict, topk_idx, topk_scores) -> None:
        self.nodes_evaluated = nodes_evaluated
        self.nodes_filtered = nodes_filtered
        self.nodes_exhausted = nodes_exhausted
        self.constraint_filtered = constraint_filtered
        self.dimension_exhausted = dimension_exhausted
        self.topk_idx = topk_idx
        self.topk_scores = topk_scores
        self._host = None

    def materialize(self):
        """A per-slot AllocMetric carrying the shared header."""
        from nomad_tpu.structs.alloc import AllocMetric

        m = AllocMetric()
        m.nodes_evaluated = self.nodes_evaluated
        m.nodes_filtered = self.nodes_filtered
        m.nodes_exhausted = self.nodes_exhausted
        if self.constraint_filtered:
            m.constraint_filtered = dict(self.constraint_filtered)
        if self.dimension_exhausted:
            m.dimension_exhausted.update(self.dimension_exhausted)
        return m

    def slot_topk(self, slot: int):
        """(rows, scores) numpy for one slot; resolves the launch's
        top-k planes to host ONCE for all slots (runs inside the plan
        window's deferred drain, off the wave-critical path)."""
        if self._host is None:
            import numpy as np

            self._host = (np.asarray(self.topk_idx),
                          np.asarray(self.topk_scores))
        return self._host[0][slot], self._host[1][slot]


def scaffold_for(job, tg) -> TGScaffold:
    """The (job, tg) scaffold, memoized per tg object.

    AskTensor.build can raise AskLimitError — it happens before the
    cache insert, so the limit error surfaces per eval exactly as
    before and never caches a half-built scaffold."""
    key = id(tg)
    ent = _CACHE.get(key)
    if ent is not None and ent[0] is tg:
        return ent[1]
    built = TGScaffold(job, tg)
    with _LOCK:
        ent = _CACHE.get(key)
        if ent is not None and ent[0] is tg:
            return ent[1]
        _CACHE[key] = (tg, built)
        _CACHE.move_to_end(key)
        while len(_CACHE) > _CACHE_MAX:
            _CACHE.popitem(last=False)
    return built
