"""Plan-skeleton cache: the per-(job, tg) scaffold evals rebuild.

Every evaluation (and every retry attempt inside one) re-derives the
same job/tg-shaped state before it can launch a kernel: the flattened
``AskTensor``, the merged constraint list, the affinity list, the
distinct-hosts flags, and (post-feasibility-compiler) the compiled
mask program. None of it depends on the evaluation — only on the job
spec — so a wave of 32 members re-deriving it 32 times is pure
sched-host overhead (ROADMAP lever #1, "cache plan skeletons").

Two-level lookup:

- identity fast path: scaffolds are memoized per TaskGroup OBJECT
  (state-store job rows are immutable and shared by every eval of the
  job, so the tg's identity is stable across wave members, retry
  attempts, and follow-up evals); entries pin the tg and re-check
  identity, so a recycled ``id()`` can never alias a dead group;
- spec-shared slow path: scaffolds key the compiled mask program by
  the structural signature, so DIFFERENT jobs with equal constraint
  trees still share one program and one cached mask.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional, Tuple

from nomad_tpu.tensors.schema import AskTensor

__all__ = ["TGScaffold", "scaffold_for"]

_LOCK = threading.Lock()
_CACHE: "OrderedDict[int, Tuple[object, TGScaffold]]" = OrderedDict()
_CACHE_MAX = 512


class TGScaffold:
    """Spec-derived, eval-independent state for one (job, tg)."""

    __slots__ = ("ask", "affinities", "distinct_hosts_job",
                 "distinct_hosts_tg", "has_devices", "program",
                 "program_compiled")

    def __init__(self, job, tg) -> None:
        from nomad_tpu.structs import consts

        self.ask: AskTensor = AskTensor.build(tg)
        affinities = list(job.affinities) + list(tg.affinities)
        for task in tg.tasks:
            affinities.extend(task.affinities)
        self.affinities: List = affinities
        self.distinct_hosts_job = any(
            con.operand == consts.CONSTRAINT_DISTINCT_HOSTS
            for con in job.constraints)
        self.distinct_hosts_tg = any(
            con.operand == consts.CONSTRAINT_DISTINCT_HOSTS
            for con in tg.constraints)
        self.has_devices = any(t.resources.devices for t in tg.tasks)
        # compiled mask program (None = Python-builder fallback); the
        # program cache dedupes by signature across jobs
        from nomad_tpu.feasibility import default_mask_cache

        self.program = default_mask_cache.program_for(job, tg)
        self.program_compiled = self.program is not None


def scaffold_for(job, tg) -> TGScaffold:
    """The (job, tg) scaffold, memoized per tg object.

    AskTensor.build can raise AskLimitError — it happens before the
    cache insert, so the limit error surfaces per eval exactly as
    before and never caches a half-built scaffold."""
    key = id(tg)
    ent = _CACHE.get(key)
    if ent is not None and ent[0] is tg:
        return ent[1]
    built = TGScaffold(job, tg)
    with _LOCK:
        ent = _CACHE.get(key)
        if ent is not None and ent[0] is tg:
            return ent[1]
        _CACHE[key] = (tg, built)
        _CACHE.move_to_end(key)
        while len(_CACHE) > _CACHE_MAX:
            _CACHE.popitem(last=False)
    return built
