"""Host-side feasibility: the ragged checks that feed the kernel's base mask.

Reference behavior: scheduler/feasible.go. The per-node iterator checkers
become one vectorized mask build:

- ready/DC membership: readyNodesInDCs (util.go:351) as numpy selects
- ConstraintChecker (:730) for job + task group + task constraints,
  memoized per computed node class via EvalEligibility (the
  FeasibilityWrapper cache, :1050); 'escaping' constraints on unique
  properties are evaluated per node, exactly like the reference's
  escaped-class path
- DriverChecker (:454): required drivers healthy (class-level)
- HostVolumeChecker (:135): per-node host volume presence
- CSIVolumeChecker (:212): per-node plugin presence (volume claims land
  with the CSI subsystem)
- DeviceChecker (:1193): device existence/count via the device planes
- DistinctHostsIterator (:526) / DistinctPropertyIterator (:625):
  proposed-alloc-dependent masks built from the job's allocations
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from nomad_tpu.scheduler.context import ELIGIBILITY_UNKNOWN, ELIGIBLE, INELIGIBLE, EvalContext
from nomad_tpu.structs import consts
from nomad_tpu.structs.constraints import (
    Constraint,
    node_meets_constraints,
    resolve_target,
)
from nomad_tpu.tensors.schema import ClusterTensors

FILTER_CONSTRAINT_HOST_VOLUMES = "missing compatible host volumes"
FILTER_CONSTRAINT_CSI_PLUGINS = "missing CSI plugins"
FILTER_CONSTRAINT_DRIVERS = "missing drivers"
FILTER_CONSTRAINT_DEVICES = "missing devices"


def merged_tg_constraints(tg) -> List[Constraint]:
    """Task-group-level constraint set: tg constraints + each task's
    (the reference wires these as separate checkers in the same
    FeasibilityWrapper, stack.go:365-377)."""
    out = list(tg.constraints)
    for task in tg.tasks:
        out.extend(task.constraints)
    return out


def required_drivers(tg) -> List[str]:
    return sorted({task.driver for task in tg.tasks})


def driver_ok(node, drivers: List[str]) -> bool:
    """DriverChecker (feasible.go:454): driver fingerprinted healthy."""
    for d in drivers:
        info = node.drivers.get(d)
        if info is not None:
            if not (info.detected and info.healthy):
                return False
            continue
        # fall back to attribute-based detection (driver.<name> = "1")
        raw = node.attributes.get(f"driver.{d}")
        if raw is None or str(raw) not in ("1", "true", "True"):
            return False
    return True


def host_volumes_ok(node, tg) -> bool:
    """HostVolumeChecker (feasible.go:135)."""
    for req in tg.volumes.values():
        if req.type != "host":
            continue
        vol = node.host_volumes.get(req.source)
        if vol is None:
            return False
        if vol.read_only and not req.read_only:
            return False
    return True


def csi_ok(node, tg, snapshot=None, namespace: str = "default") -> bool:
    """CSIVolumeChecker (feasible.go:212): the node must run a healthy
    instance of each claimed volume's plugin, and the volume itself must
    have claim capacity for the requested mode (csi.go
    WriteSchedulable/ReadSchedulable)."""
    from nomad_tpu.structs import csi as csi_structs

    for req in tg.volumes.values():
        if req.type != "csi":
            continue
        vol = None
        if snapshot is not None and hasattr(snapshot, "csi_volume_by_id"):
            vol = snapshot.csi_volume_by_id(namespace, req.source)
        if vol is None:
            # no registered volume: fall back to plugin presence keyed
            # by source (pre-registration dev mode)
            if req.source not in node.csi_node_plugins:
                return False
            continue
        info = node.csi_node_plugins.get(vol.plugin_id)
        if info is None or not info.get("healthy", False):
            return False
        mode = csi_structs.CLAIM_READ if req.read_only \
            else csi_structs.CLAIM_WRITE
        if not vol.claimable(mode):
            return False
    return True


def devices_exist(node, tg) -> bool:
    """DeviceChecker.hasDevices (feasible.go:1238) -- count-aware
    existence check; precise availability is the kernel's dev planes."""
    from nomad_tpu.scheduler.device import node_device_matches

    required = []
    for task in tg.tasks:
        required.extend(task.resources.devices)
    if not required:
        return True
    if not node.node_resources.devices:
        return False
    available = {d.id_string(): len(d.available_ids()) for d in node.node_resources.devices}
    groups = {d.id_string(): d for d in node.node_resources.devices}
    for req in required:
        placed = False
        for gid, unused in available.items():
            if unused < req.count:
                continue
            if node_device_matches(groups[gid], req):
                available[gid] -= req.count
                placed = True
                break
        if not placed:
            return False
    return True


def eligible_in_dcs(c: ClusterTensors, datacenters: List[str],
                    node_pool: str = "default") -> np.ndarray:
    """readyNodesInDCs (util.go:351) as a mask; a job's node_pool
    restricts to matching nodes ('all' is the match-everything pool).

    Module-level so the feasibility compiler's evaluation engine
    (nomad_tpu/feasibility/runtime.py) runs EXACTLY this code for its
    cached masks — bit-identity with the per-eval builder holds by
    construction, not by reimplementation."""
    mask = c.ready.copy()
    dcs = set(datacenters)
    wildcard = any("*" in dc for dc in dcs)
    if not wildcard and hasattr(c, "dc_pool_arrays"):
        # vectorized fast path (no glob patterns in the job's DCs)
        dc_arr, pool_arr = c.dc_pool_arrays()
        mask &= np.isin(dc_arr, list(dcs))
        if node_pool and node_pool != "all":
            mask &= pool_arr == node_pool
        return mask
    for i in range(c.n_real):
        if c.datacenters[i] not in dcs:
            if not (wildcard and _dc_glob_match(dcs, c.datacenters[i])):
                mask[i] = False
                continue
        if node_pool and node_pool != "all" and c.node_pools[i] != node_pool:
            mask[i] = False
    return mask


class FeasibilityBuilder:
    """Builds base_mask[n_pad] for one (job, task group)."""

    def __init__(self, cluster: ClusterTensors, snapshot, ctx: EvalContext) -> None:
        self.cluster = cluster
        self.snapshot = snapshot
        self.ctx = ctx
        # rows grouped by computed class, built lazily once per eval
        self._class_rows: Optional[Dict[str, List[int]]] = None

    def _classes(self) -> Dict[str, List[int]]:
        if self._class_rows is None:
            self._class_rows = self.cluster.class_rows()
        return self._class_rows

    def eligible_in_dcs(self, datacenters: List[str], node_pool: str = "default") -> np.ndarray:
        return eligible_in_dcs(self.cluster, datacenters, node_pool)

    def base_mask(self, job, tg, job_allocs_by_node: Dict[str, List]) -> np.ndarray:
        """The full host-side feasibility plane."""
        c = self.cluster
        mask = self.eligible_in_dcs(job.datacenters, job.node_pool)
        elig = self.ctx.eligibility
        metrics = self.ctx.metrics()

        job_cons = list(job.constraints)
        tg_cons = merged_tg_constraints(tg)
        drivers = required_drivers(tg)
        escaped = elig.has_escaped()

        # node objects are immutable per snapshot; the cluster build's
        # map avoids an O(N) dict rebuild per evaluation
        nodes_by_id = c.nodes_by_id or {
            nid: self.snapshot.node_by_id(nid) for nid in c.node_ids
        }

        # class-memoized job + tg checks
        for cls, rows in self._classes().items():
            live = [i for i in rows if i < c.n_real and mask[i]]
            if not live:
                continue
            rep = nodes_by_id.get(c.node_ids[live[0]])
            if rep is None:
                for i in live:
                    mask[i] = False
                continue

            # job-level constraints
            st = elig.job_status(cls) if not escaped else ELIGIBILITY_UNKNOWN
            if st == ELIGIBILITY_UNKNOWN:
                ok = node_meets_constraints(rep, job_cons)
                if not escaped:
                    elig.set_job_eligibility(ok, cls)
            else:
                ok = st == ELIGIBLE
            if not ok and not escaped:
                for i in live:
                    mask[i] = False
                    metrics.filter_node(nodes_by_id.get(c.node_ids[i]), "job constraints")
                continue

            # tg-level constraints + drivers + device existence
            st = elig.tg_status(tg.name, cls) if not escaped else ELIGIBILITY_UNKNOWN
            if st == ELIGIBILITY_UNKNOWN:
                ok_tg = (
                    node_meets_constraints(rep, tg_cons)
                    and driver_ok(rep, drivers)
                    and devices_exist(rep, tg)
                )
                if not escaped:
                    elig.set_tg_eligibility(ok_tg, tg.name, cls)
            else:
                ok_tg = st == ELIGIBLE
            if not escaped:
                if not ok_tg:
                    for i in live:
                        mask[i] = False
                        metrics.filter_node(nodes_by_id.get(c.node_ids[i]), "task group constraints")
                    continue
            else:
                # escaped: evaluate everything per node
                for i in live:
                    node = nodes_by_id.get(c.node_ids[i])
                    if node is None or not (
                        node_meets_constraints(node, job_cons)
                        and node_meets_constraints(node, tg_cons)
                        and driver_ok(node, drivers)
                        and devices_exist(node, tg)
                    ):
                        mask[i] = False
                        if node is not None:
                            metrics.filter_node(node, "constraints")

        # per-node ragged checks (cheap dict lookups)
        has_host_vols = any(v.type == "host" for v in tg.volumes.values())
        has_csi_vols = any(v.type == "csi" for v in tg.volumes.values())
        if has_host_vols or has_csi_vols:
            for i in range(c.n_real):
                if not mask[i]:
                    continue
                node = nodes_by_id.get(c.node_ids[i])
                if node is None:
                    mask[i] = False
                    continue
                if has_host_vols and not host_volumes_ok(node, tg):
                    mask[i] = False
                    metrics.filter_node(node, FILTER_CONSTRAINT_HOST_VOLUMES)
                elif has_csi_vols and not csi_ok(
                    node, tg, self.snapshot, job.namespace
                ):
                    mask[i] = False
                    metrics.filter_node(node, FILTER_CONSTRAINT_CSI_PLUGINS)

        # distinct_hosts / distinct_property
        self._apply_distinct(mask, job, tg, job_allocs_by_node, nodes_by_id)
        return mask

    # -- distinct constraints --------------------------------------------

    def _apply_distinct(self, mask, job, tg, job_allocs_by_node, nodes_by_id) -> None:
        c = self.cluster
        job_distinct = any(
            con.operand == consts.CONSTRAINT_DISTINCT_HOSTS for con in job.constraints
        )
        tg_distinct = any(
            con.operand == consts.CONSTRAINT_DISTINCT_HOSTS for con in tg.constraints
        )
        if job_distinct or tg_distinct:
            # DistinctHostsIterator (feasible.go:526): no co-location with
            # the job's (or group's) other live allocs
            for i in range(c.n_real):
                if not mask[i]:
                    continue
                allocs = job_allocs_by_node.get(c.node_ids[i], ())
                for a in allocs:
                    if a.terminal_status():
                        continue
                    if job_distinct and a.job_id == job.id:
                        mask[i] = False
                        break
                    if tg_distinct and a.job_id == job.id and a.task_group == tg.name:
                        mask[i] = False
                        break

        # DistinctPropertyIterator (feasible.go:625)
        for con in list(job.constraints) + list(tg.constraints):
            if con.operand != consts.CONSTRAINT_DISTINCT_PROPERTY:
                continue
            limit = 1
            if con.rtarget:
                try:
                    limit = int(con.rtarget)
                except ValueError:
                    limit = 1
            tg_scope = con in tg.constraints
            counts: Dict[str, int] = {}
            for nid, allocs in job_allocs_by_node.items():
                node = nodes_by_id.get(nid) or self.snapshot.node_by_id(nid)
                if node is None:
                    continue
                val, ok = resolve_target(con.ltarget, node)
                if not ok:
                    continue
                for a in allocs:
                    if a.terminal_status() or a.job_id != job.id:
                        continue
                    if tg_scope and a.task_group != tg.name:
                        continue
                    counts[val] = counts.get(val, 0) + 1
            for i in range(c.n_real):
                if not mask[i]:
                    continue
                node = nodes_by_id.get(c.node_ids[i])
                if node is None:
                    continue
                val, ok = resolve_target(con.ltarget, node)
                if not ok:
                    mask[i] = False
                    continue
                if counts.get(val, 0) >= limit:
                    mask[i] = False


def _dc_glob_match(patterns, dc: str) -> bool:
    import fnmatch

    return any(fnmatch.fnmatch(dc, p) for p in patterns if "*" in p)
