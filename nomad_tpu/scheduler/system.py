"""SystemScheduler: one alloc per eligible node (system + sysbatch).

Reference behavior: scheduler/scheduler_system.go (:27-527): per-node
diff instead of the reconciler -- place on every feasible node missing
an alloc, stop allocs on ineligible/removed nodes, update on job change.

TPU formulation: feasibility for ALL nodes computes in one kernel pass
(the mask planes), then exact host assignment runs per placed node --
there is no scoring/argmax because system jobs place everywhere feasible.
"""

from __future__ import annotations

import time as _time
import uuid
from typing import Dict, List, Optional

import jax
import numpy as np

from nomad_tpu.ops.kernel import FULL_FEATURES, KernelIn, _feasible, build_kernel_in
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.scheduler import (
    Planner,
    Scheduler,
    SchedulerState,
    SetStatusError,
    progress_made,
    register_scheduler,
    retry_max,
)
from nomad_tpu.scheduler.stack import XLAGenericStack, _NodeAssigner
from nomad_tpu.scheduler.util import (
    tainted_nodes,
    tasks_updated,
    update_non_terminal_allocs_to_lost,
)
from nomad_tpu.structs import consts
from nomad_tpu.structs.alloc import AllocMetric, Allocation
from nomad_tpu.structs.eval_plan import Evaluation
from nomad_tpu.tensors.schema import ClusterTensors

MAX_SYSTEM_ATTEMPTS = 5     # scheduler_system.go:20
MAX_SYSBATCH_ATTEMPTS = 2


@jax.jit
def _feasible_mask_jit(kin: KernelIn):
    st = dict(
        used_cpu=kin.used_cpu, used_mem=kin.used_mem, used_disk=kin.used_disk,
        used_cores=kin.used_cores, used_mbits=kin.used_mbits,
        free_dyn=kin.free_dyn, port_conflict=kin.port_conflict,
        dev_free=kin.dev_free, job_tg_count=kin.job_tg_count,
        job_any_count=kin.job_any_count, spread_counts=kin.spread_counts,
    )
    feasible, _, dims = _feasible(kin, st, FULL_FEATURES)
    return feasible, dims


class SystemScheduler(Scheduler):
    def __init__(self, state: SchedulerState, planner: Planner,
                 sysbatch: bool = False, events_cb=None,
                 kernel_launch=None, cluster_provider=None) -> None:
        self.state = state
        self.planner = planner
        self.sysbatch = sysbatch
        self.events_cb = events_cb
        self.kernel_launch = kernel_launch
        self.cluster_provider = cluster_provider
        self.eval: Optional[Evaluation] = None
        self.job = None
        self.plan = None
        self.plan_result = None
        self.ctx: Optional[EvalContext] = None
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}
        self.queued_allocs: Dict[str, int] = {}

    def process(self, evaluation: Evaluation) -> None:
        self.eval = evaluation
        valid = {
            consts.EVAL_TRIGGER_JOB_REGISTER, consts.EVAL_TRIGGER_JOB_DEREGISTER,
            consts.EVAL_TRIGGER_NODE_UPDATE, consts.EVAL_TRIGGER_NODE_DRAIN,
            consts.EVAL_TRIGGER_ALLOC_STOP, consts.EVAL_TRIGGER_ROLLING_UPDATE,
            consts.EVAL_TRIGGER_PERIODIC_JOB, consts.EVAL_TRIGGER_MAX_PLAN_ATTEMPTS,
            consts.EVAL_TRIGGER_QUEUED_ALLOCS, consts.EVAL_TRIGGER_SCALING,
            consts.EVAL_TRIGGER_RECONNECT,
        }
        if evaluation.triggered_by not in valid:
            self._set_status(
                consts.EVAL_STATUS_FAILED,
                f"scheduler cannot handle '{evaluation.triggered_by}' evaluation reason",
            )
            return
        limit = MAX_SYSBATCH_ATTEMPTS if self.sysbatch else MAX_SYSTEM_ATTEMPTS
        try:
            retry_max(limit, self._process, lambda: progress_made(self.plan_result))
        except SetStatusError as e:
            self._set_status(e.eval_status, e.desc)
            return
        self._set_status(consts.EVAL_STATUS_COMPLETE, "")

    def _process(self):
        self.job = self.state.job_by_id(self.eval.namespace, self.eval.job_id)
        self.plan = self.eval.make_plan(self.job)
        self.failed_tg_allocs = {}
        self.queued_allocs = {}
        self.ctx = EvalContext(self.state, self.plan, events_cb=self.events_cb,
                               kernel_launch=self.kernel_launch)
        # decorrelate concurrent evals' dynamic-port picks, like the
        # generic scheduler (network.go:598 stochastic selection)
        import zlib

        self.ctx.port_seed = zlib.crc32(
            f"{self.eval.id}:{self.state.latest_index()}".encode()
        )

        allocs = self.state.allocs_by_job(self.eval.namespace, self.eval.job_id)
        tainted = tainted_nodes(self.state, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        live_allocs = [a for a in allocs if not a.terminal_status()]

        stopped = self.job is None or self.job.stopped()
        if stopped:
            for a in live_allocs:
                self.plan.append_stopped_alloc(a, "alloc not needed due to job update")
        else:
            self._compute_system_placements(live_allocs, tainted)

        if self.plan.is_no_op():
            return True, None
        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result
        if new_state is not None:
            self.state = new_state
            return False, None
        full, _, _ = result.full_commit(self.plan)
        if not full:
            return False, None
        return True, None

    def _compute_system_placements(self, live_allocs: List[Allocation], tainted) -> None:
        if self.cluster_provider is not None:
            cluster = self.cluster_provider(self.state)
        else:
            from nomad_tpu.parallel.coalesce import default_cluster_cache

            cluster = default_cluster_cache.get(self.state)
        stack = XLAGenericStack(False, self.ctx, cluster)
        stack.set_job(self.job)
        now = _time.time()

        by_node_tg: Dict[tuple, List[Allocation]] = {}
        for a in live_allocs:
            by_node_tg.setdefault((a.node_id, a.task_group), []).append(a)

        eligible_rows = set()
        for tg in self.job.task_groups:
            ev = stack._build_eval_tensors(tg, np.zeros(cluster.n_pad, bool))
            kin = build_kernel_in(cluster, ev, 1)
            feasible, dims = _feasible_mask_jit(kin)
            feasible = np.asarray(feasible)

            placed = 0
            for i in range(cluster.n_real):
                nid = cluster.node_ids[i]
                node = self.state.node_by_id(nid)
                existing = by_node_tg.get((nid, tg.name), [])
                node_ok = node is not None and node.ready() and nid not in tainted

                if existing:
                    if not node_ok:
                        # drain/down handling: reschedule via lost marking
                        for a in existing:
                            if node is None or node.status == consts.NODE_STATUS_DOWN:
                                self.plan.append_stopped_alloc(
                                    a, "alloc lost since its node is down",
                                    consts.ALLOC_CLIENT_LOST,
                                )
                            else:
                                self.plan.append_stopped_alloc(
                                    a, "alloc not needed as node is tainted"
                                )
                        continue
                    # job version update check
                    a0 = existing[0]
                    if a0.job is not None and a0.job.job_modify_index != self.job.job_modify_index:
                        if tasks_updated(self.job, a0.job, tg.name):
                            # evict first so the fit check sees the node
                            # without the old alloc (scheduler_system.go
                            # evictAndPlace ordering)
                            self.plan.append_stopped_alloc(
                                a0, "alloc is being updated due to job update"
                            )
                            if self._fits_after_evict(node, tg):
                                self._place_on(cluster, tg, i, now)
                                placed += 1
                            else:
                                m = self.failed_tg_allocs.setdefault(
                                    tg.name, AllocMetric()
                                )
                                m.exhausted_node(node, "resources")
                        else:
                            update = a0.copy_skip_job()
                            update.eval_id = self.eval.id
                            update.job = None
                            self.plan.append_alloc(update, None)
                    continue

                if not node_ok or not ev.base_mask[i]:
                    continue
                if not feasible[i]:
                    # preemption attempt (scheduler_system.go: system
                    # preemption defaults on) before reporting exhaustion
                    if self.state.scheduler_config.preemption_enabled(
                        self.job.type
                    ) and self._place_preempting(cluster, tg, i, now):
                        placed += 1
                        continue
                    # resource-exhausted eligible node -> failed placement
                    m = self.failed_tg_allocs.setdefault(tg.name, AllocMetric())
                    m.exhausted_node(node, "resources")
                    self.queued_allocs[tg.name] = self.queued_allocs.get(tg.name, 0)
                    continue
                self._place_on(cluster, tg, i, now)
                placed += 1
            self.queued_allocs.setdefault(tg.name, 0)

    def _fits_after_evict(self, node, tg) -> bool:
        """Host-side fit re-check with plan-staged evictions excluded."""
        from nomad_tpu.structs.resources import allocs_fit
        from nomad_tpu.tensors.schema import AskTensor

        ask = AskTensor.build(tg)
        proposed = self.ctx.proposed_allocs(node.id)
        probe = Allocation(
            id="_probe",
            allocated_resources=_ask_to_allocated(ask),
        )
        fit, _, _ = allocs_fit(node, proposed + [probe])
        return fit

    def _place_preempting(self, cluster, tg, row: int, now: float) -> bool:
        """Evict lower-priority allocs on this node so the system alloc
        fits (the SystemScheduler preemption branch)."""
        from nomad_tpu.scheduler.preemption import Preemptor
        from nomad_tpu.scheduler.stack import _tg_comparable_ask

        node = self.state.node_by_id(cluster.node_ids[row])
        if node is None:
            return False
        proposed = self.ctx.proposed_allocs(node.id)
        preemptor = Preemptor(self.job.priority, self.job.namespace, self.job.id)
        preemptor.set_node(node)
        preemptor.set_candidates(proposed)
        preemptor.set_preemptions(
            [a for allocs in self.plan.node_preemptions.values() for a in allocs]
        )
        victims = preemptor.preempt_for_task_group(_tg_comparable_ask(tg))
        if not victims:
            return False
        victim_ids = {a.id for a in victims}
        remaining = [a for a in proposed if a.id not in victim_ids]
        return self._place_on(cluster, tg, row, now,
                              proposed=remaining, victims=victims)

    def _place_on(self, cluster, tg, row: int, now: float,
                  proposed=None, victims=None) -> bool:
        node = self.state.node_by_id(cluster.node_ids[row])
        assigner = _NodeAssigner(node, self.ctx, proposed=proposed)
        option = assigner.assign(tg, 0.0)
        if option is None:
            # the preempting path's caller records the exhaustion on
            # fall-through; recording here too would double count
            if victims is None:
                m = self.failed_tg_allocs.setdefault(tg.name, AllocMetric())
                m.exhausted_node(node, "resources")
            return False
        from nomad_tpu.structs.resources import (
            AllocatedResources,
            AllocatedSharedResources,
        )

        resources = AllocatedResources(
            tasks=option.task_resources,
            task_lifecycles=option.task_lifecycles,
            shared=AllocatedSharedResources(disk_mb=tg.ephemeral_disk.size_mb),
        )
        if option.alloc_resources is not None:
            resources.shared.networks = option.alloc_resources.networks
            resources.shared.ports = option.alloc_resources.ports
        alloc = Allocation(
            id=str(uuid.uuid4()),
            namespace=self.job.namespace,
            eval_id=self.eval.id,
            name=f"{self.job.id}.{tg.name}[0]",
            job_id=self.job.id,
            job_version=self.job.version,
            task_group=tg.name,
            metrics=AllocMetric(),
            node_id=option.node_id,
            node_name=node.name,
            allocated_resources=resources,
            desired_status=consts.ALLOC_DESIRED_RUN,
            client_status=consts.ALLOC_CLIENT_PENDING,
            create_time_ns=int(now * 1e9),
            modify_time_ns=int(now * 1e9),
        )
        if victims:
            preempted_ids = []
            for stop in victims:
                self.plan.append_preempted_alloc(stop, alloc.id)
                preempted_ids.append(stop.id)
            alloc.preempted_allocations = preempted_ids
        self.plan.append_alloc(alloc, None)
        return True

    def _set_status(self, status: str, desc: str) -> None:
        new_eval = self.eval.copy()
        new_eval.status = status
        new_eval.status_description = desc
        if self.failed_tg_allocs:
            new_eval.failed_tg_allocs = dict(self.failed_tg_allocs)
        if self.queued_allocs:
            new_eval.queued_allocations = dict(self.queued_allocs)
        self.planner.update_eval(new_eval)


def _ask_to_allocated(ask):
    from nomad_tpu.structs.resources import (
        AllocatedCpuResources,
        AllocatedMemoryResources,
        AllocatedResources,
        AllocatedSharedResources,
        AllocatedTaskResources,
    )

    return AllocatedResources(
        tasks={
            "_probe": AllocatedTaskResources(
                cpu=AllocatedCpuResources(cpu_shares=int(ask.cpu)),
                memory=AllocatedMemoryResources(memory_mb=int(ask.mem)),
            )
        },
        shared=AllocatedSharedResources(disk_mb=int(ask.disk)),
    )


def _system_factory(state, planner, **kw):
    return SystemScheduler(state, planner, sysbatch=False, **kw)


def _sysbatch_factory(state, planner, **kw):
    return SystemScheduler(state, planner, sysbatch=True, **kw)


register_scheduler(consts.JOB_TYPE_SYSTEM, _system_factory)
register_scheduler(consts.JOB_TYPE_SYSBATCH, _sysbatch_factory)
