"""Preemption: evicting lower-priority allocs to place higher-priority work.

Reference behavior: scheduler/preemption.go (Preemptor, :96;
PreemptForTaskGroup :199; filterSuperset :702; basicResourceDistance
:608; scoreForTaskGroup :641; filterAndGroupPreemptibleAllocs :666) and
rank.go (PreemptionScoringIterator :799, netPriority :835,
preemptionScore :858). Only allocations whose job priority is more than
PRIORITY_DELTA below the placing job's are eligible; selection greedily
minimizes multi-dimensional resource distance, then a superset-filter
pass drops evictions another pick already covers.

TPU reformulation: the reference runs the Preemptor inside
BinPackIterator for every candidate node as iteration reaches it. Here
the *candidate filter* is vectorized — numpy planes of per-node
preemptible cpu/mem/disk are added to the free planes and the
binpack+preemption score is computed for every node at once — and the
exact greedy eviction-set selection runs host-side only for the ranked
top candidates (the same host-exact/device-wide split as the port and
device assigners in stack.py).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from nomad_tpu.structs.resources import ComparableResources

# score penalty once a job/tg's in-plan preemptions exceed its migrate
# max_parallel (preemption.go:14 maxParallelPenalty)
MAX_PARALLEL_PENALTY = 50.0
# jobPriority - alloc priority must exceed this for eligibility
# (preemption.go:663 "within a delta of 10")
PRIORITY_DELTA = 10
# logistic preemption-score curve constants (rank.go:858-868)
_PREEMPTION_SCORE_RATE = 0.0048
_PREEMPTION_SCORE_ORIGIN = 2048.0


def basic_resource_distance(ask: ComparableResources,
                            used: ComparableResources) -> float:
    """Euclidean distance in normalized (cpu, mem, disk) space
    (preemption.go:608). Lower is a closer fit."""
    mem_c = cpu_c = disk_c = 0.0
    if ask.memory_mb > 0:
        mem_c = (float(ask.memory_mb) - float(used.memory_mb)) / float(ask.memory_mb)
    if ask.cpu_shares > 0:
        cpu_c = (float(ask.cpu_shares) - float(used.cpu_shares)) / float(ask.cpu_shares)
    if ask.disk_mb > 0:
        disk_c = (float(ask.disk_mb) - float(used.disk_mb)) / float(ask.disk_mb)
    return math.sqrt(mem_c * mem_c + cpu_c * cpu_c + disk_c * disk_c)


def score_for_task_group(ask: ComparableResources, used: ComparableResources,
                         max_parallel: int, num_preempted: int) -> float:
    """Distance plus a penalty when the alloc's job already has >=
    max_parallel in-plan preemptions (preemption.go:641)."""
    penalty = 0.0
    if max_parallel > 0 and num_preempted >= max_parallel:
        penalty = float((num_preempted + 1) - max_parallel) * MAX_PARALLEL_PENALTY
    return basic_resource_distance(ask, used) + penalty


def net_priority(allocs: List) -> float:
    """max priority + sum/max ratio penalty over the eviction set
    (rank.go:835)."""
    total = 0
    mx = 0.0
    for a in allocs:
        pri = float(_alloc_priority(a))
        if pri > mx:
            mx = pri
        total += int(pri)
    if mx <= 0:
        return 0.0
    return mx + float(total) / mx


def preemption_score(netp: float) -> float:
    """Logistic decay: low net-priority eviction sets score near 1,
    inflection at 2048 (rank.go:858)."""
    return 1.0 / (1.0 + math.exp(_PREEMPTION_SCORE_RATE * (netp - _PREEMPTION_SCORE_ORIGIN)))


def _alloc_priority(alloc) -> int:
    job = getattr(alloc, "job", None)
    if job is not None:
        return job.priority
    return 50


def _alloc_max_parallel(alloc) -> int:
    job = getattr(alloc, "job", None)
    if job is None:
        return 0
    tg = job.lookup_task_group(alloc.task_group)
    if tg is not None and tg.migrate is not None:
        return tg.migrate.max_parallel
    return 0


def filter_and_group_preemptible(job_priority: int, allocs: List) -> List[Tuple[int, List]]:
    """Group eligible allocs by job priority, ascending (lowest-priority
    victims first; preemption.go:666)."""
    by_pri: Dict[int, List] = {}
    for a in allocs:
        if getattr(a, "job", None) is None:
            continue
        pri = _alloc_priority(a)
        if job_priority - pri < PRIORITY_DELTA:
            continue
        by_pri.setdefault(pri, []).append(a)
    return sorted(by_pri.items(), key=lambda kv: kv[0])


class Preemptor:
    """Finds the eviction set for one node (preemption.go:96).

    Construct once per placement attempt, then per candidate node call
    ``set_node`` + ``set_candidates`` + ``preempt_for_task_group``.
    ``set_preemptions`` folds in the allocs already staged for
    preemption elsewhere in the plan so the max_parallel penalty sees
    cross-node evictions of the same job.
    """

    def __init__(self, job_priority: int, namespace: str, job_id: str) -> None:
        self.job_priority = job_priority
        self.namespace = namespace
        self.job_id = job_id
        self._current_preemptions: Dict[Tuple[str, str, str], int] = {}
        self._details: Dict[str, ComparableResources] = {}
        self._max_parallel: Dict[str, int] = {}
        self._node_remaining: Optional[ComparableResources] = None
        self._current_allocs: List = []

    def set_node(self, node) -> None:
        remaining = node.comparable_resources()
        reserved = node.comparable_reserved_resources()
        if reserved is not None:
            remaining.subtract(reserved)
        self._node_remaining = remaining

    def set_candidates(self, allocs: List) -> None:
        self._current_allocs = []
        for a in allocs:
            # never preempt the job being placed (or its plan placements)
            if a.job_id == self.job_id and a.namespace == self.namespace:
                continue
            self._details[a.id] = a.comparable_resources()
            self._max_parallel[a.id] = _alloc_max_parallel(a)
            self._current_allocs.append(a)

    def set_preemptions(self, allocs: List) -> None:
        self._current_preemptions.clear()
        for a in allocs:
            key = (a.namespace, a.job_id, a.task_group)
            self._current_preemptions[key] = self._current_preemptions.get(key, 0) + 1

    def _num_preemptions(self, alloc) -> int:
        return self._current_preemptions.get(
            (alloc.namespace, alloc.job_id, alloc.task_group), 0
        )

    def preempt_for_task_group(self, ask: ComparableResources) -> List:
        """Greedy multi-dim knapsack: repeatedly take the eligible alloc
        with the lowest resource distance until the ask fits, walking
        priority groups lowest-first; then drop superset picks
        (preemption.go:199-265)."""
        if self._node_remaining is None:
            return []
        needed = ask.copy()

        remaining = self._node_remaining.copy()
        for a in self._current_allocs:
            remaining.subtract(self._details[a.id])

        groups = filter_and_group_preemptible(self.job_priority, self._current_allocs)

        best: List = []
        met = False
        available = remaining.copy()
        for _pri, group in groups:
            group = list(group)
            while group and not met:
                best_idx = -1
                best_dist = float("inf")
                for idx, a in enumerate(group):
                    dist = score_for_task_group(
                        needed, self._details[a.id],
                        self._max_parallel[a.id], self._num_preemptions(a),
                    )
                    if dist < best_dist:
                        best_dist = dist
                        best_idx = idx
                chosen = group.pop(best_idx)
                res = self._details[chosen.id]
                available.add(res)
                met, _ = available.superset(ask)
                best.append(chosen)
                needed.subtract(res)
            if met:
                break
        if not met:
            return []
        return self._filter_superset(best, remaining, ask)

    def _filter_superset(self, best: List, node_remaining: ComparableResources,
                         ask: ComparableResources) -> List:
        """Second pass dropping evictions whose resources other picks
        already cover: add picks largest-distance-first and stop at the
        first prefix that satisfies the ask (preemption.go:702)."""
        best = sorted(
            best,
            key=lambda a: basic_resource_distance(ask, self._details[a.id]),
            reverse=True,
        )
        available = node_remaining.copy()
        filtered: List = []
        for a in best:
            filtered.append(a)
            available.add(self._details[a.id])
            ok, _ = available.superset(ask)
            if ok:
                break
        return filtered


def preemptible_planes(cluster, snapshot, ctx, job_priority: int,
                       namespace: str, job_id: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized candidate filter: per-node planes of total preemptible
    cpu/mem/disk plus the net-priority-derived preemption score of
    evicting *everything* eligible (an upper bound on reclaimable
    capacity; the exact greedy set is computed host-side only for
    ranked candidates). Replaces the reference's per-node Preemptor
    invocation inside BinPackIterator with one numpy sweep."""
    n = cluster.n_pad
    pre_cpu = np.zeros(n, np.float32)
    pre_mem = np.zeros(n, np.float32)
    pre_disk = np.zeros(n, np.float32)
    pre_score = np.zeros(n, np.float32)
    by_row: Dict[int, List] = {}
    for a in snapshot.allocs_iter():
        if a.terminal_status():
            continue
        row = cluster.index.get(a.node_id)
        if row is None:
            continue
        if a.job_id == job_id and a.namespace == namespace:
            continue
        if getattr(a, "job", None) is None:
            continue
        if job_priority - _alloc_priority(a) < PRIORITY_DELTA:
            continue
        by_row.setdefault(row, []).append(a)
    for row, allocs in by_row.items():
        for a in allocs:
            cr = a.comparable_resources()
            pre_cpu[row] += cr.cpu_shares
            pre_mem[row] += cr.memory_mb
            pre_disk[row] += cr.disk_mb
        pre_score[row] = preemption_score(net_priority(allocs))
    return pre_cpu, pre_mem, pre_disk, pre_score
