"""Placement stacks: the host orchestration around the device kernel.

Reference behavior: scheduler/stack.go GenericStack (:43-187) and
SystemStack (:191-341). One reference ``Select`` call places one alloc;
the TPU stack's ``select_many`` places *all* missing allocs of a task
group in one kernel launch (the lax.scan placement axis), then performs
exact host-side port and device assignment for the chosen nodes
(AssignPorts/AssignNetwork network.go:427,517; AssignDevice
device.go:32). If exact assignment disagrees with the kernel's
count-based planes (rare: overlapping device groups), the node is
masked and the remaining placements re-run -- semantics stay exact,
the kernel stays fast.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from nomad_tpu.ops.kernel import (
    MAX_PENALTY_NODES,
    NEG_INF,
    KernelOut,
    build_kernel_in,
    infer_features,
    neutral_planes,
    neutral_port_words,
    neutral_step_planes,
    pad_steps,
    pad_steps_live,
    place_taskgroup_jit,
)
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.device import DeviceAllocator, device_planes_for_node
from nomad_tpu.scheduler.feasible import FeasibilityBuilder
from nomad_tpu.scheduler.scaffold import MetricsSkeleton, scaffold_for
from nomad_tpu.structs import consts
from nomad_tpu.telemetry.trace import tracer
from nomad_tpu.structs.alloc import AllocMetric
from nomad_tpu.structs.constraints import matches_affinity, resolve_target
from nomad_tpu.structs.network import NetworkIndex, NetworkResource, Port
from nomad_tpu.structs.resources import (
    AllocatedCpuResources,
    AllocatedMemoryResources,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
)
from nomad_tpu.tensors.schema import (
    MAX_DEV_REQS,
    SPREAD_BUCKETS,
    AskTensor,
    ClusterTensors,
    EvalTensors,
    SpreadTensor,
)


import threading as _threading

#: process-wide hot-path observability (surfaced via Server.stats()
#: -> /v1/agent/self): how often exact host-side assignment disagreed
#: with the kernel and forced a masked re-run
_STATS_LOCK = _threading.Lock()
STATS = {"assign_retry_launches": 0}


@dataclass
class SelectRequest:
    """One placement ask (reference SelectOptions + placement name)."""

    name: str = ""
    prev_alloc: Optional[object] = None
    penalty_nodes: Tuple[str, ...] = ()
    preferred_node: str = ""


@dataclass
class SelectedOption:
    """One placement result (reference RankedNode after ranking)."""

    node_id: str
    node: object
    final_score: float
    task_resources: Dict[str, AllocatedTaskResources]
    task_lifecycles: Dict[str, Optional[object]]
    alloc_resources: Optional[AllocatedSharedResources]
    metrics: AllocMetric
    preempted_allocs: List = field(default_factory=list)
    #: lean fast path: the (job, tg)-shared frozen AllocatedResources
    #: skeleton (scheduler/scaffold.py). When set, the alloc builder
    #: rides it BY REFERENCE instead of assembling per-slot structs;
    #: None = the exact assigner built per-slot resources (networks/
    #: devices/cores)
    resources: Optional[object] = None


class XLAGenericStack:
    """The xla-binpack stack (GenericStack on the TPU kernel)."""

    def __init__(self, batch: bool, ctx: EvalContext, cluster: ClusterTensors) -> None:
        self.batch = batch
        self.ctx = ctx
        self.cluster = cluster
        self.job = None
        self._feas = FeasibilityBuilder(cluster, ctx.state, ctx)
        self._affinity_cache: Dict[Tuple[str, str], float] = {}
        # seeded node-order decorrelation (shuffleNodes util.go:464 --
        # seeded by eval id + state index); None = deterministic argmax
        self.shuffle_seed: Optional[int] = None

    # -- job/tg configuration (stack.go SetJob) --------------------------

    def set_job(self, job) -> None:
        self.job = job
        self.ctx.eligibility.set_job(job)
        self._affinity_cache.clear()

    # -- main entry ------------------------------------------------------

    def select_many(
        self, tg, requests: List[SelectRequest]
    ) -> List[Optional[SelectedOption]]:
        """Place len(requests) allocs of task group tg."""
        if not requests:
            return []
        c = self.cluster
        snapshot = self.ctx.state
        k = len(requests)
        # live launches floor the step bucket (ops/kernel.pad_steps_live)
        # so follow-up evals placing a couple of leftover allocs reuse
        # the primary evals' compiled programs instead of forking tiny
        # per-k variants
        k_pad = pad_steps_live(k)

        node_perm = None
        if self.shuffle_seed is not None:
            rng = np.random.default_rng(self.shuffle_seed)
            node_perm = rng.permutation(c.n_pad).astype(np.int32)

        exclude = np.zeros(c.n_pad, bool)
        results: List[Optional[SelectedOption]] = [None] * k
        pending = list(range(k))
        # assigners persist across retry attempts so ports/devices/cores
        # consumed by already-accepted slots stay consumed
        assigners: Dict[int, "_NodeAssigner"] = {}
        # rows of placements accepted in earlier attempts of this call;
        # their resources are re-applied to rebuilt eval tensors
        accepted_rows: List[int] = []

        for _attempt in range(3):
            ev = self._build_eval_tensors(tg, exclude)
            for row in accepted_rows:
                self._apply_accepted(ev, row)
            if any(requests[ri].penalty_nodes or requests[ri].preferred_node
                   for ri in pending):
                step_penalty = np.full(
                    (k_pad, MAX_PENALTY_NODES), -1, np.int32)
                step_preferred = np.full(k_pad, -1, np.int32)
                for slot, ri in enumerate(pending):
                    req = requests[ri]
                    for j, nid in enumerate(
                            req.penalty_nodes[:MAX_PENALTY_NODES]):
                        row = c.index.get(nid, -1)
                        step_penalty[slot, j] = row
                    if req.preferred_node:
                        step_preferred[slot] = c.index.get(
                            req.preferred_node, -1)
            else:
                # the common ask has no penalties/preferences: ship the
                # frozen singletons so wave members share them by
                # identity (one upload per wave, not per member)
                step_penalty, step_preferred = neutral_step_planes(k_pad)

            kin = build_kernel_in(c, ev, len(pending), step_penalty,
                                  step_preferred, node_perm=node_perm)
            features = infer_features(
                ev,
                any_penalty=any(requests[ri].penalty_nodes for ri in pending),
                any_preferred=any(requests[ri].preferred_node for ri in pending),
                with_shuffle=node_perm is not None,
            )
            out = self.ctx.kernel_launch(kin, k_pad, features)
            # selective host fetch: the planes the walk reads NOW come
            # to host (tiny [K] vectors — one transfer each); the
            # top-k score planes stay as the launcher handed them
            # (device arrays / lazy wave slices) until the plan
            # window's deferred score_meta drain resolves them
            out = KernelOut(*[
                x if f in ("topk_idx", "topk_scores") else np.asarray(x)
                for f, x in zip(KernelOut._fields, out)
            ])
            self._merge_kernel_metrics(out)
            if _attempt > 0:
                with _STATS_LOCK:
                    STATS["assign_retry_launches"] += 1

            # placement assembly: one shared metrics skeleton per
            # launch; lean asks (no networks/devices/cores — the
            # steady-traffic shape) take the vectorized path, sharing
            # one frozen resources skeleton per (job, tg) and skipping
            # the per-slot assigner entirely (it reads no node state
            # and cannot fail for them). Exact assignment survives for
            # every non-lean ask.
            scaffold = scaffold_for(self.job, tg)
            lean = scaffold.lean_assign
            lean_ports = scaffold.lean_ports
            static_info: Dict[int, Tuple[bool, int]] = {}
            usage = getattr(snapshot, "usage", None)
            oversub = getattr(self.ctx.state.scheduler_config,
                              "memory_oversubscription_enabled", False)
            proto = self._metrics_proto(out)
            found_l = out.found.tolist()
            chosen_l = out.chosen.tolist()
            scores_l = out.scores.tolist()
            node_cache: Dict[int, object] = {}
            dead_rows: set = set()
            retry: List[int] = []
            for slot, ri in enumerate(pending):
                if not found_l[slot]:
                    results[ri] = None
                    continue
                row = chosen_l[slot]
                if row in dead_rows:
                    retry.append(ri)
                    continue
                node = node_cache.get(row)
                if node is None:
                    node = snapshot.node_by_id(c.node_ids[row])
                    if node is None:
                        exclude[row] = True
                        dead_rows.add(row)
                        retry.append(ri)
                        continue
                    node_cache[row] = node
                if lean:
                    task_res, lifecycles, res = \
                        scaffold.lean_planes(oversub)
                    option = SelectedOption(
                        node_id=node.id,
                        node=node,
                        final_score=scores_l[slot],
                        task_resources=task_res,
                        task_lifecycles=lifecycles,
                        alloc_resources=None,
                        metrics=None,
                        resources=res,
                    )
                elif lean_ports and self._lean_port_slot_ok(
                        scaffold, row, node, usage, ev, static_info):
                    option = self._lean_port_option(
                        scaffold, tg, node, oversub, scores_l[slot])
                else:
                    asg = assigners.get(row)
                    if asg is None:
                        asg = _NodeAssigner(node, self.ctx)
                        assigners[row] = asg
                    option = asg.assign(tg, scores_l[slot])
                    if option is None:
                        # exact assignment failed: mask node, re-run
                        # this slot
                        exclude[row] = True
                        dead_rows.add(row)
                        retry.append(ri)
                        continue
                option.metrics = self._metrics_for(proto, slot)
                results[ri] = option
                accepted_rows.append(row)
            if not retry:
                break
            pending = retry
        return results

    def _lean_port_slot_ok(self, scaffold, row: int, node, usage,
                           ev: EvalTensors, static_info: Dict) -> bool:
        """Whether a static-port lean placement on ``node`` is provably
        collision-free WITHOUT building a NetworkIndex: the exact
        assigner for such an ask reads node state only for the
        collision re-check, so when every collision source is provable
        from planes — agent-reserved bits (cluster.port_words), live
        alloc bits (the usage index's port bitmaps), in-plan/accepted
        bits (ev.port_conflict_words) — assignment is pure struct
        building. Any unprovable case (multi-address node, poisoned
        bitmap row, staged stops that would free ports, a live-vs-
        static collision the assigner would fail on) returns False and
        the slot takes the exact ``_NodeAssigner`` path unchanged."""
        info = static_info.get(row)
        if info is None:
            sok = True
            smask = 0
            ips = {nt.ip or "0.0.0.0"
                   for nt in node.node_resources.networks if nt.device}
            if len(ips) > 1:
                sok = False
            else:
                for p in getattr(node.reserved_resources,
                                 "networks_ports", []):
                    if p < 0 or p >= 65536 or (smask >> p) & 1:
                        sok = False
                        break
                    smask |= 1 << p
            info = static_info[row] = (sok, smask)
        if not info[0]:
            return False
        if usage is None:
            return False
        urow = usage.rows.get(node.id)
        if urow is None or urow in usage.port_dirty:
            return False
        live = usage.port_masks.get(urow, 0)
        if live & (scaffold.static_port_mask | info[1]):
            # ask conflicts with a live alloc, or a live alloc already
            # collides with the agent-reserved set (the assigner's
            # add_allocs would fail the whole node)
            return False
        plan = self.ctx.plan
        if node.id in plan.node_update or node.id in plan.node_preemptions:
            # staged stops free ports the snapshot planes still count
            return False
        c = self.cluster
        words = c.port_words[row] | ev.port_conflict_words[row]
        if np.any(words & ev.ask.port_mask):
            return False
        return True

    def _lean_port_option(self, scaffold, tg, node, oversub: bool,
                          final_score: float) -> SelectedOption:
        """The static-port placement structs, mirroring the assigner's
        group-network branch (same offer/NetworkResource shapes) with
        the (job, tg)-shared task skeletons."""
        task_res, lifecycles, _ = scaffold.lean_planes(oversub)
        net = tg.networks[0]
        offer = [Port(label=p.label, value=p.value, to=p.to,
                      host_network=p.host_network)
                 for p in net.reserved_ports]
        nw = NetworkResource(
            mode=net.mode,
            device=(node.node_resources.networks[0].device
                    if node.node_resources.networks else ""),
            ip=(node.node_resources.networks[0].ip
                if node.node_resources.networks else ""),
            reserved_ports=list(offer),
        )
        shared = AllocatedSharedResources(
            disk_mb=tg.ephemeral_disk.size_mb,
            networks=[nw],
            ports=offer,
        )
        res = AllocatedResources(
            tasks=task_res,
            task_lifecycles=lifecycles,
            shared=shared,
        )
        return SelectedOption(
            node_id=node.id,
            node=node,
            final_score=final_score,
            task_resources=task_res,
            task_lifecycles=lifecycles,
            alloc_resources=shared,
            metrics=None,
            resources=res,
        )

    def _apply_accepted(self, ev: EvalTensors, row: int) -> None:
        """Re-apply one already-accepted placement's resources to freshly
        rebuilt eval tensors (retry attempts must not double-book)."""
        if not ev.used_cpu.flags.writeable:
            # the build shared the cluster's read-only gathered usage
            # planes; this eval now diverges — copy-on-write
            ev.used_cpu = ev.used_cpu.copy()
            ev.used_mem = ev.used_mem.copy()
            ev.used_disk = ev.used_disk.copy()
            ev.used_cores = ev.used_cores.copy()
            ev.used_mbits = ev.used_mbits.copy()
        # same COW for the neutral singletons the build shares by
        # identity (frozen: a missed copy raises, never corrupts)
        for f in ("free_dyn_delta", "job_tg_count", "job_any_count",
                  "dev_free", "port_conflict_words"):
            plane = getattr(ev, f)
            if not plane.flags.writeable:
                setattr(ev, f, plane.copy())
        ask = ev.ask
        ev.used_cpu[row] += ask.cpu
        ev.used_mem[row] += ask.mem
        ev.used_disk[row] += ask.disk
        ev.used_cores[row] += ask.cores
        ev.used_mbits[row] += ask.total_mbits
        ev.free_dyn_delta[row] += ask.n_dyn_ports
        ev.job_tg_count[row] += 1
        ev.job_any_count[row] += 1
        ev.dev_free[row] -= ask.dev_counts
        ev.port_conflict_words[row] |= ask.port_mask
        for sp in ev.spreads:
            b = int(sp.bucket_id[row])
            if b >= 0:
                sp.counts[b] += 1

    def select(self, tg, request: Optional[SelectRequest] = None) -> Optional[SelectedOption]:
        """Single-placement compatibility entry (stack.go Select)."""
        return self.select_many(tg, [request or SelectRequest()])[0]

    # -- preemption fallback (SelectOptions.Preempt second pass) ---------

    def select_preempting(self, tg, request: Optional[SelectRequest] = None) -> Optional[SelectedOption]:
        """Place one alloc by evicting lower-priority work.

        Reference: BinPackIterator's preempt branch (rank.go:258-268 area)
        + PreemptionScoringIterator (rank.go:799), invoked via
        SelectOptions.Preempt (generic_sched.go:800-819). TPU split:
        candidate nodes and their upper-bound scores come from one numpy
        sweep over the planes; the exact greedy eviction set runs only
        for the ranked top candidates.
        """
        from nomad_tpu.scheduler.preemption import (
            Preemptor,
            net_priority,
            preemptible_planes,
            preemption_score,
        )

        c = self.cluster
        snapshot = self.ctx.state
        job = self.job
        if job is None:
            return None
        ev = self._build_eval_tensors(tg, np.zeros(c.n_pad, bool))
        ask = ev.ask
        pre_cpu, pre_mem, pre_disk, pre_score = preemptible_planes(
            c, snapshot, self.ctx, job.priority, job.namespace, job.id
        )
        free_cpu = c.cap_cpu - ev.used_cpu + pre_cpu
        free_mem = c.cap_mem - ev.used_mem + pre_mem
        free_disk = c.cap_disk - ev.used_disk + pre_disk
        cand = (
            ev.base_mask
            & ((pre_cpu > 0) | (pre_mem > 0) | (pre_disk > 0))
            & (free_cpu >= ask.cpu)
            & (free_mem >= ask.mem)
            & (free_disk >= ask.disk)
        )
        rows = np.nonzero(cand)[0]
        if rows.size == 0:
            return None

        # upper-bound score per candidate: binpack fit after hypothetical
        # full eviction, averaged with the preemption-score plane (the
        # exact set can only evict less, scoring no worse on fit)
        util_cpu = ev.used_cpu[rows] - pre_cpu[rows] + ask.cpu
        util_mem = ev.used_mem[rows] - pre_mem[rows] + ask.mem
        with np.errstate(divide="ignore", invalid="ignore"):
            fc = np.where(c.cap_cpu[rows] > 0, 1.0 - util_cpu / c.cap_cpu[rows], 0.0)
            fm = np.where(c.cap_mem[rows] > 0, 1.0 - util_mem / c.cap_mem[rows], 0.0)
        total = np.power(10.0, fc) + np.power(10.0, fm)
        if self.ctx.state.scheduler_config.effective_algorithm() == consts.SCHEDULER_ALGORITHM_SPREAD:
            fit = np.clip(total - 2.0, 0.0, 18.0) / 18.0
        else:
            fit = np.clip(20.0 - total, 0.0, 18.0) / 18.0
        # rescheduling-penalty / preferred-node planes from the request
        # (NodeReschedulingPenaltyIterator rank.go:630 appends -1 for
        # penalized nodes; the preferred node is examined first)
        request = request or SelectRequest()
        penalty_rows = {
            c.index[nid] for nid in request.penalty_nodes if nid in c.index
        }
        penalized = np.array([int(r) in penalty_rows for r in rows], bool)
        est = np.where(
            penalized,
            (fit + pre_score[rows] - 1.0) / 3.0,
            (fit + pre_score[rows]) / 2.0,
        )
        preferred_row = c.index.get(request.preferred_node, -1)
        if preferred_row >= 0:
            est = np.where(rows == preferred_row, est + 2.0, est)
        order = np.argsort(-est)

        # LimitIterator semantics: examine a bounded candidate prefix
        limit = max(2, int(math.log2(max(2, c.n_real))))
        plan = self.ctx.plan
        staged = [
            a for allocs in plan.node_preemptions.values() for a in allocs
        ]
        preemptor = Preemptor(job.priority, job.namespace, job.id)

        best_option: Optional[SelectedOption] = None
        best_score = -float("inf")
        examined = 0
        for pos in order:
            if examined >= limit and best_option is not None:
                break
            examined += 1
            row = int(rows[pos])
            node = snapshot.node_by_id(c.node_ids[row])
            if node is None:
                continue
            proposed = self.ctx.proposed_allocs(node.id)
            preemptor.set_node(node)
            preemptor.set_candidates(proposed)
            preemptor.set_preemptions(staged)
            ask_cr = _tg_comparable_ask(tg)
            victims = preemptor.preempt_for_task_group(ask_cr)
            if not victims:
                continue
            victim_ids = {a.id for a in victims}
            remaining = [a for a in proposed if a.id not in victim_ids]
            asg = _NodeAssigner(node, self.ctx, proposed=remaining)
            option = asg.assign(tg, 0.0)
            if option is None:
                continue
            p_score = preemption_score(net_priority(victims))
            planes = [float(fit[pos]), p_score]
            if penalized[pos]:
                planes.append(-1.0)
            final = sum(planes) / len(planes)
            if final > best_score:
                best_score = final
                option.final_score = final
                option.preempted_allocs = victims
                m = self.ctx.metrics().copy()
                m.score_meta.append(
                    (node.id, {"binpack": float(fit[pos]),
                               "preemption": p_score}, final)
                )
                option.metrics = m
                best_option = option
        return best_option

    # -- tensor builders -------------------------------------------------

    def _base_mask(self, scaffold, job, tg, job_allocs_by_node,
                   exclude: np.ndarray) -> np.ndarray:
        """Compiled-mask fast path with Python-builder fallback.

        The compiled path returns the mask-program cache's FROZEN
        array when the eval carries no dynamic state — wave members of
        equal job specs then share one base-mask plane by identity
        (shipped once per wave, resident on device once ever). Any
        uncompilable tree, and any compiled-path error, falls back to
        ``FeasibilityBuilder.base_mask``, which is the semantics
        definition the compiler is property-tested against."""
        from nomad_tpu.feasibility import apply_program, default_mask_cache

        if scaffold.program is not None:
            try:
                return apply_program(
                    scaffold.program, self.cluster, self.ctx.state,
                    self.ctx, job, tg, job_allocs_by_node, exclude,
                    self._feas)
            except Exception:                   # noqa: BLE001
                import logging

                logging.getLogger(__name__).warning(
                    "feasibility compiler failed; falling back",
                    exc_info=True)
        default_mask_cache.note_fallback()
        base = self._feas.base_mask(job, tg, job_allocs_by_node)
        base &= ~exclude
        return base

    def _build_eval_tensors(self, tg, exclude: np.ndarray) -> EvalTensors:
        with tracer.span("sched.assembly"):
            return self._build_eval_tensors_inner(tg, exclude)

    def _build_eval_tensors_inner(self, tg, exclude: np.ndarray) -> EvalTensors:
        c = self.cluster
        snapshot = self.ctx.state
        job = self.job
        n = c.n_pad
        scaffold = scaffold_for(job, tg)

        job_allocs = snapshot.allocs_by_job(job.namespace, job.id)
        # distinct_hosts/property masks see PROPOSED allocs (feasible.go
        # uses ctx.ProposedAllocs): exclude plan-staged stops/preemptions,
        # include plan placements
        plan = self.ctx.plan
        staged_out = {
            a.id
            for allocs in list(plan.node_update.values())
            + list(plan.node_preemptions.values())
            for a in allocs
        }
        staged_in = {
            a.id for allocs in plan.node_allocation.values() for a in allocs
        }
        job_allocs_by_node: Dict[str, List] = {}
        for a in job_allocs:
            if a.id in staged_out or a.id in staged_in:
                continue
            job_allocs_by_node.setdefault(a.node_id, []).append(a)
        for allocs in plan.node_allocation.values():
            for a in allocs:
                if a.job_id == job.id:
                    job_allocs_by_node.setdefault(a.node_id, []).append(a)

        with tracer.span("sched.feasibility"):
            base = self._base_mask(scaffold, job, tg,
                                   job_allocs_by_node, exclude)

        # neutral O(n) planes are frozen singletons shared BY IDENTITY
        # across evals (and so shipped once per coalesced wave); any
        # path that actually writes one allocates its own copy
        neutral = neutral_planes(n)
        job_tg_count = neutral.zeros_i32
        job_any_count = neutral.zeros_i32
        conflict_words = neutral_port_words(n, c.port_words.shape[1])
        free_dyn_delta = neutral.zeros_i32

        # plan-skeleton cache: the flattened ask is spec-derived and
        # shared across wave members / retry attempts of the job
        ask = scaffold.ask

        u = getattr(snapshot, "usage", None)
        if (u is not None and not plan.node_update
                and not plan.node_preemptions and not plan.node_allocation):
            # empty plan (first placements of the eval): the proposed
            # utilization IS the snapshot's — share the cluster's
            # read-only gathered planes BY IDENTITY, so every eval of a
            # wave ships one copy to the device instead of one each
            used_cpu, used_mem, used_disk, used_cores, used_mbits = \
                c.gathered_usage(u)
            live_job_allocs = [a for a in job_allocs
                               if not a.terminal_status()]
            if live_job_allocs:
                job_tg_count = np.zeros(n, np.int32)
                job_any_count = np.zeros(n, np.int32)
                for a in live_job_allocs:
                    row = c.index.get(a.node_id)
                    if row is None:
                        continue
                    job_any_count[row] += 1
                    if a.task_group == tg.name:
                        job_tg_count[row] += 1
        else:
            used_cpu = np.zeros(n, np.float32)
            used_mem = np.zeros(n, np.float32)
            used_disk = np.zeros(n, np.float32)
            used_mbits = np.zeros(n, np.int32)
            used_cores = np.zeros(n, np.int32)
            job_tg_count = np.zeros(n, np.int32)
            job_any_count = np.zeros(n, np.int32)
            conflict_words = np.zeros((n, c.port_words.shape[1]), np.uint32)
            free_dyn_delta = np.zeros(n, np.int32)
            # proposed utilization per node (context.go ProposedAllocs
            # over every node)
            self._accumulate_usage(
                used_cpu, used_mem, used_disk, used_mbits, used_cores,
                job_tg_count, job_any_count, conflict_words,
                free_dyn_delta, tg, ask,
            )
        # node-static plane, shared from the cluster build (read-only)
        avail_mbits = (c.avail_mbits if c.avail_mbits is not None
                       else neutral.zeros_i32)

        # live-port conflict overlay for reserved-port asks: sparse
        # walk of the usage index's per-node port bitmaps (only nodes
        # holding ports have entries; poisoned rows stay unflagged —
        # the exact assigner arbitrates them). Sound only when the
        # plan stages no stops (a stop would free its ports); the
        # empty-plan fast path above is exactly that case.
        port_live = None
        if (ask.reserved_ports and u is not None
                and (u.port_masks or u.port_dirty)
                and not plan.node_update and not plan.node_preemptions):
            ask_mask_int = 0
            for v in ask.reserved_ports:
                ask_mask_int |= 1 << v
            for urow, mask in u.port_masks.items():
                if mask & ask_mask_int and urow not in u.port_dirty:
                    nid = u.ids[urow] if urow < len(u.ids) else None
                    row = c.index.get(nid) if nid is not None else None
                    if row is None:
                        continue
                    if port_live is None:
                        port_live = np.zeros(n, bool)
                    port_live[row] = True

        # device planes
        dev_free = neutral.zeros_dev
        dev_aff = neutral.zeros_f32
        has_dev_aff = False
        dev_reqs = [d for task in tg.tasks for d in task.resources.devices]
        if dev_reqs:
            dev_free = np.zeros((n, MAX_DEV_REQS), np.float32)
            dev_aff = np.zeros(n, np.float32)
            for i in range(c.n_real):
                if not base[i]:
                    continue
                node = snapshot.node_by_id(c.node_ids[i])
                if node is None:
                    continue
                proposed = self.ctx.proposed_allocs(c.node_ids[i])
                counts, score, has_aff = device_planes_for_node(node, proposed, dev_reqs)
                for r, cnt in enumerate(counts[:MAX_DEV_REQS]):
                    dev_free[i, r] = cnt
                dev_aff[i] = score
                has_dev_aff = has_dev_aff or has_aff

        # affinity plane (NodeAffinityIterator rank.go:674)
        affinities = scaffold.affinities
        aff_score = neutral.zeros_f32
        if affinities:
            aff_score = np.zeros(n, np.float32)
            sum_weight = sum(abs(float(a.weight)) for a in affinities)
            cache: Dict[str, float] = {}
            for i in range(c.n_real):
                if not base[i]:
                    continue
                cls = c.computed_classes[i]
                if cls in cache and not self.ctx.eligibility.has_escaped():
                    aff_score[i] = cache[cls]
                    continue
                node = snapshot.node_by_id(c.node_ids[i])
                if node is None:
                    continue
                total = sum(
                    float(a.weight) for a in affinities if matches_affinity(a, node)
                )
                score = total / sum_weight if sum_weight else 0.0
                aff_score[i] = score
                cache[cls] = score

        spreads = self._build_spreads(tg, job_allocs)

        return EvalTensors(
            base_mask=base,
            used_cpu=used_cpu,
            used_mem=used_mem,
            used_disk=used_disk,
            used_mbits=used_mbits,
            avail_mbits=avail_mbits,
            used_cores=used_cores,
            port_conflict_words=conflict_words,
            free_dyn_delta=free_dyn_delta,
            dev_free=dev_free,
            dev_aff_score=dev_aff,
            has_dev_affinity=has_dev_aff,
            job_tg_count=job_tg_count,
            job_any_count=job_any_count,
            distinct_hosts_job=scaffold.distinct_hosts_job,
            distinct_hosts_tg=scaffold.distinct_hosts_tg,
            penalty=neutral.zeros_bool,
            aff_score=aff_score,
            has_affinities=bool(affinities),
            spreads=spreads,
            ask=ask,
            desired_count=tg.count,
            algorithm=self.ctx.state.scheduler_config.effective_algorithm(),
            port_live_conflict=port_live,
        )

    def _accumulate_usage(
        self, used_cpu, used_mem, used_disk, used_mbits, used_cores,
        job_tg_count, job_any_count, conflict_words, free_dyn_delta, tg, ask,
    ) -> None:
        """Fold proposed allocs (state + in-flight plan) into the planes."""
        c = self.cluster
        snapshot = self.ctx.state
        plan = self.ctx.plan
        job = self.job

        stopping = {
            a.id
            for allocs in list(plan.node_update.values())
            + list(plan.node_preemptions.values())
            for a in allocs
        }
        # in-plan placements override same-ID state rows (in-place
        # updates) rather than double counting (context.go:193-207)
        planned_ids = {
            a.id for allocs in plan.node_allocation.values() for a in allocs
        }

        def add_alloc(a, sign: float) -> None:
            row = c.index.get(a.node_id)
            if row is None:
                return
            cr = a.comparable_resources()
            used_cpu[row] += sign * cr.cpu_shares
            used_mem[row] += sign * cr.memory_mb
            used_disk[row] += sign * cr.disk_mb
            used_cores[row] += int(sign) * len(cr.reserved_cores)
            for net in cr.networks:
                used_mbits[row] += int(sign) * net.mbits
            if a.job_id == job.id:
                job_any_count[row] += int(sign)
                if a.task_group == tg.name:
                    job_tg_count[row] += int(sign)

        u = getattr(snapshot, "usage", None)
        if u is not None:
            # fast path: gather the store's live utilization planes
            # (state/usage.py) instead of scanning every alloc, then
            # correct for this plan's staged stops and in-plan updates
            perm, valid = c.usage_perm(u)
            np.copyto(used_cpu, np.where(valid, u.used_cpu[perm], 0.0))
            np.copyto(used_mem, np.where(valid, u.used_mem[perm], 0.0))
            np.copyto(used_disk, np.where(valid, u.used_disk[perm], 0.0))
            np.copyto(used_cores, np.where(valid, u.used_cores[perm], 0))
            np.copyto(used_mbits, np.where(valid, u.used_mbits[perm], 0))
            for aid in stopping | planned_ids:
                old = snapshot.alloc_by_id(aid)
                if old is not None and not old.terminal_status():
                    row = c.index.get(old.node_id)
                    if row is None:
                        continue
                    cr = old.comparable_resources()
                    used_cpu[row] -= cr.cpu_shares
                    used_mem[row] -= cr.memory_mb
                    used_disk[row] -= cr.disk_mb
                    used_cores[row] -= len(cr.reserved_cores)
                    for net in cr.networks:
                        used_mbits[row] -= net.mbits
            # job-local planes from the per-job index (small)
            for a in snapshot.allocs_by_job(job.namespace, job.id):
                if a.terminal_status() or a.id in stopping or a.id in planned_ids:
                    continue
                row = c.index.get(a.node_id)
                if row is None or a.job_id != job.id:
                    continue
                job_any_count[row] += 1
                if a.task_group == tg.name:
                    job_tg_count[row] += 1
        else:
            for a in snapshot.allocs_iter():
                if a.terminal_status() or a.id in stopping or a.id in planned_ids:
                    continue
                add_alloc(a, 1.0)
        for allocs in plan.node_allocation.values():
            for a in allocs:
                add_alloc(a, 1.0)
                # in-plan port usage -> conflict words + dyn delta
                row = c.index.get(a.node_id)
                if row is None or a.allocated_resources is None:
                    continue
                for tr in a.allocated_resources.tasks.values():
                    for net in tr.networks:
                        for p in list(net.reserved_ports) + list(net.dynamic_ports):
                            conflict_words[row, p.value >> 5] |= np.uint32(
                                1 << (p.value & 31)
                            )
                            if 20000 <= p.value <= 32000:
                                free_dyn_delta[row] += 1
                for p in a.allocated_resources.shared.ports:
                    conflict_words[row, p.value >> 5] |= np.uint32(1 << (p.value & 31))
                    if 20000 <= p.value <= 32000:
                        free_dyn_delta[row] += 1

    def _build_spreads(self, tg, job_allocs) -> List[SpreadTensor]:
        """SpreadIterator state -> SpreadTensor list (spread.go:82-113,
        computeSpreadInfo :245)."""
        c = self.cluster
        job = self.job
        combined = list(tg.spreads) + list(job.spreads)
        if not combined:
            return []
        sum_weights = sum(abs(s.weight) for s in combined)
        out = []
        plan_allocs = [
            a
            for allocs in self.ctx.plan.node_allocation.values()
            for a in allocs
            if a.job_id == job.id and a.task_group == tg.name
        ]
        live_allocs = [
            a
            for a in job_allocs
            if not a.terminal_status() and a.task_group == tg.name
        ] + plan_allocs
        node_of = {nid: i for i, nid in enumerate(c.node_ids)}
        for spread in combined:
            # value table: desired targets first, then observed node values
            values: Dict[str, int] = {}
            for t in spread.spread_target:
                if t.value != "*":
                    values.setdefault(t.value, len(values))
            bucket_id = np.full(c.n_pad, -1, np.int32)
            node_vals: List[Optional[str]] = [None] * c.n_real
            for i in range(c.n_real):
                node = self.ctx.state.node_by_id(c.node_ids[i])
                if node is None:
                    continue
                val, ok = resolve_target(spread.attribute, node)
                if not ok:
                    continue
                node_vals[i] = val
                if val not in values:
                    if len(values) >= SPREAD_BUCKETS:
                        continue  # overflow: value scores as missing
                    values[val] = len(values)
                bucket_id[i] = values[val]
            counts = np.zeros(SPREAD_BUCKETS, np.float32)
            for a in live_allocs:
                row = node_of.get(a.node_id)
                if row is None or node_vals[row] is None:
                    continue
                b = values.get(node_vals[row])
                if b is not None:
                    counts[b] += 1
            desired = np.full(SPREAD_BUCKETS, -1.0, np.float32)
            even = not spread.spread_target
            if not even:
                total_count = float(tg.count)
                sum_desired = 0.0
                implicit_pct = None
                for t in spread.spread_target:
                    dc = (float(t.percent) / 100.0) * total_count
                    if t.value == "*":
                        implicit_pct = dc
                        continue
                    desired[values[t.value]] = dc
                    sum_desired += dc
                # implicit remainder target (spread.go:258-262)
                remainder = total_count - sum_desired
                if implicit_pct is None and 0 < sum_desired < total_count:
                    implicit_pct = remainder
                if implicit_pct is not None:
                    for v, b in values.items():
                        if desired[b] < 0:
                            desired[b] = implicit_pct
                    # nodes with unseen values also get the implicit target:
                    # they were added to the table above, so covered.
            out.append(
                SpreadTensor(
                    bucket_id=bucket_id,
                    counts=counts,
                    desired=desired,
                    weight_frac=float(spread.weight) / float(sum_weights) if sum_weights else 0.0,
                    even=even,
                )
            )
        return out

    def _merge_kernel_metrics(self, out: KernelOut) -> None:
        """Fold the kernel's mask-population counts into the eval
        context metrics so failed placements report why (the blocked
        eval's FailedTGAllocs carries these, eval_endpoint surface)."""
        m = self.ctx.metrics()
        m.nodes_evaluated = int(out.nodes_evaluated)
        m.nodes_exhausted = int(out.nodes_evaluated - out.nodes_feasible)
        for dim, cnt in (
            ("cpu", out.exhausted_cpu),
            ("memory", out.exhausted_mem),
            ("disk", out.exhausted_disk),
            ("network: dynamic port selection failed", out.exhausted_ports),
            ("devices", out.exhausted_devices),
            ("cores", out.exhausted_cores),
        ):
            if int(cnt) > 0:
                m.dimension_exhausted[dim] = int(cnt)

    def _metrics_proto(self, out: KernelOut) -> MetricsSkeleton:
        """Per-launch MetricsSkeleton (scheduler/scaffold.py): the
        header counts are identical for every slot, captured once; the
        top-k planes ride the skeleton UNRESOLVED (device arrays or
        the coalescer's lazy wave slices) — their single d2h fetch and
        the score_meta materialization are DEFERRED onto the plan's
        post-processing queue (plan.deferred_work), so they run inside
        the batching worker's plan window — overlapping the next
        wave's execute — instead of on the wave-critical eval path."""
        dim_exhausted = {}
        for dim, cnt in (
            ("cpu", out.exhausted_cpu),
            ("memory", out.exhausted_mem),
            ("disk", out.exhausted_disk),
            ("network: dynamic port selection failed", out.exhausted_ports),
            ("devices", out.exhausted_devices),
            ("cores", out.exhausted_cores),
        ):
            if int(cnt) > 0:
                dim_exhausted[dim] = int(cnt)
        m = self.ctx.metrics()
        return MetricsSkeleton(
            nodes_evaluated=int(out.nodes_evaluated),
            nodes_filtered=m.nodes_filtered,
            nodes_exhausted=int(out.nodes_evaluated - out.nodes_feasible),
            constraint_filtered=dict(m.constraint_filtered),
            dimension_exhausted=dim_exhausted,
            topk_idx=out.topk_idx,
            topk_scores=out.topk_scores,
        )

    def _metrics_for(self, proto: MetricsSkeleton, slot: int) -> AllocMetric:
        m = proto.materialize()
        # score_meta fills in place before the plan applies (the
        # Allocation holds this same AllocMetric object by reference)
        self.ctx.plan.deferred_work.append(
            lambda m=m, proto=proto, slot=slot: self._fill_score_meta(
                m, proto, slot))
        return m

    def _fill_score_meta(self, m: AllocMetric, proto: MetricsSkeleton,
                         slot: int) -> None:
        c = self.cluster
        rows, scores = proto.slot_topk(slot)
        for row, score in zip(rows.tolist(), scores.tolist()):
            if score <= NEG_INF / 2:
                continue
            if row < c.n_real:
                m.score_meta.append(
                    (c.node_ids[row], {"normalized-score": score}, score)
                )


def _tg_comparable_ask(tg) -> "ComparableResources":
    """Flatten a task group's total ask to ComparableResources (the
    resourceAsk.Comparable() the Preemptor scores against)."""
    from nomad_tpu.structs.resources import ComparableResources

    ask = ComparableResources(disk_mb=int(tg.ephemeral_disk.size_mb))
    for task in tg.tasks:
        ask.cpu_shares += int(task.resources.cpu)
        ask.memory_mb += int(task.resources.memory_mb)
    return ask


class _NodeAssigner:
    """Exact per-node assignment of ports, devices, and cores for one or
    more placements on the same chosen node (the tail of
    BinPackIterator.Next, rank.go:280-520, run host-side only for
    selected nodes)."""

    def __init__(self, node, ctx: EvalContext, proposed=None) -> None:
        self.node = node
        self.ctx = ctx
        # every sub-assigner is built LAZILY on the first ask that needs
        # it: a lean cpu/mem placement (the common case) pays for none
        # of the port/device/core indexing, which otherwise dominated
        # the per-placement host profile (reference equally only enters
        # these branches for non-empty asks, rank.go:270-492)
        self._proposed = proposed
        self._net_idx: Optional[NetworkIndex] = None
        self._net_ok = True
        self._dev_alloc: Optional[DeviceAllocator] = None
        self._used_cores: Optional[set] = None

    def _get_proposed(self):
        if self._proposed is None:
            self._proposed = self.ctx.proposed_allocs(self.node.id)
        return self._proposed

    @property
    def net_idx(self) -> NetworkIndex:
        if self._net_idx is None:
            self._net_idx = NetworkIndex()
            if self.ctx.port_seed is not None:
                import zlib

                self._net_idx.seed(
                    self.ctx.port_seed ^ zlib.crc32(self.node.id.encode()))
            collide, reason = self._net_idx.set_node(self.node)
            if not collide:
                collide, reason = self._net_idx.add_allocs(
                    self._get_proposed())
            self._net_ok = not collide
            if collide:
                from nomad_tpu.scheduler.context import PortCollisionEvent

                self.ctx.send_event(
                    PortCollisionEvent(reason, node=self.node))
        return self._net_idx

    @property
    def dev_alloc(self) -> DeviceAllocator:
        if self._dev_alloc is None:
            self._dev_alloc = DeviceAllocator(self.node)
            self._dev_alloc.add_allocs(self._get_proposed())
        return self._dev_alloc

    @property
    def used_cores(self) -> set:
        if self._used_cores is None:
            self._used_cores = set()
            for a in self._get_proposed():
                self._used_cores |= set(
                    a.comparable_resources().reserved_cores)
        return self._used_cores

    @used_cores.setter
    def used_cores(self, value: set) -> None:
        self._used_cores = value

    def assign(self, tg, final_score: float) -> Optional[SelectedOption]:
        needs_net = bool(tg.networks) or any(
            t.resources.networks for t in tg.tasks)
        if needs_net:
            self.net_idx          # build + validate
            if not self._net_ok:
                return None
        task_resources: Dict[str, AllocatedTaskResources] = {}
        task_lifecycles: Dict[str, Optional[object]] = {}
        alloc_resources = None

        # group-level networks (rank.go:270-348)
        if tg.networks:
            group_ask = tg.networks[0].copy()
            offer, err = self.net_idx.assign_ports(group_ask)
            if offer is None:
                return None
            self.net_idx.add_reserved_ports(offer)
            nw = NetworkResource(
                mode=group_ask.mode,
                device=(self.node.node_resources.networks[0].device
                        if self.node.node_resources.networks else ""),
                ip=(self.node.node_resources.networks[0].ip
                    if self.node.node_resources.networks else ""),
                reserved_ports=[p for p in offer],
            )
            alloc_resources = AllocatedSharedResources(
                disk_mb=tg.ephemeral_disk.size_mb,
                networks=[nw],
                ports=offer,
            )

        # memory oversubscription (structs.go MemoryMaxMB): the burst
        # ceiling rides on the allocation ONLY when the operator enabled
        # it (SchedulerConfiguration.MemoryOversubscriptionEnabled);
        # scheduling always counts the reserve (memory_mb)
        oversub = getattr(self.ctx.state.scheduler_config,
                          "memory_oversubscription_enabled", False)
        for task in tg.tasks:
            r = task.resources
            tr = AllocatedTaskResources(
                cpu=AllocatedCpuResources(cpu_shares=int(r.cpu)),
                memory=AllocatedMemoryResources(
                    memory_mb=int(r.memory_mb),
                    memory_max_mb=(int(r.memory_max_mb)
                                   if oversub else 0),
                ),
            )
            # task-level legacy networks (rank.go:363-410)
            if r.networks:
                offer, err = self.net_idx.assign_network(r.networks[0])
                if offer is None:
                    return None
                self.net_idx.add_reserved(offer)
                tr.networks = [offer]
            # devices (rank.go:413-460)
            for req in r.devices:
                offer, _weights, err = self.dev_alloc.assign(req)
                if offer is None:
                    return None
                self.dev_alloc.add_reserved(offer)
                tr.devices.append(offer)
            # reserved cores (rank.go:462-492)
            if r.cores > 0:
                avail = [
                    core
                    for core in self.node.node_resources.cpu.reservable_cpu_cores
                    if core not in self.used_cores
                ]
                if len(avail) < r.cores:
                    return None
                tr.cpu.reserved_cores = avail[: r.cores]
                self.used_cores |= set(tr.cpu.reserved_cores)
                tr.cpu.cpu_shares = (
                    self.node.node_resources.cpu.shares_per_core() * r.cores
                )
            task_resources[task.name] = tr
            task_lifecycles[task.name] = task.lifecycle

        return SelectedOption(
            node_id=self.node.id,
            node=self.node,
            final_score=final_score,
            task_resources=task_resources,
            task_lifecycles=task_lifecycles,
            alloc_resources=alloc_resources,
            metrics=AllocMetric(),
        )
