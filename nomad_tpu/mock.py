"""Mock factories for tests and benchmarks.

Reference behavior: nomad/mock/mock.go -- mock.Node(), mock.Job(),
mock.Alloc(), mock.Eval(), mock.SystemJob() with the same default shapes
(4000 MHz / 8192 MB nodes; 500 MHz / 256 MB tasks) so scheduler tests port
over with identical arithmetic.
"""

from __future__ import annotations

import itertools
import uuid

from nomad_tpu import structs
from nomad_tpu.structs import consts

_counter = itertools.count()


def _uuid() -> str:
    return str(uuid.uuid4())


def node(**overrides) -> structs.Node:
    """mock.Node(): 4000 MHz cpu, 8192 MB mem, 100 GB disk, 1000 mbit net."""
    i = next(_counter)
    n = structs.Node(
        id=_uuid(),
        name=f"foobar-{i}",
        datacenter="dc1",
        node_class="",
        attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "nomad.version": "1.3.0",
            "driver.exec": "1",
            "driver.mock_driver": "1",
            "cpu.numcores": "4",
        },
        node_resources=structs.NodeResources(
            cpu=structs.NodeCpuResources(
                cpu_shares=4000,
                total_core_count=4,
                reservable_cpu_cores=[0, 1, 2, 3],
            ),
            memory=structs.NodeMemoryResources(memory_mb=8192),
            disk=structs.NodeDiskResources(disk_mb=100 * 1024),
            networks=[
                structs.NetworkResource(
                    device="eth0", cidr="192.168.0.100/32", ip="192.168.0.100",
                    mbits=1000,
                )
            ],
        ),
        reserved_resources=structs.NodeReservedResources(
            cpu_shares=100, memory_mb=256, disk_mb=4 * 1024,
            networks_ports=[22],
        ),
        drivers={
            "exec": structs.DriverInfo(detected=True, healthy=True),
            "mock_driver": structs.DriverInfo(detected=True, healthy=True),
        },
        status=consts.NODE_STATUS_READY,
    )
    for k, v in overrides.items():
        setattr(n, k, v)
    n.compute_class()
    return n


def job(**overrides) -> structs.Job:
    """mock.Job(): service job, 1 TG x count 10, 1 task (500 MHz/256 MB)."""
    j = structs.Job(
        id=f"mock-service-{_uuid()}",
        name="my-job",
        type=consts.JOB_TYPE_SERVICE,
        priority=50,
        datacenters=["dc1"],
        constraints=[
            structs.Constraint(
                ltarget="${attr.kernel.name}", rtarget="linux", operand="="
            )
        ],
        task_groups=[
            structs.TaskGroup(
                name="web",
                count=10,
                ephemeral_disk=structs.EphemeralDisk(size_mb=150),
                restart_policy=structs.RestartPolicy(
                    attempts=3, interval_s=600, delay_s=60, mode="delay"
                ),
                reschedule_policy=structs.ReschedulePolicy(
                    attempts=2, interval_s=600, delay_s=5,
                    delay_function="constant",
                ),
                tasks=[
                    structs.Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date"},
                        env={"FOO": "bar"},
                        resources=structs.Resources(
                            cpu=500, memory_mb=256,
                            networks=[
                                structs.NetworkResource(
                                    mbits=50,
                                    dynamic_ports=[
                                        structs.Port(label="http"),
                                        structs.Port(label="admin"),
                                    ],
                                )
                            ],
                        ),
                        meta={"foo": "bar"},
                    )
                ],
                meta={"elb_check_type": "http"},
            )
        ],
        meta={"owner": "armon"},
        status=consts.JOB_STATUS_PENDING,
        version=0,
        create_index=42,
        modify_index=99,
        job_modify_index=99,
    )
    for k, v in overrides.items():
        setattr(j, k, v)
    return j


def simple_job(**overrides) -> structs.Job:
    """A cpu/mem-only job (no ports) -- the pure binpack bench shape."""
    j = job()
    j.constraints = []
    tg = j.task_groups[0]
    tg.tasks[0].resources = structs.Resources(cpu=500, memory_mb=256)
    tg.tasks[0].driver = "mock_driver"
    for k, v in overrides.items():
        setattr(j, k, v)
    return j


def system_job(**overrides) -> structs.Job:
    j = structs.Job(
        id=f"mock-system-{_uuid()}",
        name="my-job",
        type=consts.JOB_TYPE_SYSTEM,
        priority=100,
        datacenters=["dc1"],
        constraints=[
            structs.Constraint(
                ltarget="${attr.kernel.name}", rtarget="linux", operand="="
            )
        ],
        task_groups=[
            structs.TaskGroup(
                name="web",
                count=1,
                restart_policy=structs.RestartPolicy(
                    attempts=3, interval_s=600, delay_s=60, mode="delay"
                ),
                ephemeral_disk=structs.EphemeralDisk(size_mb=50),
                tasks=[
                    structs.Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date"},
                        resources=structs.Resources(cpu=500, memory_mb=256),
                    )
                ],
            )
        ],
        status=consts.JOB_STATUS_PENDING,
    )
    for k, v in overrides.items():
        setattr(j, k, v)
    return j


def batch_job(**overrides) -> structs.Job:
    j = job()
    j.type = consts.JOB_TYPE_BATCH
    j.id = f"mock-batch-{_uuid()}"
    tg = j.task_groups[0]
    tg.tasks[0].resources = structs.Resources(cpu=500, memory_mb=256)
    for k, v in overrides.items():
        setattr(j, k, v)
    return j


def eval(**overrides) -> structs.Evaluation:
    e = structs.Evaluation(
        namespace="default",
        priority=50,
        type=consts.JOB_TYPE_SERVICE,
        job_id=_uuid(),
        status=consts.EVAL_STATUS_PENDING,
        triggered_by=consts.EVAL_TRIGGER_JOB_REGISTER,
    )
    for k, v in overrides.items():
        setattr(e, k, v)
    return e


def alloc(**overrides) -> structs.Allocation:
    j = job()
    a = structs.Allocation(
        id=_uuid(),
        eval_id=_uuid(),
        node_id="12345678-abcd-efab-cdef-123456789abc",
        namespace="default",
        task_group="web",
        job_id=j.id,
        job=j,
        name="my-job.web[0]",
        desired_status=consts.ALLOC_DESIRED_RUN,
        client_status=consts.ALLOC_CLIENT_PENDING,
        allocated_resources=structs.AllocatedResources(
            tasks={
                "web": structs.AllocatedTaskResources(
                    cpu=structs.AllocatedCpuResources(cpu_shares=500),
                    memory=structs.AllocatedMemoryResources(memory_mb=256),
                )
            },
            shared=structs.AllocatedSharedResources(disk_mb=150),
        ),
    )
    for k, v in overrides.items():
        setattr(a, k, v)
    if "job" not in overrides and "job_id" in overrides:
        a.job = None
    return a
