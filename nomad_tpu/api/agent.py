"""The merged agent process: server and/or client plus the HTTP API.

Reference behavior: command/agent/agent.go — NewAgent (:122) builds
server (setupServer :731) and/or client (setupClient :906) from one
merged config, then NewHTTPServers (http.go:86) exposes /v1.
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

LOG = logging.getLogger(__name__)


class SerialEventWorker:
    """One ordered worker for gossip-event side effects.

    Membership events MUST apply in arrival order: a thread-per-event
    dispatch let a MEMBER_FAILED land after the MEMBER_ALIVE that
    refuted it (the OS scheduler decided raft membership during
    failure flaps). Events enqueue without blocking the gossip rx /
    prober threads — which is the property the thread-per-event design
    existed for (raft applies can stall up to 10s on an impaired
    quorum) — and one daemon thread drains them in FIFO order.
    """

    def __init__(self, handler: Callable[[str, Dict], None],
                 name: str = "membership-reconcile") -> None:
        self._handler = handler
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=name)
        self._thread.start()

    def submit(self, kind: str, member: Dict) -> None:
        self._q.put((kind, member))

    def shutdown(self, timeout: float = 2.0) -> None:
        self._stop.set()
        self._q.put(None)            # wake the drain loop
        self._thread.join(timeout=timeout)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None or self._stop.is_set():
                return
            kind, member = item
            try:
                self._handler(kind, member)
            except Exception:                    # noqa: BLE001
                LOG.exception("membership event handler failed (%s %s)",
                              kind, member.get("Name"))


@dataclass
class AgentConfig:
    """Merged agent configuration (command/agent/config.go:39)."""

    name: str = "agent-1"
    region: str = "global"
    datacenter: str = "dc1"
    bind_addr: str = "127.0.0.1"
    http_port: int = 0            # 0 = ephemeral (reference default 4646)
    server_enabled: bool = True
    client_enabled: bool = False
    dev_mode: bool = False
    acl_enabled: bool = False
    num_schedulers: int = 2
    node_class: str = ""
    plugin_dir: str = ""           # external driver plugins (loader)
    meta: Dict[str, str] = field(default_factory=dict)
    # client { options { "docker.volumes.enabled" = "true" } }
    client_options: Dict[str, str] = field(default_factory=dict)
    tls: Optional[object] = None   # utils.tlsutil.TLSConfig
    # HA server mode (server.go setupRaft + serf-discovered peers; here
    # a static peer set, the reference's server_join/retry_join shape):
    # raft_peers lists every server's raft address host:port, this
    # agent's included
    raft_port: int = 0             # 0 = ephemeral
    raft_peers: List[str] = field(default_factory=list)
    #: address peers dial (host:port); required when binding 0.0.0.0
    raft_advertise: str = ""
    # WAN federation auto-join (serf retry_join analog, agent.go
    # retryJoin/command server_join stanza): entries "region@http_url";
    # retried with backoff until every entry has joined. 0 attempts =
    # retry forever.
    retry_join: List[str] = field(default_factory=list)
    retry_join_interval: float = 5.0
    retry_join_max_attempts: int = 0
    # Server gossip membership (nomad/serf.go over hashicorp/serf;
    # here server/membership.py): liveness-probed `server members`,
    # member events feeding raft peer add/remove on the leader, and
    # join-by-DNS. server_join entries are "host:port" membership
    # addresses (a DNS name expands to every A record).
    serf_enabled: bool = True
    serf_port: int = 0             # 0 = ephemeral
    server_join: List[str] = field(default_factory=list)
    #: probe cadence; tests shrink these for fast convergence
    serf_probe_interval: float = 1.0
    serf_suspect_timeout: float = 3.0
    # shared gossip key (agent `encrypt` config, serf keyring analog):
    # when set, membership datagrams are HMAC-authenticated and
    # unsigned/mismatched packets are rejected
    encrypt: str = ""
    # real Vault server (agent config vault stanza; empty = dev
    # in-memory provider)
    vault_addr: str = ""
    vault_token: str = ""
    vault_token_role: str = ""
    # AOT placement-kernel warmup (ops/warmup.py): None = auto (warm
    # when a manifest exists), plus the manifest path ("" = default
    # ~/.cache location)
    kernel_warmup: Optional[bool] = None
    warmup_manifest: str = ""
    # adaptive wave-coalescer knobs (server block: coalesce_adaptive
    # + coalesce_window_min_ms / coalesce_window_max_ms)
    coalesce_adaptive: bool = True
    coalesce_window_min_ms: float = 1.0
    coalesce_window_max_ms: float = 50.0
    # crash-safe raft durability (raft/wal.py, ISSUE 13): the agent's
    # state dir (reference top-level `data_dir`); empty = in-memory
    # raft. raft_fsync_policy: "always" (per-record) or "batch"
    # (group-fsync at ack boundaries; the default)
    data_dir: str = ""
    raft_fsync_policy: str = "batch"
    # multi-process scheduler workers (server/workerproc.py, ISSUE 17):
    # N worker processes running feasibility/reconcile/plan-build over
    # MVCC snapshot frames; 0 = in-process threads (the default, and
    # bit-identical to pre-17 behavior)
    scheduler_workers: int = 0
    # pipelined AppendEntries + leader leases (raft/node.py, ISSUE 18):
    # raft_max_in_flight bounds the per-peer replication window (1 =
    # the synchronous path); raft_leader_lease gates the quorum-free
    # linearizable-read fast path; raft_lease_fraction is the lease
    # window as a fraction of election_timeout_min
    raft_max_in_flight: int = 8
    raft_leader_lease: bool = True
    raft_lease_fraction: float = 0.75

    @classmethod
    def dev(cls, **overrides) -> "AgentConfig":
        """-dev preset: server + client in one process."""
        return cls(server_enabled=True, client_enabled=True, dev_mode=True,
                   **overrides)


class Agent:
    def __init__(self, config: Optional[AgentConfig] = None) -> None:
        self.config = config or AgentConfig()
        self.server = None
        self.client = None
        self.http = None
        self.acl_resolver = None

        if self.config.server_enabled:
            self._setup_server()
        if self.config.client_enabled:
            self._setup_client()

        from nomad_tpu.api.http import HTTPAgent

        self.http = HTTPAgent(
            self, bind=self.config.bind_addr, port=self.config.http_port,
            tls_config=self.config.tls,
        )
        tls = self.config.tls
        if self.server is not None and tls is not None and tls.enabled:
            # server-originated HTTP (ACL replication) must speak the
            # cluster's TLS
            self.server.tls_api = {
                "ca_cert": tls.ca_file,
                "client_cert": tls.cert_file,
                "client_key": tls.key_file,
            }

    def _setup_server(self) -> None:
        from nomad_tpu.server.server import Server, ServerConfig

        cfg = ServerConfig(
            num_workers=self.config.num_schedulers,
            region=self.config.region,
            datacenter=self.config.datacenter,
            name=self.config.name,
            vault_addr=self.config.vault_addr,
            vault_token=self.config.vault_token,
            vault_token_role=self.config.vault_token_role,
            kernel_warmup=self.config.kernel_warmup,
            warmup_manifest_path=self.config.warmup_manifest,
            coalesce_adaptive=self.config.coalesce_adaptive,
            coalesce_window_min_ms=self.config.coalesce_window_min_ms,
            coalesce_window_max_ms=self.config.coalesce_window_max_ms,
            data_dir=self.config.data_dir,
            raft_fsync_policy=self.config.raft_fsync_policy,
            scheduler_workers=self.config.scheduler_workers,
            raft_max_in_flight=self.config.raft_max_in_flight,
            raft_leader_lease=self.config.raft_leader_lease,
            raft_lease_fraction=self.config.raft_lease_fraction,
        )
        self.server = Server(cfg)
        self.raft_transport = None
        if self.config.raft_peers:
            # HA: raft over TCP between server agents (server.go:1228
            # setupRaft over the RaftLayer; peers here are static the
            # way retry_join server addresses are)
            from nomad_tpu.raft.node import RaftConfig
            from nomad_tpu.raft.transport import TcpTransport

            self.raft_transport = TcpTransport(
                self.config.bind_addr, self.config.raft_port)
            # the raft identity must be the address PEERS can dial;
            # a wildcard bind needs an explicit advertise address or
            # it would join as an undialable phantom member
            self_addr = self.config.raft_advertise or self.raft_transport.addr
            if self_addr.split(":")[0] in ("0.0.0.0", "::"):
                raise ValueError(
                    "raft over a wildcard bind needs raft_advertise "
                    "set to the address peers dial")
            peers = list(self.config.raft_peers)
            if self_addr not in peers:
                peers.append(self_addr)
            self.server.setup_raft(
                node_id=self_addr,
                peers=peers,
                transport=self.raft_transport,
                # python control plane: generous timeouts so GIL-holding
                # compiles don't churn elections (server/testing.py)
                raft_config=RaftConfig(
                    heartbeat_interval=0.05,
                    election_timeout_min=0.30,
                    election_timeout_max=0.60,
                ),
            )
        if self.config.acl_enabled:
            from nomad_tpu.acl.resolver import TokenResolver

            self.acl_resolver = TokenResolver(self.server)
        # default namespace always exists (reference creates it on boot)
        from nomad_tpu.structs.namespace import Namespace

        self.server.state.upsert_namespace(
            Namespace(name="default", description="Default shared namespace")
        )

    def _setup_client(self) -> None:
        from nomad_tpu.client.client import Client, ClientConfig, InProcessRPC

        if self.server is None:
            raise ValueError(
                "client-only agents need a server address (in-process "
                "agent requires server_enabled)"
            )
        cfg = ClientConfig(
            node_class=self.config.node_class,
            plugin_dir=self.config.plugin_dir,
            options=self.config.client_options,
        )
        self.client = Client(InProcessRPC(self.server), cfg)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self.server is not None:
            self.server.start()
            if self.server.raft is None:
                # standalone server is immediately the authority
                self.server.establish_leadership()
            if self.config.retry_join:
                self._start_retry_join()
            if self.config.serf_enabled:
                self._start_membership()
        if self.client is not None:
            # advertise this agent's HTTP address on the node so
            # servers can pass /v1/client/* requests through
            # (client.go HTTPAddr -> Node.HTTPAddr)
            self.client.node.http_addr = self.http.addr
            self.client.start()
        self.http.start()

    def _start_retry_join(self) -> None:
        """Background WAN auto-join (serf retry_join / agent.go
        retryJoin): keep attempting each configured region join with
        backoff until it lands; an unreachable peer at boot must not
        fail the agent, and a later-started peer is joined as soon as
        it answers. The join is recorded through raft (join_region),
        so a success survives failover."""
        import threading

        def run() -> None:
            import time as _time

            pending = {}
            for entry in self.config.retry_join:
                region, _, addr = str(entry).partition("@")
                if not region or not addr:
                    LOG.warning("retry_join: malformed entry %r "
                                "(want region@http_url)", entry)
                    continue
                if region == self.config.region:
                    continue
                pending[region] = addr
            attempt = 0
            delay = self.config.retry_join_interval
            while pending and not self.server._shutdown.is_set():
                attempt += 1
                for region, addr in list(pending.items()):
                    try:
                        # verify the peer answers before recording it
                        from nomad_tpu.api.client import APIClient

                        tls = getattr(self.server, "tls_api", None) or {}
                        APIClient(addr, **tls).get("/v1/agent/self")
                        self.server.join_region(region, addr)
                        del pending[region]
                        LOG.info("retry_join: joined region %s at %s",
                                 region, addr)
                    except Exception as e:      # noqa: BLE001
                        LOG.debug("retry_join %s (%s): %s",
                                  region, addr, e)
                maxa = self.config.retry_join_max_attempts
                if pending and maxa and attempt >= maxa:
                    LOG.warning("retry_join: giving up on %s after %d "
                                "attempts", sorted(pending), attempt)
                    return
                if pending:
                    self.server._shutdown.wait(delay)
                    delay = min(delay * 1.5, 60.0)

        threading.Thread(target=run, daemon=True,
                         name="retry-join").start()

    def _start_membership(self) -> None:
        """Server gossip membership (serf.go:1). Events drive the raft
        voter set on the leader — the reference's nomadJoin adds the
        peer, nomadFailed/reap removes it (leader.go:1182-1345) — so a
        dead server leaves the peer set without operator action and a
        booted one joins without a config edit."""
        from nomad_tpu.server.membership import (
            MEMBER_ALIVE, MEMBER_FAILED, MEMBER_JOIN, MEMBER_LEAVE,
            Membership, expand_join_addrs,
        )

        tags = {
            "region": self.config.region,
            "dc": self.config.datacenter,
            "http_addr": self.http.addr if self.http else "",
        }
        raft = self.server.raft
        if raft is not None:
            tags["raft_addr"] = raft.id
        self._serf = Membership(
            name=self.config.name,
            bind=self.config.bind_addr,
            port=self.config.serf_port,
            tags=tags,
            region=self.config.region,
            probe_interval=self.config.serf_probe_interval,
            suspect_timeout=self.config.serf_suspect_timeout,
            encrypt=self.config.encrypt,
        )

        def reconcile(kind: str, member: dict) -> None:
            raft = self.server.raft if self.server is not None else None
            if raft is None or not raft.is_leader():
                return
            peer = (member.get("Tags") or {}).get("raft_addr", "")
            if not peer or peer == raft.id:
                return
            try:
                if kind in (MEMBER_JOIN, MEMBER_ALIVE):
                    if peer not in raft.peers:
                        raft.add_peer(peer)
                        LOG.info("membership: added raft peer %s (%s)",
                                 peer, member.get("Name"))
                elif kind in (MEMBER_FAILED, MEMBER_LEAVE):
                    if peer not in raft.peers:
                        return
                    # quorum guard (autopilot pruneDeadServers): never
                    # remove below a functioning majority. Judged from
                    # the MEMBERSHIP view — the failure detector that
                    # just fired — not raft last-contact, whose 10s
                    # horizon lags the 3-4s gossip verdict and would
                    # wave through a quorum-breaking removal.
                    dead_addrs = {
                        (m.get("Tags") or {}).get("raft_addr", "")
                        for m in self._serf.members()
                        if m["Status"] in ("failed", "left")
                    }
                    n_total = len(raft.peers) + 1
                    n_dead = sum(1 for p in raft.peers
                                 if p in dead_addrs)
                    if kind == MEMBER_FAILED \
                            and n_total - n_dead <= n_total // 2:
                        LOG.warning("membership: not removing %s: would "
                                    "break quorum", peer)
                        return
                    raft.remove_peer(peer)
                    LOG.info("membership: removed raft peer %s (%s, %s)",
                             peer, member.get("Name"), kind)
            except Exception as e:               # noqa: BLE001
                LOG.warning("membership raft reconcile (%s %s): %s",
                            kind, member.get("Name"), e)

        # ONE ordered worker: raft applies may block up to 10s on an
        # impaired quorum — exactly when failure events fire — so the
        # gossip rx/prober threads never run reconciles inline; but a
        # thread PER event let MEMBER_FAILED/MEMBER_ALIVE flap pairs
        # race each other, and the loser decided the raft voter set
        self._reconcile_worker = SerialEventWorker(reconcile)
        self._serf.on_event(self._reconcile_worker.submit)
        self._serf.start()
        if self.config.server_join:
            targets = expand_join_addrs(self.config.server_join)
            joined = self._serf.join(targets)
            if not joined and targets:
                # seeds not up yet: keep trying in the background the
                # way serf's retry_join does
                def retry() -> None:
                    while not self.server._shutdown.is_set():
                        if self._serf.join(expand_join_addrs(
                                self.config.server_join)):
                            return
                        self.server._shutdown.wait(2.0)

                threading.Thread(target=retry, daemon=True,
                                 name="membership-join").start()

    def shutdown(self) -> None:
        serf = getattr(self, "_serf", None)
        if serf is not None:
            serf.shutdown(leave=True)
        worker = getattr(self, "_reconcile_worker", None)
        if worker is not None:
            worker.shutdown()
        if self.client is not None:
            self.client.shutdown()
        if self.server is not None:
            self.server.shutdown()
        # raft transport is closed by RaftNode.shutdown (one owner)
        if self.http is not None:
            self.http.shutdown()

    @property
    def http_addr(self) -> str:
        return self.http.addr

    def members(self) -> List[Dict]:
        """serf.go Members: this server plus (in HA mode) its raft
        peers — the static-peer analog of gossip membership. The Addr
        column is the raft (server-to-server) address throughout; the
        HTTP address rides in Tags like the reference's rpc_addr."""
        import time as _time

        serf = getattr(self, "_serf", None)
        if serf is not None:
            rows = serf.members()
            raft = self.server.raft if self.server is not None else None
            leader = raft.leader_addr() if raft is not None else None
            for r in rows:
                tags = r.get("Tags") or {}
                if raft is not None:
                    r["Leader"] = bool(leader) and \
                        tags.get("raft_addr", "") == leader
                else:
                    r["Leader"] = (r["Name"] == self.config.name
                                   and self.server is not None
                                   and self.server.is_leader())
            return rows
        tags = {"region": self.config.region,
                "dc": self.config.datacenter,
                "http_addr": self.http.addr if self.http else ""}
        raft = self.server.raft if self.server is not None else None
        if raft is None:
            return [{
                "Name": self.config.name, "Status": "alive",
                "Addr": self.http.addr if self.http else "",
                "Leader": bool(self.server is not None
                               and self.server.is_leader()),
                "Tags": tags,
            }]
        leader = raft.leader_addr()
        out = [{
            "Name": self.config.name, "Status": "alive",
            "Addr": raft.id,
            "Leader": raft.id == leader,
            "Tags": tags,
        }]
        now = _time.monotonic()
        for peer in raft.peers:
            # a peer is failed when it hasn't answered in several
            # election timeouts (only the leader appends entries, so a
            # follower's view of its peers may simply be unobserved)
            seen = raft.peer_last_contact.get(peer)
            if raft.is_leader():
                status = "alive" if seen is not None \
                    and now - seen < 3.0 else "failed"
            else:
                status = "alive" if peer == leader or (
                    seen is not None and now - seen < 3.0) else "unknown"
            out.append({
                "Name": peer, "Status": status,
                "Addr": peer,
                "Leader": peer == leader,
                "Tags": dict(tags, http_addr=""),
            })
        return out
