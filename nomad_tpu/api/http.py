"""The /v1 HTTP API agent.

Reference: command/agent/http.go — registerHandlers (:321-411) route
table, wrap() error handling, blocking-query parameters
(parseWait/parseConsistency), NDJSON event streaming, and the merged
server+client agent process.

Implementation: stdlib ThreadingHTTPServer + a regex route table. Each
handler receives a Request carrying path params, query, decoded JSON
body, and the resolved ACL token; blocking queries ride
StateStore.block_until (the memdb WatchSet analog).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from nomad_tpu.api.codec import decode, encode
from nomad_tpu.server import endpoints
from nomad_tpu.server.readplane import ReadPlaneError
from nomad_tpu.structs import consts
from nomad_tpu.structs.job import Job


class HTTPError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(message)
        self.status = status
        self.message = message


class Request:
    """One parsed HTTP request handed to route handlers."""

    def __init__(self, method: str, path: str, params: Dict[str, str],
                 query: Dict[str, List[str]], body: Optional[Any],
                 token: str, handler: BaseHTTPRequestHandler) -> None:
        self.method = method
        self.path = path
        self.params = params
        self.query = query
        self.body = body
        self.token = token
        self.handler = handler

    def q(self, name: str, default: str = "") -> str:
        vals = self.query.get(name)
        return vals[0] if vals else default

    def flag(self, name: str) -> bool:
        return self.q(name) not in ("", "false", "0")

    @property
    def namespace(self) -> str:
        return self.q("namespace", "default")

    def wait_params(self) -> Tuple[int, float]:
        """parseWait: ?index=N&wait=Dur -> (min_index, timeout_s)."""
        index = int(self.q("index", "0") or 0)
        wait = self.q("wait", "")
        timeout = 300.0
        if wait:
            parsed = parse_duration(wait)
            if parsed is not None:
                timeout = parsed
        return index, min(timeout, 600.0)

    def consistency_params(self) -> Tuple[str, Optional[float]]:
        """parseConsistency (ISSUE 20): ``?stale`` / ``max_stale=<dur>``
        / ``consistency=<mode>`` -> (mode, max_stale_s). An explicit
        ``consistency=`` wins; ``max_stale`` alone implies stale."""
        max_stale = None
        raw = self.q("max_stale", "")
        if raw:
            max_stale = parse_duration(raw)
            if max_stale is None:
                raise HTTPError(400, f"invalid max_stale duration {raw!r}")
        mode = self.q("consistency", "")
        if not mode:
            mode = ("stale" if (self.flag("stale") or max_stale is not None)
                    else "default")
        elif mode not in ("default", "stale", "linearizable"):
            raise HTTPError(400, f"unknown consistency mode {mode!r}")
        return mode, max_stale


def parse_duration(v) -> Optional[float]:
    """Go-style duration -> seconds ('500ms', '10s', '1m', '2h', bare
    numbers are seconds); None if unparseable."""
    if isinstance(v, (int, float)):
        return float(v)
    if not isinstance(v, str):
        return None
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h)?", v)
    if m is None:
        return None
    mult = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}[m.group(2) or "s"]
    return float(m.group(1)) * mult


class HTTPAgent:
    """Routes + lifecycle for one agent's HTTP server."""

    def __init__(self, agent, bind: str = "127.0.0.1", port: int = 0,
                 tls_config=None) -> None:
        self.agent = agent
        self.tls_config = tls_config
        self._routes: List[Tuple[str, re.Pattern, Callable]] = []
        self._register_routes()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _dispatch(self, method: str) -> None:
                outer._handle(self, method)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        class _QuietServer(ThreadingHTTPServer):
            def handle_error(self, request, client_address):
                # socketserver's default prints a raw traceback to
                # stderr; route it through logging instead so stderr
                # stays clean for the process's own consumers. A
                # client dropping mid-response is routine (debug);
                # anything else is a real handler failure and must
                # stay visible at default log levels
                import logging
                import sys

                exc = sys.exc_info()[1]
                log = logging.getLogger(__name__)
                if isinstance(exc, (ConnectionError, TimeoutError)):
                    log.debug("http: client %s dropped: %s",
                              client_address, exc)
                else:
                    log.warning("http: error serving %s",
                                client_address, exc_info=True)

        self.httpd = _QuietServer((bind, port), _Handler)
        self.httpd.daemon_threads = True
        scheme = "http"
        # outbound SSL context for intra-cluster forwarding (region +
        # node proxying must trust the cluster CA and present this
        # agent's cert when peers enforce mTLS)
        self._fwd_context = None
        if tls_config is not None and tls_config.enabled:
            # TLS listener (tlsutil/config.go IncomingTLSConfig); with
            # verify_https_client the handshake requires a CA-signed
            # client cert (mTLS). do_handshake_on_connect=False defers
            # the handshake to the per-connection handler thread so a
            # stalled peer can't block the accept loop.
            from nomad_tpu.utils.tlsutil import client_context, server_context
            self.httpd.socket = server_context(tls_config).wrap_socket(
                self.httpd.socket, server_side=True,
                do_handshake_on_connect=False)
            self._fwd_context = client_context(
                tls_config.ca_file, tls_config.cert_file,
                tls_config.key_file)
            scheme = "https"
        self.addr = (f"{scheme}://{self.httpd.server_address[0]}:"
                     f"{self.httpd.server_address[1]}")
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="http-agent", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- request plumbing (http.go wrap()) -------------------------------

    def _handle(self, handler: BaseHTTPRequestHandler, method: str) -> None:
        parsed = urllib.parse.urlparse(handler.path)
        path = parsed.path
        query = urllib.parse.parse_qs(parsed.query)
        body = None
        raw_body = b""
        length = int(handler.headers.get("Content-Length") or 0)
        if length:
            raw_body = handler.rfile.read(length)
            if raw_body:
                try:
                    body = json.loads(raw_body)
                except json.JSONDecodeError:
                    body = raw_body
        token = handler.headers.get("X-Nomad-Token", "")
        if not token:
            auth = handler.headers.get("Authorization", "")
            if auth.startswith("Bearer "):
                token = auth[7:]
        if not token:
            # browsers cannot set headers on WebSocket upgrades; the
            # UI's exec terminal passes the token as a query param
            # (the reference UI does the same, ui/app/services/token.js).
            # Accepted ONLY for upgrade/stream requests — on plain
            # requests a query token would leak into access logs,
            # proxies, and browser history.
            is_upgrade = "upgrade" in (
                handler.headers.get("Connection", "").lower())
            if is_upgrade or path == "/v1/event/stream":
                token = (query.get("x_nomad_token") or [""])[0]

        # cross-region forwarding (rpc.go:537 forward/forwardRegion):
        # a request naming another region proxies to a server there
        region = (query.get("region") or [""])[0]
        agent_region = self.agent.config.region
        if region and region != agent_region:
            if self.agent.server is None:
                # a client-only agent has no WAN registry; answering
                # locally would masquerade as the remote region
                self._send(handler, 400, {
                    "error": f"No path to region {region}: "
                             "agent has no server",
                })
            else:
                self._forward_region(handler, method, region, parsed,
                                     token, raw_body)
            return

        # server->node pass-through (rpc.go:708 NodeStreamingRpc /
        # nodeConns): proxy /v1/client/* to the HTTP agent on the
        # allocation's node when the alloc doesn't run locally (covers
        # server-only agents AND combined agents asked about another
        # node's alloc)
        if path.startswith("/v1/client/") and self.agent.server is not None \
                and not self._alloc_is_local(parsed):
            self._forward_client(handler, method, parsed, token, raw_body)
            return

        for route_method, pattern, fn in self._routes:
            if route_method != method:
                continue
            m = pattern.fullmatch(path)
            if m is None:
                continue
            # path params arrive percent-encoded (dispatched job IDs
            # contain '/'); decode before handing to endpoint handlers
            params = {
                k: urllib.parse.unquote(v)
                for k, v in m.groupdict().items()
                if v is not None
            }
            req = Request(method, path, params, query, body, token, handler)
            try:
                result = fn(req)
            except HTTPError as e:
                self._send(handler, e.status, {"error": e.message})
            except ReadPlaneError as e:
                # the read plane refused (no leader / over max_stale):
                # loud 503 + the leader hint so callers can re-aim
                if e.known_leader:
                    handler._read_leader_hint = e.known_leader
                self._send(handler, 503, {"error": str(e)})
            except PermissionError as e:
                self._send(handler, 403, {"error": str(e)})
            except KeyError as e:
                self._send(handler, 404, {"error": str(e)})
            except (ValueError, TypeError) as e:
                self._send(handler, 400, {"error": str(e)})
            except Exception as e:  # wrap(): 500 + message
                self._send(handler, 500, {"error": f"{type(e).__name__}: {e}"})
            else:
                if result is not StreamedResponse:
                    status, payload = result if isinstance(result, tuple) else (200, result)
                    self._send(handler, status, payload)
            return
        self._send(handler, 404, {"error": f"no handler for {method} {path}"})

    # endpoints whose responses never end; forwarding must relay
    # them incrementally rather than buffer the body
    _STREAMING_PATHS = frozenset({"/v1/event/stream", "/v1/agent/monitor"})

    def _forward_region(self, handler, method: str, region: str,
                        parsed, token: str, raw_body: bytes) -> None:
        """Proxy the request to the named region's server verbatim
        (minus the region param, so it doesn't loop)."""
        addr = self.agent.server.region_addr(region)
        if addr is None:
            self._send(handler, 400, {"error": f"No path to region {region}"})
            return
        pairs = [(k, v) for k, v in urllib.parse.parse_qsl(parsed.query)
                 if k != "region"]
        url = addr + parsed.path
        if pairs:
            url += "?" + urllib.parse.urlencode(pairs)
        if handler.headers.get("Upgrade", "").lower() == "websocket":
            self._tunnel_websocket(handler, url, token)
            return
        # outlive the remote's blocking-query hold (default 300s,
        # capped at 600s server-side) plus slack
        wait = dict(pairs).get("wait", "")
        hold = parse_duration(wait) if wait else 300.0
        fwd_timeout = min(hold if hold is not None else 300.0, 600.0) + 10.0
        raw_stream = self._wants_stream(parsed)
        if parsed.path in self._STREAMING_PATHS or raw_stream:
            # infinite stream: relay incrementally instead of buffering
            # an unbounded body (NDJSON line-wise, follow-logs raw);
            # outlive the remote's 600s stream deadline
            req = urllib.request.Request(url, method=method)
            if token:
                req.add_header("X-Nomad-Token", token)
            try:
                with urllib.request.urlopen(
                        req, timeout=660.0,
                        context=self._fwd_context) as resp:
                    self._relay_body(handler, resp, raw=raw_stream)
            except (OSError, ValueError, urllib.error.HTTPError) as e:
                self._send(handler, 502,
                           {"error": f"region {region} unreachable: {e}"})
            return
        self._proxy(handler, method, url, token, raw_body,
                    timeout=fwd_timeout, unreachable=f"region {region}")

    _CLIENT_PATH_RE = re.compile(
        r"/v1/client/(?:allocation|fs/[a-z]+)/(?P<id>[^/?]+)"
    )

    def _client_path_alloc_id(self, parsed) -> str:
        m = self._CLIENT_PATH_RE.match(parsed.path)
        return urllib.parse.unquote(m.group("id")) if m else ""

    def _alloc_is_local(self, parsed) -> bool:
        """Does this agent's client run the alloc the path names?"""
        if self.agent.client is None:
            return False
        alloc_id = self._client_path_alloc_id(parsed)
        if not alloc_id:
            return True   # non-alloc client routes (e.g. /v1/client/stats)
        return self.agent.client.alloc_runner(alloc_id) is not None

    def _proxy(self, handler, method: str, url: str, token: str,
               raw_body: bytes, timeout: float = 60.0,
               unreachable: str = "upstream") -> None:
        """Shared HTTP proxy plumbing (region + node forwarding)."""
        req = urllib.request.Request(url, data=raw_body or None,
                                     method=method)
        req.add_header("Content-Type", "application/json")
        if token:
            req.add_header("X-Nomad-Token", token)
        remote_index = None
        try:
            with urllib.request.urlopen(req, timeout=timeout,
                                        context=self._fwd_context) as resp:
                raw, status = resp.read(), resp.status
                remote_index = resp.headers.get("X-Nomad-Index")
        except urllib.error.HTTPError as e:
            raw, status = e.read(), e.code
            remote_index = e.headers.get("X-Nomad-Index")
        except (OSError, ValueError) as e:
            self._send(handler, 502,
                       {"error": f"{unreachable} unreachable: {e}"})
            return
        try:
            payload = json.loads(raw) if raw else None
        except json.JSONDecodeError:
            # non-JSON upstream body (e.g. /v1/metrics?format=prometheus
            # raw text exposition): relay it verbatim with the remote's
            # content type instead of mangling it into a 502
            if 200 <= status < 300:
                self._send_text(
                    handler, raw.decode("utf-8", "replace"), status=status)
                return
            status, payload = 502, {"error": "bad upstream response"}
        self._send(handler, status, payload, index=remote_index)

    def _forward_client(self, handler, method, parsed, token,
                        raw_body) -> None:
        """Resolve the alloc's node and proxy the request there."""
        snap = self.agent.server.state.snapshot()
        node = None
        alloc_id = self._client_path_alloc_id(parsed)
        if alloc_id:
            alloc = snap.alloc_by_id(alloc_id)
            if alloc is None:
                self._send(handler, 404, {"error": "unknown allocation"})
                return
            node = snap.node_by_id(alloc.node_id)
        else:
            node_id = (urllib.parse.parse_qs(parsed.query)
                       .get("node_id") or [""])[0]
            if node_id:
                node = snap.node_by_id(node_id)
        if node is None or not getattr(node, "http_addr", ""):
            self._send(handler, 404,
                       {"error": "no client agent reachable for request"})
            return
        if node.http_addr == self.addr:
            # the alloc is assigned here but its runner hasn't started
            # yet; proxying to ourselves would loop
            self._send(handler, 404,
                       {"error": "allocation not yet running on node"})
            return
        url = node.http_addr + parsed.path
        if parsed.query:
            url += "?" + parsed.query
        if handler.headers.get("Upgrade", "").lower() == "websocket":
            # interactive exec: opaque byte tunnel to the node's agent
            # (rpc.go:708 NodeStreamingRpc analog)
            self._tunnel_websocket(handler, url, token)
            return
        if self._wants_stream(parsed):
            req = urllib.request.Request(url, method=method)
            if token:
                req.add_header("X-Nomad-Token", token)
            try:
                with urllib.request.urlopen(
                        req, timeout=660.0,
                        context=self._fwd_context) as resp:
                    self._relay_raw(handler, resp)
            except (OSError, ValueError, urllib.error.HTTPError) as e:
                self._send(handler, 502, {"error": f"node unreachable: {e}"})
            return
        self._proxy(handler, method, url, token, raw_body,
                    unreachable="node")

    def _tunnel_websocket(self, handler, url: str, token: str) -> None:
        """Relay a websocket upgrade + both byte directions verbatim.

        The tunnel re-issues the upgrade toward the node with the
        caller's Sec-WebSocket-Key, writes the node's 101 response back,
        then pumps raw bytes both ways — no frame parsing needed."""
        import socket
        import ssl as _ssl

        parsed = urllib.parse.urlparse(url)
        host = parsed.hostname
        port = parsed.port or (443 if parsed.scheme == "https" else 80)
        try:
            upstream = socket.create_connection((host, port), timeout=30)
            if parsed.scheme == "https":
                ctx = self._fwd_context or _ssl.create_default_context()
                upstream = ctx.wrap_socket(upstream, server_hostname=host)
            # connect timeout only; a quiet session must stay open
            upstream.settimeout(None)
        except OSError as e:
            self._send(handler, 502, {"error": f"node unreachable: {e}"})
            return
        path = parsed.path + (f"?{parsed.query}" if parsed.query else "")
        lines = [f"GET {path} HTTP/1.1", f"Host: {host}:{port}"]
        for h in ("Upgrade", "Connection", "Sec-WebSocket-Key",
                  "Sec-WebSocket-Version"):
            v = handler.headers.get(h)
            if v:
                lines.append(f"{h}: {v}")
        if token:
            lines.append(f"X-Nomad-Token: {token}")
        try:
            upstream.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
        except OSError as e:
            self._send(handler, 502, {"error": f"node unreachable: {e}"})
            upstream.close()
            return

        handler.close_connection = True
        down = handler.connection

        def shut(*socks) -> None:
            for s in socks:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

        def pump_up() -> None:
            # downstream reads go through rfile: it may hold frames the
            # header parser read ahead of
            try:
                while True:
                    data = handler.rfile.read1(65536)
                    if not data:
                        break
                    upstream.sendall(data)
            except (OSError, ValueError):
                pass
            finally:
                shut(down, upstream)

        t = threading.Thread(target=pump_up, daemon=True,
                             name="ws-tunnel-up")
        t.start()
        try:
            while True:
                data = upstream.recv(65536)
                if not data:
                    break
                down.sendall(data)
        except OSError:
            pass
        finally:
            shut(down, upstream)
        t.join(timeout=5)
        try:
            upstream.close()
        except OSError:
            pass

    @staticmethod
    def _wants_stream(parsed) -> bool:
        """Endpoints whose responses never end mid-request: follow-mode
        log tails (the exact-path streaming set is separate)."""
        q = urllib.parse.parse_qs(parsed.query)
        return parsed.path.startswith("/v1/client/fs/logs/") and \
            (q.get("follow") or [""])[0] not in ("", "false", "0")

    def _relay_raw(self, handler, resp) -> None:
        self._relay_body(handler, resp, raw=True)

    def _relay_body(self, handler, resp, raw: bool) -> None:
        """Pipe a remote endless stream through as it arrives — raw
        byte chunks (follow logs) or NDJSON line-wise (event stream,
        monitor). Always terminates the chunked framing."""
        try:
            handler.send_response(resp.status)
            handler.send_header(
                "Content-Type",
                resp.headers.get("Content-Type", "application/json"))
            handler.send_header("Transfer-Encoding", "chunked")
            handler.end_headers()
            if raw:
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    self._write_chunk(handler, chunk)
            else:
                for line in resp:
                    self._write_chunk(handler, line)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self._end_chunks(handler)

    def _relay_stream(self, handler, resp) -> None:
        self._relay_body(handler, resp, raw=False)

    def _send(self, handler, status: int, payload, index=None) -> None:
        """``index`` overrides the stamped X-Nomad-Index (forwarded
        responses must carry the REMOTE region's index or cross-region
        blocking queries spin)."""
        try:
            data = json.dumps(encode(payload)).encode()
            handler.send_response(status)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(data)))
            if index is None:
                index = self.agent.server.state.latest_index() \
                    if self.agent.server else 0
            handler.send_header("X-Nomad-Index", str(index))
            # read-plane attribution (ISSUE 20): every routed read
            # carries how stale its data may be and where the leader
            # is; a refused read still carries the leader hint
            ctx = getattr(handler, "_read_ctx", None)
            if ctx is not None:
                handler.send_header("X-Nomad-Last-Contact",
                                    str(ctx.last_contact_ms))
                if ctx.known_leader:
                    handler.send_header("X-Nomad-Known-Leader",
                                        ctx.known_leader)
            else:
                hint = getattr(handler, "_read_leader_hint", "")
                if hint:
                    handler.send_header("X-Nomad-Known-Leader", hint)
            handler.end_headers()
            handler.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _send_text(self, handler, body: str, status: int = 200,
                   content_type: str = "text/plain; version=0.0.4") -> None:
        """Raw text response (Prometheus exposition is not JSON)."""
        try:
            data = body.encode()
            handler.send_response(status)
            handler.send_header("Content-Type", content_type)
            handler.send_header("Content-Length", str(len(data)))
            handler.end_headers()
            handler.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _block(self, req: Request, tables: List[str]) -> None:
        """Blocking query: wait until any listed table passes ?index."""
        min_index, timeout = req.wait_params()
        if min_index > 0 and self.agent.server is not None:
            self.agent.server.state.block_until(tables, min_index + 1, timeout)

    def _read(self, req: Request, tables: Optional[List[str]] = None):
        """Consistency-routed read (ISSUE 20): resolve the mode fence
        through the server's read plane, run the blocking-query wait
        against the LOCAL store (followers wake on their own FSM
        applies), then take the serving snapshot. Order matters: the
        fence first (a default-mode follower read is ordered after the
        leader's commit frontier before it blocks or serves), the
        snapshot last (it sees everything the fence + wait admitted).
        Raises ReadPlaneError -> 503 when the plane refuses."""
        server = self._server
        mode, max_stale = req.consistency_params()
        ctx = server.readplane.resolve(mode, max_stale)
        req.handler._read_ctx = ctx
        if tables:
            self._block(req, tables)
        return server.state.snapshot()

    # -- ACL gate --------------------------------------------------------

    def _acl(self, req: Request, check: str, *args) -> None:
        """Resolve + enforce (nomad/acl.go ResolveToken). No-op until
        ACLs are enabled on the agent."""
        resolver = getattr(self.agent, "acl_resolver", None)
        if resolver is None:
            return
        acl = resolver.resolve(req.token)
        if not getattr(acl, check)(*args):
            raise HTTPError(403, "Permission denied")

    @property
    def _server(self):
        s = self.agent.server
        if s is None:
            raise HTTPError(400, "server is not enabled on this agent")
        return s

    # -- route table (http.go:321-411) -----------------------------------

    def _register_routes(self) -> None:
        def add(method: str, pattern: str, fn) -> None:
            self._routes.append((method, re.compile(pattern), fn))

        # web UI (reference serves the Ember app at /ui; http.go:318)
        add("GET", r"/", self.ui_redirect)
        add("GET", r"/ui/app\.js", self.ui_app_js)
        add("GET", r"/ui(?:/.*)?", self.ui_index)

        # jobs
        add("GET", r"/v1/jobs", self.jobs_list)
        add("PUT", r"/v1/jobs", self.job_register)
        add("POST", r"/v1/jobs", self.job_register)
        add("POST", r"/v1/jobs/parse", self.jobs_parse)
        add("PUT", r"/v1/validate/job", self.job_validate)
        add("POST", r"/v1/validate/job", self.job_validate)
        add("GET", r"/v1/job/(?P<id>[^/]+)", self.job_get)
        add("POST", r"/v1/job/(?P<id>[^/]+)", self.job_update)
        add("PUT", r"/v1/job/(?P<id>[^/]+)", self.job_update)
        add("DELETE", r"/v1/job/(?P<id>[^/]+)", self.job_delete)
        add("PUT", r"/v1/job/(?P<id>[^/]+)/plan", self.job_plan)
        add("POST", r"/v1/job/(?P<id>[^/]+)/plan", self.job_plan)
        add("GET", r"/v1/job/(?P<id>[^/]+)/allocations", self.job_allocs)
        add("GET", r"/v1/job/(?P<id>[^/]+)/evaluations", self.job_evals)
        add("GET", r"/v1/job/(?P<id>[^/]+)/deployments", self.job_deployments)
        add("GET", r"/v1/job/(?P<id>[^/]+)/deployment", self.job_latest_deployment)
        # multiregion gate release + failure propagation
        # (Deployment.Unblock / Deployment.Fail analogs, by job)
        add("POST", r"/v1/job/(?P<id>[^/]+)/deployment/unblock",
            self.job_deployment_unblock)
        add("POST", r"/v1/job/(?P<id>[^/]+)/deployment/fail",
            self.job_deployment_fail)
        add("GET", r"/v1/job/(?P<id>[^/]+)/summary", self.job_summary)
        add("GET", r"/v1/job/(?P<id>[^/]+)/versions", self.job_versions)
        add("POST", r"/v1/job/(?P<id>[^/]+)/revert", self.job_revert)
        add("PUT", r"/v1/job/(?P<id>[^/]+)/revert", self.job_revert)
        add("POST", r"/v1/job/(?P<id>[^/]+)/stable", self.job_stable)
        add("PUT", r"/v1/job/(?P<id>[^/]+)/stable", self.job_stable)
        add("POST", r"/v1/job/(?P<id>[^/]+)/dispatch", self.job_dispatch)
        add("PUT", r"/v1/job/(?P<id>[^/]+)/dispatch", self.job_dispatch)
        add("POST", r"/v1/job/(?P<id>[^/]+)/scale", self.job_scale)
        add("PUT", r"/v1/job/(?P<id>[^/]+)/scale", self.job_scale)
        add("GET", r"/v1/job/(?P<id>[^/]+)/scale", self.job_scale_status)
        add("POST", r"/v1/job/(?P<id>[^/]+)/periodic/force", self.job_periodic_force)
        add("PUT", r"/v1/job/(?P<id>[^/]+)/periodic/force", self.job_periodic_force)

        # nodes
        add("GET", r"/v1/nodes", self.nodes_list)
        add("GET", r"/v1/node/(?P<id>[^/]+)", self.node_get)
        add("GET", r"/v1/node/(?P<id>[^/]+)/allocations", self.node_allocs)
        add("POST", r"/v1/node/(?P<id>[^/]+)/drain", self.node_drain)
        add("PUT", r"/v1/node/(?P<id>[^/]+)/drain", self.node_drain)
        add("POST", r"/v1/node/(?P<id>[^/]+)/eligibility", self.node_eligibility)
        add("PUT", r"/v1/node/(?P<id>[^/]+)/eligibility", self.node_eligibility)
        add("POST", r"/v1/node/(?P<id>[^/]+)/evaluate", self.node_evaluate)
        add("PUT", r"/v1/node/(?P<id>[^/]+)/evaluate", self.node_evaluate)
        add("POST", r"/v1/node/(?P<id>[^/]+)/purge", self.node_purge)
        add("PUT", r"/v1/node/(?P<id>[^/]+)/purge", self.node_purge)

        # allocations
        add("GET", r"/v1/allocations", self.allocs_list)
        add("GET", r"/v1/allocation/(?P<id>[^/]+)", self.alloc_get)
        add("POST", r"/v1/allocation/(?P<id>[^/]+)/stop", self.alloc_stop)
        add("PUT", r"/v1/allocation/(?P<id>[^/]+)/stop", self.alloc_stop)

        # evaluations
        add("GET", r"/v1/evaluations", self.evals_list)
        add("GET", r"/v1/evaluation/(?P<id>[^/]+)", self.eval_get)
        add("GET", r"/v1/evaluation/(?P<id>[^/]+)/allocations", self.eval_allocs)

        # deployments
        add("GET", r"/v1/deployments", self.deployments_list)
        add("GET", r"/v1/deployment/(?P<id>[^/]+)", self.deployment_get)
        add("GET", r"/v1/deployment/allocations/(?P<id>[^/]+)", self.deployment_allocs)
        add("POST", r"/v1/deployment/fail/(?P<id>[^/]+)", self.deployment_fail)
        add("PUT", r"/v1/deployment/fail/(?P<id>[^/]+)", self.deployment_fail)
        add("POST", r"/v1/deployment/pause/(?P<id>[^/]+)", self.deployment_pause)
        add("PUT", r"/v1/deployment/pause/(?P<id>[^/]+)", self.deployment_pause)
        add("POST", r"/v1/deployment/promote/(?P<id>[^/]+)", self.deployment_promote)
        add("PUT", r"/v1/deployment/promote/(?P<id>[^/]+)", self.deployment_promote)

        # status / agent / operator
        add("GET", r"/v1/regions", self.regions_list)
        add("GET", r"/v1/status/leader", self.status_leader)
        add("GET", r"/v1/status/peers", self.status_peers)
        add("GET", r"/v1/agent/self", self.agent_self)
        add("GET", r"/v1/agent/health", self.agent_health)
        add("GET", r"/v1/agent/members", self.agent_members)
        add("PUT", r"/v1/agent/join", self.agent_join)
        add("POST", r"/v1/agent/join", self.agent_join)
        add("GET", r"/v1/agent/monitor", self.agent_monitor)
        add("GET", r"/v1/agent/pprof/goroutine", self.pprof_goroutine)
        add("GET", r"/v1/agent/pprof/profile", self.pprof_profile)
        add("GET", r"/v1/agent/pprof/heap", self.pprof_heap)
        add("GET", r"/v1/agent/servers", self.agent_servers)
        add("GET", r"/v1/metrics", self.metrics)
        add("GET", r"/v1/operator/traces", self.operator_traces)
        add("PUT", r"/v1/operator/traces", self.operator_traces_put)
        add("POST", r"/v1/operator/traces", self.operator_traces_put)
        add("GET", r"/v1/operator/slow-evals", self.operator_slow_evals)
        add("GET", r"/v1/operator/slow-raft", self.operator_slow_raft)
        add("GET", r"/v1/operator/stream-health", self.operator_stream_health)
        add("GET", r"/v1/operator/cluster-health",
            self.operator_cluster_health)
        add("GET", r"/v1/operator/scheduler/configuration", self.sched_config_get)
        add("PUT", r"/v1/operator/scheduler/configuration", self.sched_config_put)
        add("POST", r"/v1/operator/scheduler/configuration", self.sched_config_put)
        add("GET", r"/v1/operator/raft/configuration", self.raft_config)
        add("GET", r"/v1/operator/autopilot/configuration",
            self.autopilot_config_get)
        add("PUT", r"/v1/operator/autopilot/configuration",
            self.autopilot_config_put)
        add("POST", r"/v1/operator/autopilot/configuration",
            self.autopilot_config_put)
        add("GET", r"/v1/operator/autopilot/health", self.autopilot_health)
        add("GET", r"/v1/operator/snapshot", self.snapshot_save)
        add("PUT", r"/v1/operator/snapshot", self.snapshot_restore)
        add("POST", r"/v1/operator/snapshot", self.snapshot_restore)

        # system
        add("PUT", r"/v1/system/gc", self.system_gc)
        add("POST", r"/v1/system/gc", self.system_gc)
        add("PUT", r"/v1/system/reconcile/summaries", self.system_reconcile)
        add("POST", r"/v1/system/reconcile/summaries", self.system_reconcile)

        # search
        add("POST", r"/v1/search", self.search)
        add("PUT", r"/v1/search", self.search)
        add("POST", r"/v1/search/fuzzy", self.search_fuzzy)
        add("PUT", r"/v1/search/fuzzy", self.search_fuzzy)

        # namespaces
        add("GET", r"/v1/namespaces", self.namespaces_list)
        add("GET", r"/v1/namespace/(?P<name>[^/]+)", self.namespace_get)
        add("PUT", r"/v1/namespace/(?P<name>[^/]+)", self.namespace_upsert)
        add("POST", r"/v1/namespace/(?P<name>[^/]+)", self.namespace_upsert)
        add("PUT", r"/v1/namespace", self.namespace_upsert)
        add("POST", r"/v1/namespace", self.namespace_upsert)
        add("DELETE", r"/v1/namespace/(?P<name>[^/]+)", self.namespace_delete)

        # scaling
        add("GET", r"/v1/scaling/policies", self.scaling_policies)
        add("GET", r"/v1/scaling/policy/(?P<id>.+)", self.scaling_policy)

        # CSI volumes + plugins (http.go CSIVolumesRequest)
        add("GET", r"/v1/volumes", self.volumes_list)
        add("PUT", r"/v1/volumes", self.volume_register)
        add("POST", r"/v1/volumes", self.volume_register)
        add("GET", r"/v1/volume/csi/(?P<id>[^/]+)", self.volume_get)
        add("PUT", r"/v1/volume/csi/(?P<id>[^/]+)", self.volume_register)
        add("POST", r"/v1/volume/csi/(?P<id>[^/]+)", self.volume_register)
        add("DELETE", r"/v1/volume/csi/(?P<id>[^/]+)", self.volume_deregister)
        add("PUT", r"/v1/volume/csi/(?P<id>[^/]+)/create", self.volume_create)
        add("POST", r"/v1/volume/csi/(?P<id>[^/]+)/create", self.volume_create)
        add("DELETE", r"/v1/volume/csi/(?P<id>[^/]+)/delete", self.volume_delete)
        add("PUT", r"/v1/volume/csi/(?P<id>[^/]+)/detach", self.volume_detach)
        add("POST", r"/v1/volume/csi/(?P<id>[^/]+)/detach", self.volume_detach)
        add("GET", r"/v1/plugins", self.plugins_list)
        add("GET", r"/v1/plugin/csi/(?P<id>[^/]+)", self.plugin_get)

        # native service discovery (http.go ServiceRegistrations)
        add("GET", r"/v1/services", self.services_list)
        add("GET", r"/v1/service/(?P<name>[^/]+)", self.service_get)
        add("DELETE", r"/v1/service/(?P<name>[^/]+)/(?P<id>[^/]+)",
            self.service_delete)

        # event stream
        add("GET", r"/v1/event/stream", self.event_stream)

        # ACL
        add("POST", r"/v1/acl/bootstrap", self.acl_bootstrap)
        add("PUT", r"/v1/acl/bootstrap", self.acl_bootstrap)
        add("GET", r"/v1/acl/policies", self.acl_policies_list)
        add("GET", r"/v1/acl/policy/(?P<name>[^/]+)", self.acl_policy_get)
        add("PUT", r"/v1/acl/policy/(?P<name>[^/]+)", self.acl_policy_put)
        add("POST", r"/v1/acl/policy/(?P<name>[^/]+)", self.acl_policy_put)
        add("DELETE", r"/v1/acl/policy/(?P<name>[^/]+)", self.acl_policy_delete)
        add("GET", r"/v1/acl/tokens", self.acl_tokens_list)
        add("POST", r"/v1/acl/token/onetime", self.acl_ott_create)
        add("PUT", r"/v1/acl/token/onetime", self.acl_ott_create)
        add("POST", r"/v1/acl/token/onetime/exchange", self.acl_ott_exchange)
        add("PUT", r"/v1/acl/token/onetime/exchange", self.acl_ott_exchange)
        add("PUT", r"/v1/acl/token", self.acl_token_put)
        add("POST", r"/v1/acl/token", self.acl_token_put)
        add("GET", r"/v1/acl/token/self", self.acl_token_self)
        add("GET", r"/v1/acl/token/(?P<id>[^/]+)", self.acl_token_get)
        add("PUT", r"/v1/acl/token/(?P<id>[^/]+)", self.acl_token_put)
        add("POST", r"/v1/acl/token/(?P<id>[^/]+)", self.acl_token_put)
        add("DELETE", r"/v1/acl/token/(?P<id>[^/]+)", self.acl_token_delete)

        # client (stats/fs) routes
        add("GET", r"/v1/client/allocation/(?P<id>[^/]+)/stats", self.client_alloc_stats)
        add("POST", r"/v1/client/allocation/(?P<id>[^/]+)/restart", self.client_alloc_restart)
        add("PUT", r"/v1/client/allocation/(?P<id>[^/]+)/restart", self.client_alloc_restart)
        add("POST", r"/v1/client/allocation/(?P<id>[^/]+)/signal", self.client_alloc_signal)
        add("PUT", r"/v1/client/allocation/(?P<id>[^/]+)/signal", self.client_alloc_signal)
        add("POST", r"/v1/client/allocation/(?P<id>[^/]+)/exec", self.client_alloc_exec)
        add("PUT", r"/v1/client/allocation/(?P<id>[^/]+)/exec", self.client_alloc_exec)
        # websocket upgrade (interactive exec, api/allocations_exec.go)
        add("GET", r"/v1/client/allocation/(?P<id>[^/]+)/exec", self.client_alloc_exec)
        add("GET", r"/v1/client/fs/logs/(?P<id>[^/]+)", self.client_fs_logs)
        add("GET", r"/v1/client/fs/ls/(?P<id>[^/]+)", self.client_fs_ls)
        add("GET", r"/v1/client/fs/stat/(?P<id>[^/]+)", self.client_fs_stat)
        add("GET", r"/v1/client/fs/cat/(?P<id>[^/]+)", self.client_fs_cat)
        add("GET", r"/v1/client/fs/readat/(?P<id>[^/]+)", self.client_fs_readat)
        add("GET", r"/v1/client/stats", self.client_stats)

    # -- job handlers ----------------------------------------------------

    def _decode_job(self, data: Dict) -> Job:
        payload = data.get("Job", data) if isinstance(data, dict) else data
        job = decode(payload, Job)
        if job is None or not job.id:
            raise HTTPError(400, "Job must be specified")
        if not job.namespace:
            job.namespace = "default"
        return job

    def jobs_list(self, req: Request):
        self._acl(req, "allow_ns_op", req.namespace, "read-job")
        snap = self._read(req, ["jobs"])
        prefix = req.q("prefix")
        jobs = [
            _job_stub(j) for j in snap.jobs()
            if j.namespace == req.namespace and j.id.startswith(prefix)
        ]
        return sorted(jobs, key=lambda j: j["ID"])

    def job_register(self, req: Request):
        job = self._decode_job(req.body)
        self._acl(req, "allow_ns_op", job.namespace, "submit-job")
        res = self._server.job_register(job, token=req.token)
        return {"EvalID": res["eval_id"], "EvalCreateIndex": res["index"],
                "JobModifyIndex": res["index"], "Warnings": "; ".join(res["warnings"])}

    def job_update(self, req: Request):
        return self.job_register(req)

    def job_validate(self, req: Request):
        """Job.Validate (job_endpoint.go Validate): structural check
        without committing anything."""
        from nomad_tpu.structs.job import Job

        body = req.body or {}
        if not isinstance(body, dict) or "Job" not in body:
            raise HTTPError(400, "Job is required")
        job = decode(body["Job"], Job)
        errs = job.validate()
        return {
            "DriverConfigValidated": True,
            "ValidationErrors": errs,
            "Error": "; ".join(errs) if errs else "",
            "Warnings": "",
        }

    def jobs_parse(self, req: Request):
        from nomad_tpu.jobspec.parse import parse_hcl

        if not isinstance(req.body, dict) or "JobHCL" not in req.body:
            raise HTTPError(400, "JobHCL is required")
        job = parse_hcl(req.body["JobHCL"],
                        req.body.get("Variables") or None)
        return encode(job)

    def job_get(self, req: Request):
        self._acl(req, "allow_ns_op", req.namespace, "read-job")
        snap = self._read(req, ["jobs"])
        job = snap.job_by_id(req.namespace, req.params["id"])
        if job is None:
            raise HTTPError(404, "job not found")
        return job

    def job_delete(self, req: Request):
        self._acl(req, "allow_ns_op", req.namespace, "submit-job")
        res = self._server.job_deregister(
            req.namespace, req.params["id"], purge=req.flag("purge")
        )
        return {"EvalID": res["eval_id"], "EvalCreateIndex": res["index"],
                "JobModifyIndex": res["index"]}

    def job_plan(self, req: Request):
        job = self._decode_job(req.body)
        self._acl(req, "allow_ns_op", job.namespace, "submit-job")
        diff = bool(req.body.get("Diff")) if isinstance(req.body, dict) else False
        res = endpoints.job_plan(self._server, job, diff=diff)
        return {
            "Annotations": res["annotations"],
            "FailedTGAllocs": res["failed_tg_allocs"],
            "Diff": res["diff"],
            "JobModifyIndex": res["job_modify_index"],
            "CreatedEvals": res["created_evals"],
        }

    def job_allocs(self, req: Request):
        self._acl(req, "allow_ns_op", req.namespace, "read-job")
        snap = self._read(req, ["allocs"])
        allocs = snap.allocs_by_job(req.namespace, req.params["id"])
        return [_alloc_stub(a) for a in allocs]

    def job_evals(self, req: Request):
        snap = self._read(req, ["evals"])
        return snap.evals_by_job(req.namespace, req.params["id"])

    def job_deployments(self, req: Request):
        snap = self._read(req, ["deployment"])
        return snap.deployments_by_job_id(req.namespace, req.params["id"])

    def job_latest_deployment(self, req: Request):
        snap = self._read(req, ["deployment"])
        return snap.latest_deployment_by_job_id(req.namespace, req.params["id"])

    def job_deployment_unblock(self, req: Request):
        """Multiregion gate release: an earlier region succeeded
        (Deployment.Unblock; deployment watcher cross-region kick)."""
        self._acl(req, "allow_ns_op", req.namespace, "submit-job")
        index, unblocked = self._server.unblock_job_deployment(
            req.namespace, req.params["id"])
        return {"Index": index, "Unblocked": unblocked}

    def job_deployment_fail(self, req: Request):
        """Multiregion failure propagation: an earlier/peer region
        failed and the job's on_failure strategy fails this one too."""
        self._acl(req, "allow_ns_op", req.namespace, "submit-job")
        index, failed = self._server.fail_job_deployment(
            req.namespace, req.params["id"],
            "Failed because of an unsuccessful deployment in a "
            "federated region")
        return {"Index": index, "Failed": failed}

    def job_summary(self, req: Request):
        snap = self._read(req, ["allocs"])
        job = snap.job_by_id(req.namespace, req.params["id"])
        if job is None:
            raise HTTPError(404, "job not found")
        summary: Dict[str, Dict[str, int]] = {}
        for tg in job.task_groups:
            summary[tg.name] = {
                "Queued": 0, "Complete": 0, "Failed": 0, "Running": 0,
                "Starting": 0, "Lost": 0, "Unknown": 0,
            }
        for a in snap.allocs_by_job(req.namespace, job.id):
            tg = summary.setdefault(a.task_group, {
                "Queued": 0, "Complete": 0, "Failed": 0, "Running": 0,
                "Starting": 0, "Lost": 0, "Unknown": 0,
            })
            status = {
                consts.ALLOC_CLIENT_PENDING: "Starting",
                consts.ALLOC_CLIENT_RUNNING: "Running",
                consts.ALLOC_CLIENT_COMPLETE: "Complete",
                consts.ALLOC_CLIENT_FAILED: "Failed",
                consts.ALLOC_CLIENT_LOST: "Lost",
                consts.ALLOC_CLIENT_UNKNOWN: "Unknown",
            }.get(a.client_status, "Starting")
            tg[status] += 1
        return {"JobID": job.id, "Namespace": job.namespace, "Summary": summary}

    def job_versions(self, req: Request):
        snap = self._read(req, ["jobs"])
        versions = []
        v = 0
        job = snap.job_by_id(req.namespace, req.params["id"])
        if job is None:
            raise HTTPError(404, "job not found")
        for v in range(job.version, -1, -1):
            jv = snap.job_by_id_and_version(req.namespace, req.params["id"], v)
            if jv is not None:
                versions.append(jv)
        return {"Versions": versions}

    def job_revert(self, req: Request):
        body = req.body or {}
        res = endpoints.job_revert(
            self._server, req.namespace, req.params["id"],
            int(body.get("JobVersion", 0)),
            body.get("EnforcePriorVersion"),
        )
        return {"EvalID": res["eval_id"], "Index": res["index"]}

    def job_stable(self, req: Request):
        body = req.body or {}
        res = endpoints.job_stable(
            self._server, req.namespace, req.params["id"],
            int(body.get("JobVersion", 0)), bool(body.get("Stable", False)),
        )
        return {"Index": res["index"]}

    def job_dispatch(self, req: Request):
        body = req.body or {}
        import base64

        payload = base64.b64decode(body.get("Payload", "") or "")
        res = endpoints.job_dispatch(
            self._server, req.namespace, req.params["id"],
            payload=payload, meta=body.get("Meta") or {},
        )
        return {"DispatchedJobID": res["dispatched_job_id"],
                "EvalID": res["eval_id"], "Index": res["index"]}

    def job_scale(self, req: Request):
        body = req.body or {}
        target = body.get("Target") or {}
        res = endpoints.job_scale(
            self._server, req.namespace, req.params["id"],
            target.get("Group", ""),
            body.get("Count"),
            message=body.get("Message", ""),
            error=bool(body.get("Error", False)),
            meta=body.get("Meta"),
        )
        return {"EvalID": res["eval_id"], "EvalCreateIndex": res["index"]}

    def job_scale_status(self, req: Request):
        snap = self._read(req)
        job = snap.job_by_id(req.namespace, req.params["id"])
        if job is None:
            raise HTTPError(404, "job not found")
        groups = {}
        allocs = snap.allocs_by_job(req.namespace, job.id)
        for tg in job.task_groups:
            running = sum(
                1 for a in allocs
                if a.task_group == tg.name
                and a.client_status == consts.ALLOC_CLIENT_RUNNING
            )
            groups[tg.name] = {
                "Desired": tg.count,
                "Running": running,
                "Events": self._server.state.scaling_events(req.namespace, job.id),
            }
        return {"JobID": job.id, "JobStopped": job.stopped(),
                "TaskGroups": groups}

    def job_periodic_force(self, req: Request):
        snap = self._server.state.snapshot()
        job = snap.job_by_id(req.namespace, req.params["id"])
        if job is None:
            raise HTTPError(404, "job not found")
        if not job.is_periodic():
            raise HTTPError(400, "job is not periodic")
        child = self._server.periodic_dispatcher.force_run(job)
        return {"EvalCreateIndex": self._server.state.latest_index(),
                "EvalID": child}

    # -- node handlers ---------------------------------------------------

    def nodes_list(self, req: Request):
        self._acl(req, "allow_node_read")
        snap = self._read(req, ["nodes"])
        prefix = req.q("prefix")
        with_res = req.flag("resources")
        return sorted(
            (_node_stub(n, resources=with_res)
             for n in snap.nodes() if n.id.startswith(prefix)),
            key=lambda n: n["ID"],
        )

    def node_get(self, req: Request):
        self._acl(req, "allow_node_read")
        snap = self._read(req, ["nodes"])
        node = snap.node_by_id(req.params["id"])
        if node is None:
            raise HTTPError(404, "node not found")
        return node

    def node_allocs(self, req: Request):
        snap = self._read(req, ["allocs"])
        return snap.allocs_by_node(req.params["id"])

    def node_drain(self, req: Request):
        self._acl(req, "allow_node_write")
        body = req.body or {}
        spec = body.get("DrainSpec")
        enable = spec is not None
        strategy = None
        if enable:
            from nomad_tpu.server.drainer import DrainStrategy
            strategy = DrainStrategy(
                deadline_s=float(spec.get("Deadline", 0)) / 1e9
                if spec.get("Deadline") else 3600.0,
                ignore_system_jobs=bool(spec.get("IgnoreSystemJobs",
                                                 False)),
            )
        index = self._server.node_update_drain(req.params["id"], enable, strategy)
        return {"EvalIDs": [], "EvalCreateIndex": index, "NodeModifyIndex": index}

    def node_eligibility(self, req: Request):
        self._acl(req, "allow_node_write")
        body = req.body or {}
        elig = body.get("Eligibility", "")
        if elig not in (consts.NODE_SCHEDULING_ELIGIBLE,
                        consts.NODE_SCHEDULING_INELIGIBLE):
            raise HTTPError(400, f"invalid eligibility '{elig}'")
        index = self._server.node_update_eligibility(req.params["id"], elig)
        return {"NodeModifyIndex": index}

    def node_evaluate(self, req: Request):
        res = endpoints.node_evaluate(self._server, req.params["id"])
        return {"EvalIDs": res["eval_ids"], "EvalCreateIndex": res["index"]}

    def node_purge(self, req: Request):
        self._acl(req, "allow_node_write")
        res = endpoints.node_deregister(self._server, req.params["id"])
        return {"EvalIDs": res["eval_ids"], "NodeModifyIndex": res["index"]}

    # -- alloc / eval handlers -------------------------------------------

    def allocs_list(self, req: Request):
        snap = self._read(req, ["allocs"])
        prefix = req.q("prefix")
        with_res = req.flag("resources")
        out = [
            _alloc_stub(a, resources=with_res) for a in snap.allocs_iter()
            if a.namespace == req.namespace and a.id.startswith(prefix)
        ]
        return sorted(out, key=lambda a: a["ID"])

    def alloc_get(self, req: Request):
        snap = self._read(req, ["allocs"])
        alloc = snap.alloc_by_id(req.params["id"])
        if alloc is None:
            raise HTTPError(404, "alloc not found")
        return alloc

    def alloc_stop(self, req: Request):
        res = endpoints.alloc_stop(self._server, req.params["id"])
        return {"EvalID": res["eval_id"], "Index": res["index"]}

    def evals_list(self, req: Request):
        snap = self._read(req, ["evals"])
        prefix = req.q("prefix")
        return sorted(
            (e for e in snap.evals_iter()
             if e.namespace == req.namespace and e.id.startswith(prefix)),
            key=lambda e: e.id,
        )

    def eval_get(self, req: Request):
        snap = self._read(req, ["evals"])
        ev = snap.eval_by_id(req.params["id"])
        if ev is None:
            raise HTTPError(404, "eval not found")
        return ev

    def eval_allocs(self, req: Request):
        snap = self._read(req, ["allocs"])
        return [_alloc_stub(a) for a in snap.allocs_by_eval(req.params["id"])]

    # -- deployment handlers ---------------------------------------------

    def deployments_list(self, req: Request):
        snap = self._read(req, ["deployment"])
        return sorted(
            (d for d in snap.deployments_iter() if d.namespace == req.namespace),
            key=lambda d: d.id,
        )

    def deployment_get(self, req: Request):
        snap = self._read(req, ["deployment"])
        d = snap.deployment_by_id(req.params["id"])
        if d is None:
            raise HTTPError(404, "deployment not found")
        return d

    def deployment_allocs(self, req: Request):
        snap = self._read(req)
        return [
            _alloc_stub(a) for a in snap.allocs_iter()
            if a.deployment_id == req.params["id"]
        ]

    def deployment_fail(self, req: Request):
        index = self._server.deployments_watcher.fail_deployment(req.params["id"])
        return {"DeploymentModifyIndex": index}

    def deployment_pause(self, req: Request):
        body = req.body or {}
        index = self._server.deployments_watcher.pause_deployment(
            req.params["id"], bool(body.get("Pause", False))
        )
        return {"DeploymentModifyIndex": index}

    def deployment_promote(self, req: Request):
        body = req.body or {}
        index = self._server.deployments_watcher.promote_deployment(
            req.params["id"], body.get("Groups"), bool(body.get("All", True)),
        )
        return {"DeploymentModifyIndex": index}

    # -- status / agent / operator ---------------------------------------

    def status_leader(self, req: Request):
        s = self._server
        if s.raft is not None:
            return s.raft.leader_id or ""
        return s.config.name

    def status_peers(self, req: Request):
        s = self._server
        if s.raft is not None:
            return list(s.raft.peers)
        return [s.config.name]

    def agent_self(self, req: Request):
        a = self.agent
        stats = {}
        if a.server is not None:
            stats["nomad"] = a.server.stats()
        if a.client is not None:
            stats["client"] = a.client.stats()
        return {
            "Config": {
                "Region": a.config.region,
                "Datacenter": a.config.datacenter,
                "Name": a.config.name,
                "Server": a.server is not None,
                "Client": a.client is not None,
                "Version": {"Version": "0.1.0"},
            },
            "Stats": stats,
            "Member": {"Name": a.config.name, "Addr": self.addr},
        }

    def agent_health(self, req: Request):
        ok = {"ok": True, "message": "ok"}
        return {
            "server": ok if self.agent.server is not None else None,
            "client": ok if self.agent.client is not None else None,
        }

    def agent_join(self, req: Request):
        """PUT /v1/agent/join?address=<http addr>&join_region=<name>:
        federate with another region (serf WAN join analog). agent:write
        gated -- an open join would let anyone redirect token-bearing
        forwarded requests to their own endpoint."""
        self._acl(req, "allow_agent_write")
        addr = req.q("address")
        region = req.q("join_region")
        if not addr or not region:
            raise HTTPError(400, "address and join_region are required")
        if not addr.startswith(("http://", "https://")):
            raise HTTPError(400, f"address must be an http(s) URL: {addr!r}")
        if region == self.agent.config.region:
            raise HTTPError(400, f"cannot join own region {region!r}")
        self._server.join_region(region, addr)
        return {"num_joined": 1}

    # -- web UI ----------------------------------------------------------

    _UI_HTML: Optional[bytes] = None

    def ui_redirect(self, req: Request):
        h = req.handler
        h.send_response(307)
        h.send_header("Location", "/ui/")
        h.send_header("Content-Length", "0")
        h.end_headers()
        return StreamedResponse

    def _serve_static(self, req: Request, cache_attr: str, relpath: str,
                      content_type: str):
        """Lazily-cached static asset from the ui/ directory."""
        cls = type(self)
        body = getattr(cls, cache_attr, None)
        if body is None:
            path = os.path.join(os.path.dirname(__file__), "..", "ui",
                                relpath)
            with open(path, "rb") as f:
                body = f.read()
            setattr(cls, cache_attr, body)
        h = req.handler
        h.send_response(200)
        h.send_header("Content-Type", content_type)
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)
        return StreamedResponse

    def ui_index(self, req: Request):
        """Serve the SPA shell; every /ui/* path gets the same document
        (hash routing client-side)."""
        return self._serve_static(req, "_UI_HTML", "index.html",
                                  "text/html; charset=utf-8")

    _UI_JS = None

    def ui_app_js(self, req: Request):
        """The SPA's application module (extracted from the document so
        tests and tooling can read it standalone)."""
        return self._serve_static(
            req, "_UI_JS", "app.js",
            "application/javascript; charset=utf-8")

    @staticmethod
    def _write_chunk(h, payload: bytes) -> None:
        h.wfile.write(f"{len(payload):x}\r\n".encode())
        h.wfile.write(payload + b"\r\n")
        h.wfile.flush()

    @staticmethod
    def _end_chunks(h) -> None:
        """Best-effort terminal chunk so clients see a clean EOF even
        after a mid-stream error."""
        try:
            h.wfile.write(b"0\r\n\r\n")
            h.wfile.flush()
        except OSError:
            pass

    @classmethod
    def _begin_chunked(cls, h, content_type: str = "application/json"):
        """Start a chunked response; returns the frame writer."""
        h.send_response(200)
        h.send_header("Content-Type", content_type)
        h.send_header("Transfer-Encoding", "chunked")
        h.end_headers()
        return lambda payload: cls._write_chunk(h, payload)

    def agent_monitor(self, req: Request):
        """GET /v1/agent/monitor?log_level=X: stream agent logs as
        NDJSON frames (monitor.go / ndjson streaming)."""
        from nomad_tpu.utils.monitor import LogMonitor

        self._acl(req, "allow_agent_read")
        level = req.q("log_level", "info")
        mon = LogMonitor.install()
        h = req.handler
        deadline = time.time() + 600.0
        stop = threading.Event()
        try:
            write_chunk = self._begin_chunked(h)
            for line in mon.stream(level, stop):
                if time.time() > deadline:
                    stop.set()
                    break
                obj = {"Data": line} if line else {}
                write_chunk(json.dumps(obj).encode() + b"\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            stop.set()
            self._end_chunks(h)
        return StreamedResponse

    def pprof_goroutine(self, req: Request):
        from nomad_tpu.utils.monitor import thread_dump

        self._acl(req, "allow_agent_read")
        return {"Profile": thread_dump()}

    def pprof_profile(self, req: Request):
        from nomad_tpu.utils.monitor import sample_profile

        self._acl(req, "allow_agent_read")
        seconds = min(float(req.q("seconds", "1") or 1), 30.0)
        return {"Profile": sample_profile(seconds)}

    def pprof_heap(self, req: Request):
        from nomad_tpu.utils.monitor import heap_summary

        self._acl(req, "allow_agent_read")
        return {"Profile": heap_summary()}

    def agent_members(self, req: Request):
        members = getattr(self.agent, "members", None)
        if members is not None:
            return {"ServerRegion": self.agent.config.region,
                    "Members": members()}
        return {"ServerRegion": self.agent.config.region,
                "Members": [{"Name": self.agent.config.name,
                             "Status": "alive", "Addr": self.addr}]}

    def agent_servers(self, req: Request):
        return [self.addr]

    def metrics(self, req: Request):
        from nomad_tpu.telemetry import exporter
        from nomad_tpu.utils import metrics as m

        if req.q("format") == "prometheus":
            # real text exposition (text/plain), not a JSON-quoted
            # string: Prometheus scrapers parse the raw body. The
            # event broker is per-server state — pass it so the
            # nomad_tpu_stream_* serving-plane gauges ride the scrape
            broker = self.agent.server.event_broker \
                if self.agent.server is not None else None
            self._send_text(req.handler,
                            exporter.prometheus_text(
                                m.global_registry, event_broker=broker))
            return StreamedResponse
        return m.global_registry.summary()

    def operator_traces(self, req: Request):
        """Operator trace dump (gated like the event stream: the token
        must hold a real capability — operator:read — or the request
        is rejected outright)."""
        from nomad_tpu.telemetry import exporter

        self._acl(req, "allow_operator_read")
        try:
            limit = int(req.q("limit", "2000") or 2000)
        except ValueError:
            limit = 2000
        # ?trace_id= narrows the dump to one eval's span tree
        # (Tracer.spans already filters; this is the HTTP plumbing)
        return exporter.traces_json(limit=limit,
                                    trace_id=req.q("trace_id", ""))

    def operator_slow_evals(self, req: Request):
        """Slow-eval flight recorder dump: complete span trees of the
        evals that crossed the adaptive e2e-p99 threshold, plus the
        streaming latency histogram summaries. Same ACL as the trace
        dump (operator:read)."""
        from nomad_tpu.telemetry import exporter

        self._acl(req, "allow_operator_read")
        try:
            limit = int(req.q("limit", "0") or 0)
        except ValueError:
            limit = 0
        return exporter.slow_evals_json(limit=limit)

    def operator_slow_raft(self, req: Request):
        """Consensus flight recorder dump (ISSUE 15): slow raft
        appends / WAL fsync batches / elections past their adaptive
        thresholds — the slow-evals recorder's sibling. Same ACL
        (operator:read)."""
        from nomad_tpu.telemetry import exporter

        self._acl(req, "allow_operator_read")
        try:
            limit = int(req.q("limit", "0") or 0)
        except ValueError:
            limit = 0
        return exporter.slow_raft_json(limit=limit)

    def operator_cluster_health(self, req: Request):
        """Autopilot-style consensus health (ISSUE 15): this server's
        raft identity/term/state, per-peer match/lag/last-contact
        (leader-side), WAL occupancy + durability counters, consensus
        latency distributions, transition counters, and the fault
        plane's arm state. ACL: operator:read."""
        from nomad_tpu.telemetry import exporter

        self._acl(req, "allow_operator_read")
        return exporter.cluster_health_json(self._server)

    def operator_stream_health(self, req: Request):
        """Serving-plane health in one pull (ISSUE 11): event-ring
        publish/deliver/lost counters + subscriber lag, blocking-query
        wakeup accounting, heartbeat fan-in coalescing, and the
        delivery-lag histogram summary. Same ACL as the trace dump
        (operator:read)."""
        from nomad_tpu.telemetry import exporter

        self._acl(req, "allow_operator_read")
        return exporter.stream_health_json(self._server.event_broker)

    def operator_traces_put(self, req: Request):
        """Toggle tracing at runtime: {"Enable": true|false}, optional
        {"Reset": true} to clear collected spans first."""
        from nomad_tpu import telemetry

        self._acl(req, "allow_operator_write")
        body = req.body if isinstance(req.body, dict) else {}
        if body.get("Reset"):
            telemetry.reset()
        if "Enable" in body:
            if body["Enable"]:
                telemetry.enable()
            else:
                telemetry.disable()
        return {"Enabled": telemetry.enabled()}

    def sched_config_get(self, req: Request):
        cfg = self._server.state.scheduler_config
        return {
            "SchedulerConfig": {
                "SchedulerAlgorithm": cfg.scheduler_algorithm,
                "MemoryOversubscriptionEnabled": cfg.memory_oversubscription_enabled,
                "PauseEvalBroker": cfg.pause_eval_broker,
                "PreemptionConfig": {
                    "SystemSchedulerEnabled": cfg.preemption_system_enabled,
                    "SysBatchSchedulerEnabled": cfg.preemption_system_enabled,
                    "BatchSchedulerEnabled": cfg.preemption_batch_enabled,
                    "ServiceSchedulerEnabled": cfg.preemption_service_enabled,
                },
            }
        }

    def sched_config_put(self, req: Request):
        from nomad_tpu.server import fsm as fsm_msgs
        from nomad_tpu.state.store import SchedulerConfiguration

        body = req.body or {}
        cfg = SchedulerConfiguration()
        cfg.scheduler_algorithm = body.get(
            "SchedulerAlgorithm", consts.SCHEDULER_ALGORITHM_BINPACK
        )
        cfg.memory_oversubscription_enabled = bool(
            body.get("MemoryOversubscriptionEnabled", False)
        )
        cfg.pause_eval_broker = bool(body.get("PauseEvalBroker", False))
        pre = body.get("PreemptionConfig") or {}
        cfg.preemption_system_enabled = bool(pre.get("SystemSchedulerEnabled", True))
        cfg.preemption_batch_enabled = bool(pre.get("BatchSchedulerEnabled", False))
        cfg.preemption_service_enabled = bool(pre.get("ServiceSchedulerEnabled", False))
        index = self._server.raft_apply(fsm_msgs.SCHEDULER_CONFIG, {"config": cfg})
        return {"Updated": True, "Index": index}

    def regions_list(self, req: Request):
        """region_endpoint.go List."""
        return self._server.known_regions()

    def raft_config(self, req: Request):
        """operator_endpoint.go RaftGetConfiguration: ID/Node/Address/
        Leader/Voter per server — THIS server included (raft.peers
        excludes self). The UI and `operator raft list-peers` both
        render Address; the contract walk caught it missing."""
        self._acl(req, "allow_operator_read")
        s = self._server
        if s.raft is None:
            return {"Servers": [{"ID": s.config.name, "Node": s.config.name,
                                 "Address": s.config.name,
                                 "Leader": True, "Voter": True}], "Index": 0}
        leader = s.raft.leader_addr()
        rows = [{"ID": rid, "Node": rid, "Address": rid,
                 "Leader": rid == leader, "Voter": True}
                for rid in [s.raft.id, *s.raft.peers]]
        return {"Servers": rows, "Index": s.raft.commit_index}

    def autopilot_config_get(self, req: Request):
        self._acl(req, "allow_operator_read")
        cfg = self._server.state.autopilot_config
        return {
            "CleanupDeadServers": cfg.get("cleanup_dead_servers", True),
            "LastContactThreshold":
                f"{cfg.get('last_contact_threshold_s', 10.0)}s",
            "ServerStabilizationTime":
                f"{cfg.get('server_stabilization_time_s', 10.0)}s",
        }

    def autopilot_config_put(self, req: Request):
        from nomad_tpu.server import fsm as fsm_msgs

        self._acl(req, "allow_operator_write")
        body = req.body or {}

        def dur(key, default):
            raw = body.get(key)
            if raw is None:
                return default
            parsed = parse_duration(raw)
            if parsed is None:
                raise HTTPError(400, f"invalid duration for {key}: {raw!r}")
            return parsed

        cfg = {
            "cleanup_dead_servers": bool(body.get("CleanupDeadServers", True)),
            "last_contact_threshold_s": dur("LastContactThreshold", 10.0),
            "server_stabilization_time_s": dur("ServerStabilizationTime", 10.0),
        }
        index = self._server.raft_apply(
            fsm_msgs.AUTOPILOT_CONFIG, {"config": cfg}
        )
        return {"Updated": True, "Index": index}

    def autopilot_health(self, req: Request):
        self._acl(req, "allow_operator_read")
        return self._server.autopilot.health()

    def snapshot_save(self, req: Request):
        import base64

        from nomad_tpu.utils.snapshot import archive_snapshot

        data = archive_snapshot(self._server)
        return {"Snapshot": base64.b64encode(data).decode()}

    def snapshot_restore(self, req: Request):
        import base64

        from nomad_tpu.utils.snapshot import restore_snapshot

        body = req.body or {}
        if "Snapshot" not in body:
            raise HTTPError(400, "Snapshot is required")
        restore_snapshot(self._server, base64.b64decode(body["Snapshot"]))
        return {"Restored": True}

    def system_gc(self, req: Request):
        self._server.force_gc()
        return {}

    def system_reconcile(self, req: Request):
        return {}

    # -- search ----------------------------------------------------------

    def search(self, req: Request):
        from nomad_tpu.server.search import prefix_search

        body = req.body or {}
        return prefix_search(
            self._server.state.snapshot(),
            body.get("Prefix", ""), body.get("Context", "all"),
            namespace=req.namespace,
        )

    def search_fuzzy(self, req: Request):
        from nomad_tpu.server.search import fuzzy_search

        body = req.body or {}
        return fuzzy_search(
            self._server.state.snapshot(),
            body.get("Text", ""), body.get("Context", "all"),
            namespace=req.namespace,
        )

    # -- namespaces / scaling --------------------------------------------

    def namespaces_list(self, req: Request):
        return sorted(self._server.state.namespaces(), key=lambda n: n.name)

    def namespace_get(self, req: Request):
        ns = self._server.state.namespace_by_name(req.params["name"])
        if ns is None:
            raise HTTPError(404, "namespace not found")
        return ns

    def namespace_upsert(self, req: Request):
        from nomad_tpu.server import fsm as fsm_msgs
        from nomad_tpu.structs.namespace import Namespace

        body = req.body or {}
        name = req.params.get("name") or body.get("Name", "")
        if not name:
            raise HTTPError(400, "namespace name required")
        ns = Namespace(name=name, description=body.get("Description", ""),
                       quota=body.get("Quota", ""))
        index = self._server.raft_apply(
            fsm_msgs.NAMESPACE_UPSERT, {"namespaces": [ns]}
        )
        return {"Index": index}

    def namespace_delete(self, req: Request):
        from nomad_tpu.server import fsm as fsm_msgs

        index = self._server.raft_apply(
            fsm_msgs.NAMESPACE_DELETE, {"names": [req.params["name"]]}
        )
        return {"Index": index}

    def scaling_policies(self, req: Request):
        return self._server.state.scaling_policies()

    def scaling_policy(self, req: Request):
        p = self._server.state.scaling_policy_by_id(req.params["id"])
        if p is None:
            raise HTTPError(404, "scaling policy not found")
        return p

    # -- CSI volumes + plugins (csi_endpoint.go) -------------------------

    def volumes_list(self, req: Request):
        self._acl(req, "allow_ns_op", req.namespace, "csi-list-volume")
        self._read(req, ["csi_volumes"])
        ns = req.namespace
        plugin_id = req.q("plugin_id")
        vols = [
            v for v in self._server.state.csi_volumes()
            if (ns in ("*", v.namespace))
            and (not plugin_id or v.plugin_id == plugin_id)
        ]
        return [v.stub() for v in sorted(vols, key=lambda v: v.id)]

    def volume_get(self, req: Request):
        self._acl(req, "allow_ns_op", req.namespace, "csi-read-volume")
        self._read(req, ["csi_volumes"])
        vol = self._server.state.csi_volume_by_id(
            req.namespace, req.params["id"]
        )
        if vol is None:
            raise HTTPError(404, "volume not found")
        # secrets never leave the server (csi_endpoint.go Get strips
        # Secrets before responding)
        redacted = vol.copy()
        redacted.secrets = {k: "[REDACTED]" for k in vol.secrets}
        return redacted

    def volume_register(self, req: Request):
        self._acl(req, "allow_ns_op", req.namespace, "csi-write-volume")
        vols = self._decode_volumes(req)
        try:
            index = self._server.csi_volume_register(vols)
        except ValueError as e:
            raise HTTPError(400, str(e))
        return {"Index": index}

    def volume_create(self, req: Request):
        self._acl(req, "allow_ns_op", req.namespace, "csi-write-volume")
        vols = self._decode_volumes(req)
        try:
            created = self._server.csi_volume_create(vols)
        except ValueError as e:
            raise HTTPError(400, str(e))
        return {"Volumes": created}

    def _decode_volumes(self, req: Request):
        from nomad_tpu.api.codec import decode
        from nomad_tpu.structs.csi import CSIVolume

        body = req.body or {}
        raw = body.get("Volumes") or ([body.get("Volume")]
                                      if body.get("Volume") else [])
        if not raw:
            raise HTTPError(400, "no volumes provided")
        vols = []
        for r in raw:
            v = decode(r, CSIVolume)
            if not v.namespace or v.namespace == "default":
                v.namespace = req.namespace if req.namespace != "*" \
                    else "default"
            vols.append(v)
        return vols

    def volume_deregister(self, req: Request):
        self._acl(req, "allow_ns_op", req.namespace, "csi-write-volume")
        try:
            index = self._server.csi_volume_deregister(
                req.namespace, req.params["id"], force=req.flag("force")
            )
        except ValueError as e:
            raise HTTPError(400 if "in use" in str(e) else 404, str(e))
        return {"Index": index}

    def volume_delete(self, req: Request):
        self._acl(req, "allow_ns_op", req.namespace, "csi-write-volume")
        try:
            index = self._server.csi_volume_delete(
                req.namespace, req.params["id"]
            )
        except ValueError as e:
            raise HTTPError(400 if "in use" in str(e) else 404, str(e))
        return {"Index": index}

    def volume_detach(self, req: Request):
        """Force-release one alloc's (or node's) claims
        (csi_endpoint.go Unpublish)."""
        self._acl(req, "allow_ns_op", req.namespace, "csi-write-volume")
        vol = self._server.state.csi_volume_by_id(
            req.namespace, req.params["id"]
        )
        if vol is None:
            raise HTTPError(404, "volume not found")
        node_id = req.q("node")
        alloc_id = req.q("alloc")
        index = self._server.state.latest_index()
        for claims in (vol.read_claims, vol.write_claims):
            for aid, claim in list(claims.items()):
                if alloc_id and aid != alloc_id:
                    continue
                if node_id and claim.node_id != node_id:
                    continue
                index = self._server.csi_volume_claim(
                    vol.namespace, vol.id, claim.release_copy()
                )
        return {"Index": index}

    def plugins_list(self, req: Request):
        self._acl(req, "allow_plugin_read")
        self._read(req, ["nodes"])
        plugins = self._server.csi_plugins()
        return [p.stub() for p in sorted(plugins.values(), key=lambda p: p.id)]

    def plugin_get(self, req: Request):
        self._acl(req, "allow_plugin_read")
        self._read(req, ["nodes"])
        p = self._server.csi_plugins().get(req.params["id"])
        if p is None:
            raise HTTPError(404, "plugin not found")
        out = p.stub()
        out["Controllers"] = p.controllers
        out["Nodes"] = p.nodes
        return out

    # -- native service discovery (service_registration_endpoint.go) -----

    def services_list(self, req: Request):
        """Grouped stubs: [{Namespace, Services: [{ServiceName, Tags}]}]
        (service_registration_endpoint.go List)."""
        self._acl(req, "allow_ns_op", req.namespace, "read-job")
        self._read(req, ["services"])
        regs = self._server.state.service_registrations(req.namespace)
        by_ns: Dict[str, Dict[str, set]] = {}
        for r in regs:
            tags = by_ns.setdefault(r.namespace, {}).setdefault(
                r.service_name, set()
            )
            tags.update(r.tags)
        return [
            {
                "Namespace": ns,
                "Services": [
                    {"ServiceName": name, "Tags": sorted(tags)}
                    for name, tags in sorted(services.items())
                ],
            }
            for ns, services in sorted(by_ns.items())
        ]

    def service_get(self, req: Request):
        self._acl(req, "allow_ns_op", req.namespace, "read-job")
        self._read(req, ["services"])
        regs = self._server.state.service_registrations_by_name(
            req.namespace, req.params["name"]
        )
        return [r.stub() for r in sorted(regs, key=lambda r: r.id)]

    def service_delete(self, req: Request):
        reg = self._server.state.service_registration_by_id(req.params["id"])
        if reg is None or reg.service_name != req.params["name"] \
                or reg.namespace != req.namespace:
            raise HTTPError(404, "service registration not found")
        self._acl(req, "allow_ns_op", reg.namespace, "submit-job")
        try:
            index = self._server.service_deregister(reg.id)
        except ValueError as e:
            raise HTTPError(404, str(e))
        return {"Index": index}

    # -- event stream (stream/ndjson.go) ---------------------------------

    def event_stream(self, req: Request):
        broker = self._server.event_broker
        # subscriptions are inherently local reads: each server's FSM
        # feeds its own ring, so a follower serves its own events and
        # resumes by raft index across failovers (ISSUE 12/20). Route
        # through the read plane in stale mode so the subscriber gets
        # the same staleness attribution + max_stale rejection as any
        # other query — a follower over the caller's bound refuses the
        # stream loudly instead of silently lagging it.
        _, max_stale = req.consistency_params()
        self._server.readplane.resolve("stale", max_stale)
        resolver = getattr(self.agent, "acl_resolver", None)

        # subscribe-time ACL (event_broker.go:55 SubscribeWithACLCheck):
        # the token must resolve NOW, and is re-resolved every poll so a
        # revocation drops the stream (handleACLUpdates analog) instead
        # of a dead token riding a live subscription forever
        def _resolve():
            if resolver is None:
                return None
            try:
                acl = resolver.resolve(req.token)
            except PermissionError:
                raise HTTPError(403, "Permission denied")
            # SubscribeWithACLCheck rejects tokens with no relevant
            # read capability at all (incl. anonymous) outright rather
            # than letting them hold a 600s heartbeat-only stream
            if not (acl.is_management() or acl.allow_node_read()
                    or acl.allow_any_ns_op("read-job")):
                raise HTTPError(403, "Permission denied")
            return acl

        acl = _resolve()

        def _visible(ev) -> bool:
            """Namespace/topic capability filter (aclAllowsSubscription):
            Node/ACL topics need node:read / management; namespaced
            topics need read-job capability on the event's namespace.
            LostEvents markers always pass — a slow consumer must learn
            it lost events (the marker carries a count and a resume
            index, never another namespace's payload)."""
            if ev.topic == "LostEvents":
                return True
            if acl is None or acl.is_management():
                return True
            if ev.topic in ("ACLToken", "ACLPolicy"):
                return False
            if ev.topic == "Node":
                return acl.allow_node_read()
            return acl.allow_ns_op(ev.namespace or "default", "read-job")

        topics: Dict[str, List[str]] = {}
        for t in req.query.get("topic", []):
            if ":" in t:
                topic, key = t.split(":", 1)
            else:
                topic, key = t, "*"
            topics.setdefault(topic, []).append(key)
        index, _ = req.wait_params()
        sub = broker.subscribe(topics or {"*": ["*"]}, from_index=index)
        h = req.handler
        try:
            write_chunk = self._begin_chunked(h)
            deadline = time.time() + 600
            last_write = time.time()
            while time.time() < deadline:
                events = sub.next_events(timeout=5.0)
                try:
                    acl = _resolve()
                except HTTPError:
                    break               # token revoked: drop the stream
                events = [e for e in events if _visible(e)]
                if events:
                    batch = {
                        "Index": events[-1].index,
                        "Events": [encode(e) for e in events],
                    }
                    payload = (json.dumps(batch) + "\n").encode()
                    write_chunk(payload)
                    broker.note_delivered_bytes(len(payload))
                    last_write = time.time()
                elif time.time() - last_write >= 5.0:
                    # keepalive on ELAPSED TIME, not on queue state:
                    # an instant {} per filtered batch would leak
                    # hidden-namespace activity timing, and pure
                    # silence would trip client/proxy idle timeouts.
                    # A reconnecting client resumes with ?index=<last
                    # Index it saw>: the ring replays from there, or
                    # delivers a LostEvents marker if that span was
                    # trimmed (stream/ndjson.go keepalive + resume)
                    write_chunk(b"{}\n")
                    last_write = time.time()
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            sub.close()
            self._end_chunks(req.handler)
        return StreamedResponse

    # -- ACL handlers ----------------------------------------------------

    @property
    def _acl_store(self):
        resolver = getattr(self.agent, "acl_resolver", None)
        if resolver is None:
            raise HTTPError(400, "ACL support disabled")
        return resolver

    def acl_bootstrap(self, req: Request):
        return self._acl_store.bootstrap()

    def acl_policies_list(self, req: Request):
        self._acl(req, "is_management")
        return [
            {"Name": p.name, "Description": p.description}
            for p in self._server.state.acl_policies()
        ]

    def acl_policy_get(self, req: Request):
        self._acl(req, "is_management")
        p = self._server.state.acl_policy_by_name(req.params["name"])
        if p is None:
            raise HTTPError(404, "policy not found")
        return p

    def acl_policy_put(self, req: Request):
        from nomad_tpu.acl.policy import ACLPolicy
        from nomad_tpu.server import fsm as fsm_msgs

        self._acl(req, "is_management")
        body = req.body or {}
        p = ACLPolicy(
            name=req.params["name"],
            description=body.get("Description", ""),
            rules=body.get("Rules", ""),
        )
        p.validate()
        index = self._server.raft_apply(
            fsm_msgs.ACL_POLICY_UPSERT, {"policies": [p]}
        )
        return {"Index": index}

    def acl_policy_delete(self, req: Request):
        from nomad_tpu.server import fsm as fsm_msgs

        self._acl(req, "is_management")
        index = self._server.raft_apply(
            fsm_msgs.ACL_POLICY_DELETE, {"names": [req.params["name"]]}
        )
        return {"Index": index}

    def acl_tokens_list(self, req: Request):
        self._acl(req, "is_management")
        return [
            {"AccessorID": t.accessor_id, "Name": t.name, "Type": t.type,
             "Policies": t.policies, "Global": t.global_}
            for t in self._server.state.acl_tokens()
        ]

    def acl_token_self(self, req: Request):
        t = self._server.state.acl_token_by_secret(req.token)
        if t is None:
            raise HTTPError(403, "token not found")
        return t

    def acl_token_get(self, req: Request):
        self._acl(req, "is_management")
        t = self._server.state.acl_token_by_accessor(req.params["id"])
        if t is None:
            raise HTTPError(404, "token not found")
        return t

    def acl_token_put(self, req: Request):
        from nomad_tpu.acl.policy import ACLToken
        from nomad_tpu.server import fsm as fsm_msgs

        self._acl(req, "is_management")
        body = req.body or {}
        t = ACLToken.create(
            name=body.get("Name", ""),
            type=body.get("Type", "client"),
            policies=body.get("Policies") or [],
            global_=bool(body.get("Global", False)),
        )
        if req.params.get("id"):
            existing = self._server.state.acl_token_by_accessor(req.params["id"])
            if existing is None:
                raise HTTPError(404, "token not found")
            t.accessor_id = existing.accessor_id
            t.secret_id = existing.secret_id
        index = self._server.raft_apply(
            fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [t]}
        )
        out = encode(t)
        out["Index"] = index
        return out

    def acl_ott_create(self, req: Request):
        """POST /v1/acl/token/onetime: mint a one-time token for the
        caller's ACL token (acl_endpoint.go UpsertOneTimeToken)."""
        t = self._server.state.acl_token_by_secret(req.token)
        if t is None:
            raise HTTPError(403, "token not found")
        ott = self._server.create_one_time_token(t.accessor_id)
        return {"OneTimeToken": {
            "OneTimeSecretID": ott["one_time_secret_id"],
            "AccessorID": ott["accessor_id"],
            "ExpiresAt": ott["expires_at"],
        }}

    def acl_ott_exchange(self, req: Request):
        body = req.body or {}
        secret = body.get("OneTimeSecretID", "")
        try:
            token = self._server.exchange_one_time_token(secret)
        except ValueError as e:
            raise HTTPError(403, str(e))
        return {"Token": token}

    def acl_token_delete(self, req: Request):
        from nomad_tpu.server import fsm as fsm_msgs

        self._acl(req, "is_management")
        index = self._server.raft_apply(
            fsm_msgs.ACL_TOKEN_DELETE, {"accessor_ids": [req.params["id"]]}
        )
        return {"Index": index}

    # -- client handlers -------------------------------------------------

    @property
    def _client(self):
        c = self.agent.client
        if c is None:
            raise HTTPError(400, "client is not enabled on this agent")
        return c

    def client_alloc_stats(self, req: Request):
        return self._runner(req, "read-job").stats()

    def client_fs_logs(self, req: Request):
        runner = self._runner(req, "read-logs")
        task = req.q("task")
        logtype = req.q("type", "stdout")
        offset = int(req.q("offset", "0") or 0)
        if req.flag("follow"):
            return self._stream_fs_logs(req, runner, task, logtype, offset)
        try:
            logs = runner.task_logs(
                task, logtype,
                offset=offset,
                limit=int(req.q("limit", "0") or 0),
            )
        except PermissionError as e:
            raise HTTPError(403, str(e))
        return {"Data": logs}

    def _stream_fs_logs(self, req: Request, runner, task: str,
                        logtype: str, offset: int):
        """?follow=true: raw chunked text that tails the rotation
        chain until the task is done (fs_endpoint.go Logs follow)."""
        # probe before committing the 200: bad task names / escaping
        # paths must 403 like the non-follow read does
        try:
            first = runner.task_logs_bytes(task, logtype, offset=offset)
        except PermissionError as e:
            raise HTTPError(403, str(e))
        h = req.handler
        deadline = time.time() + 600.0
        try:
            write_chunk = self._begin_chunked(
                h, content_type="text/plain; charset=utf-8")
            pos = offset
            data = first
            idle_after_done = 0
            while time.time() < deadline:
                if data:
                    pos += len(data)
                    write_chunk(data)
                    idle_after_done = 0
                else:
                    if runner.is_done():
                        # grace passes catch the logmon drain on stop
                        idle_after_done += 1
                        if idle_after_done > 2:
                            break
                    time.sleep(0.25)
                data = runner.task_logs_bytes(task, logtype, offset=pos)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self._end_chunks(h)
        return StreamedResponse

    def client_fs_ls(self, req: Request):
        try:
            return self._runner(req, "read-fs").list_dir(req.q("path", "/"))
        except FileNotFoundError:
            raise HTTPError(404, "path not found")
        except PermissionError as e:
            raise HTTPError(403, str(e))

    def client_stats(self, req: Request):
        return self._client.stats()

    def _runner(self, req: Request, capability: str = ""):
        """Resolve the local runner; ACL-check against the alloc's REAL
        namespace (the query param is caller-controlled)."""
        runner = self._client.alloc_runner(req.params["id"])
        if runner is None:
            raise HTTPError(404, "unknown allocation")
        if capability:
            self._acl(req, "allow_ns_op", runner.alloc.namespace, capability)
        return runner

    def client_alloc_restart(self, req: Request):
        body = req.body or {}
        try:
            self._runner(req, "alloc-lifecycle").restart_tasks(
                body.get("TaskName", "")
            )
        except KeyError as e:
            raise HTTPError(404, str(e))
        return {}

    def client_alloc_signal(self, req: Request):
        body = req.body or {}
        try:
            self._runner(req, "alloc-lifecycle").signal_tasks(
                body.get("Signal", "SIGTERM"), body.get("TaskName", "")
            )
        except KeyError as e:
            raise HTTPError(404, str(e))
        return {}

    def client_alloc_exec(self, req: Request):
        """Exec in a task. Two modes (reference api/allocations_exec.go):

        - websocket upgrade: interactive bidirectional stream; JSON
          frames {"stdin": {"data": b64}} / {"stdin": {"close": true}}
          / {"tty_size": {"height", "width"}} inbound, {"stdout"/
          "stderr": {"data": b64}} / {"exited", "result"} outbound.
        - plain POST: one-shot captured output (kept for simple
          clients; the reference CLI always streams).
        """
        handler = req.handler
        if handler is not None and \
                handler.headers.get("Upgrade", "").lower() == "websocket":
            return self._exec_websocket(req)
        if req.method == "GET":
            raise HTTPError(400, "interactive exec requires a websocket "
                                 "upgrade; use POST for one-shot exec")
        body = req.body or {}
        task = body.get("Task", "")
        cmd = body.get("Cmd") or []
        if not task or not cmd:
            raise HTTPError(400, "Task and Cmd are required")
        try:
            out = self._runner(req, "alloc-exec").exec_in_task(task, cmd)
        except KeyError as e:
            raise HTTPError(404, str(e))
        except NotImplementedError as e:
            raise HTTPError(400, str(e))
        for k in ("stdout", "stderr"):
            if isinstance(out.get(k), bytes):
                out[k] = out[k].decode(errors="replace")
        return out

    def _exec_websocket(self, req: Request):
        """The interactive leg: ws frames <-> driver ExecStream."""
        import base64

        from nomad_tpu.utils import ws as wslib

        handler = req.handler
        task = req.q("task", "")
        tty = req.q("tty", "") in ("true", "1")
        try:
            cmd = json.loads(req.q("command", "[]"))
        except json.JSONDecodeError:
            cmd = []
        if not task or not cmd:
            raise HTTPError(400, "task and command are required")
        runner = self._runner(req, "alloc-exec")
        try:
            stream = runner.exec_stream_in_task(task, cmd, tty=tty)
        except KeyError as e:
            raise HTTPError(404, str(e))
        except NotImplementedError as e:
            raise HTTPError(400, str(e))

        if not wslib.server_handshake(handler):
            stream.terminate()
            return StreamedResponse
        handler.close_connection = True

        stop = threading.Event()
        # both threads write frames on the same buffered wfile; a lock
        # keeps a PONG from landing inside a half-flushed TEXT frame
        wlock = threading.Lock()

        def send_frame(op, payload: bytes) -> None:
            with wlock:
                wslib.write_frame(handler.wfile, op, payload)

        def pump_in() -> None:
            """ws -> process stdin / resize."""
            try:
                while not stop.is_set():
                    op, payload = wslib.read_frame(handler.rfile)
                    if op == wslib.OP_CLOSE:
                        break
                    if op == wslib.OP_PING:
                        send_frame(wslib.OP_PONG, payload)
                        continue
                    if op not in (wslib.OP_TEXT, wslib.OP_BINARY):
                        continue
                    try:
                        frame = json.loads(payload)
                    except json.JSONDecodeError:
                        continue
                    stdin = frame.get("stdin") or {}
                    if stdin.get("data"):
                        stream.write_stdin(base64.b64decode(stdin["data"]))
                    if stdin.get("close"):
                        stream.close_stdin()
                    size = frame.get("tty_size") or {}
                    if size:
                        stream.resize(int(size.get("height", 24)),
                                      int(size.get("width", 80)))
            except (ConnectionError, OSError, ValueError):
                pass
            finally:
                stream.terminate()

        t = threading.Thread(target=pump_in, daemon=True, name="exec-ws-in")
        t.start()
        try:
            exit_code = None
            while True:
                # after the process exits, keep draining briefly: the
                # output pumps race the waiter, and trailing pty bytes
                # must not be lost behind the exited frame
                item = stream.read_output(
                    timeout=0.5 if exit_code is None else 0.2)
                if item is None:
                    if exit_code is not None:
                        break
                    continue
                name, data = item
                if name == "exited":
                    exit_code = data
                    continue
                if data:
                    send_frame(wslib.OP_TEXT, json.dumps({
                        name: {"data": base64.b64encode(data).decode()},
                    }).encode())
            send_frame(wslib.OP_TEXT, json.dumps({
                "exited": True,
                "result": {"exit_code": exit_code},
            }).encode())
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            stop.set()
            stream.terminate()
            try:
                send_frame(wslib.OP_CLOSE, b"")
            except OSError:
                pass
        return StreamedResponse

    def client_fs_stat(self, req: Request):
        try:
            return self._runner(req, "read-fs").stat_file(req.q("path", "/"))
        except FileNotFoundError:
            raise HTTPError(404, "file not found")
        except PermissionError as e:
            raise HTTPError(403, str(e))

    def client_fs_cat(self, req: Request):
        try:
            data = self._runner(req, "read-fs").cat_file(req.q("path", "/"))
        except FileNotFoundError:
            raise HTTPError(404, "file not found")
        except IsADirectoryError:
            raise HTTPError(400, "path is a directory")
        except PermissionError as e:
            raise HTTPError(403, str(e))
        return {"Data": data.decode(errors="replace")}

    def client_fs_readat(self, req: Request):
        try:
            offset = int(req.q("offset", "0") or 0)
            limit = int(req.q("limit", "0") or 0)
        except ValueError:
            raise HTTPError(400, "offset and limit must be integers")
        if offset < 0 or limit < 0:
            raise HTTPError(400, "offset and limit must be >= 0")
        try:
            data = self._runner(req, "read-fs").cat_file(
                req.q("path", "/"), offset=offset, limit=limit,
            )
        except FileNotFoundError:
            raise HTTPError(404, "file not found")
        except IsADirectoryError:
            raise HTTPError(400, "path is a directory")
        except PermissionError as e:
            raise HTTPError(403, str(e))
        return {"Data": data.decode(errors="replace"),
                "Offset": offset}


class StreamedResponse:
    """Sentinel: handler already wrote the response body."""


def _job_stub(j) -> Dict:
    return {
        "ID": j.id, "ParentID": j.parent_id, "Name": j.name or j.id,
        "Namespace": j.namespace, "Type": j.type, "Priority": j.priority,
        "Status": j.status,
        "Stop": j.stop, "Version": j.version,
        "CreateIndex": j.create_index, "ModifyIndex": j.modify_index,
        "JobModifyIndex": j.job_modify_index,
    }


def _node_stub(n, resources: bool = False) -> Dict:
    out = {
        "ID": n.id, "Name": n.name, "Datacenter": n.datacenter,
        "NodeClass": n.node_class, "Status": n.status,
        "SchedulingEligibility": n.scheduling_eligibility,
        "Drain": n.drain_strategy is not None,
        "Address": getattr(n, "http_addr", ""),
        "NodePool": getattr(n, "node_pool", "default"),
    }
    if resources:
        # ?resources=true includes flattened capacity on the stub
        # (reference NodeListStub.NodeResources; the UI topology view
        # reads capacity from one list call instead of N detail calls)
        cr = n.node_resources.comparable()
        out["NodeResources"] = {
            "CPU": cr.cpu_shares, "MemoryMB": cr.memory_mb,
            "DiskMB": cr.disk_mb,
        }
    return out


def _alloc_stub(a, resources: bool = False) -> Dict:
    out = {
        "ID": a.id, "EvalID": a.eval_id, "Name": a.name,
        "Namespace": a.namespace, "NodeID": a.node_id, "NodeName": a.node_name,
        "JobID": a.job_id, "JobVersion": a.job_version,
        "TaskGroup": a.task_group,
        "DesiredStatus": a.desired_status, "ClientStatus": a.client_status,
        "DeploymentID": a.deployment_id,
        "CreateIndex": a.create_index, "ModifyIndex": a.modify_index,
        "CreateTime": a.create_time_ns, "ModifyTime": a.modify_time_ns,
        "FollowupEvalID": a.follow_up_eval_id,
    }
    if resources:
        # ?resources=true includes flattened allocated resources on the
        # stub (reference AllocationListStub.AllocatedResources; used by
        # the UI topology view)
        cr = a.comparable_resources()
        out["AllocatedResources"] = {
            "CPU": cr.cpu_shares, "MemoryMB": cr.memory_mb,
            "DiskMB": cr.disk_mb,
        }
    return out
