"""JSON wire codec for the /v1 API.

Reference: the Go structs marshal directly to JSON with their exported
field names (api/ package mirrors nomad/structs). Here a generic
dataclass walker produces the same shape: snake_case fields become
PascalCase with Nomad's acronym conventions (id -> ID, cpu -> CPU,
mb -> MB, ...), `_s`/`_ns` duration suffixes map to the reference's
nanosecond fields, and numpy scalars degrade to Python numbers.

Decoding is tolerant: unknown keys are ignored (the reference's
jsonpb/mapstructure behavior), missing keys keep dataclass defaults.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, Optional, Type, get_args, get_origin

# snake token -> wire token (reference exported-name conventions)
_ACRONYMS = {
    "id": "ID",
    "cpu": "CPU",
    "mb": "MB",
    "mhz": "MHz",
    "ip": "IP",
    "cidr": "CIDR",
    "ttl": "TTL",
    "acl": "ACL",
    "csi": "CSI",
    "dns": "DNS",
    "tg": "TG",
    "gc": "GC",
    "url": "URL",
    "hcl": "HCL",
}


def wire_name(snake: str) -> str:
    """cpu_shares -> CPUShares, job_id -> JobID, memory_mb -> MemoryMB."""
    parts = snake.split("_")
    # duration fields: foo_s / foo_ns keep the suffix as-is capitalized
    out = []
    for p in parts:
        if not p:
            continue
        out.append(_ACRONYMS.get(p, p.capitalize()))
    return "".join(out)


def _is_dataclass_type(t) -> bool:
    return dataclasses.is_dataclass(t) and isinstance(t, type)


def encode(obj: Any) -> Any:
    """Struct tree -> plain JSON-able value."""
    import numpy as np

    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            # never serialize back-references / cached companions
            if f.name.startswith("_"):
                continue
            v = getattr(obj, f.name)
            out[wire_name(f.name)] = encode(v)
        return out
    if isinstance(obj, dict):
        return {str(k): encode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [encode(v) for v in obj]
    # plain objects (e.g. __init__-style configs): walk __dict__
    if hasattr(obj, "__dict__"):
        return {
            wire_name(k): encode(v)
            for k, v in vars(obj).items()
            if not k.startswith("_")
        }
    return str(obj)


def _decode_value(value: Any, ftype) -> Any:
    if value is None:
        return None
    origin = get_origin(ftype)
    if origin is typing.Union:
        args = [a for a in get_args(ftype) if a is not type(None)]
        if len(args) == 1:
            return _decode_value(value, args[0])
        return value
    if _is_dataclass_type(ftype):
        return decode(value, ftype)
    if origin in (list, typing.List):
        (item_t,) = get_args(ftype) or (Any,)
        return [_decode_value(v, item_t) for v in value]
    if origin in (dict, typing.Dict):
        args = get_args(ftype)
        item_t = args[1] if len(args) == 2 else Any
        return {k: _decode_value(v, item_t) for k, v in value.items()}
    if ftype is float and isinstance(value, (int, float)):
        return float(value)
    if ftype is int and isinstance(value, (int, float)):
        return int(value)
    return value


def decode(data: Optional[Dict], cls: Type) -> Any:
    """Plain JSON dict -> dataclass instance (unknown keys ignored)."""
    if data is None:
        return None
    if not dataclasses.is_dataclass(cls):
        return data
    hints = typing.get_type_hints(cls)
    kwargs = {}
    by_wire = {wire_name(f.name): f for f in dataclasses.fields(cls)}
    for key, value in data.items():
        f = by_wire.get(key)
        if f is None:
            continue
        kwargs[f.name] = _decode_value(value, hints.get(f.name, Any))
    return cls(**kwargs)
