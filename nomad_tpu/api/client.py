"""Python SDK for the /v1 API (the api/ Go module analog).

Reference behavior: api/api.go:448 Client — per-endpoint typed
helpers, QueryOptions with blocking-query support (WaitIndex/WaitTime),
WriteOptions with namespace/token, event-stream decoding.

Usage::

    c = APIClient("http://127.0.0.1:4646")
    c.jobs.register(job_dict)
    for ev in c.events.stream(topics={"Job": ["*"]}):
        ...
"""

from __future__ import annotations

import copy
import json
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional


def _esc(segment: Any) -> str:
    """URL-escape a path segment. Dispatched job IDs contain '/'
    (``parent/dispatch-...``, structs.go DispatchedID), so any ID embedded
    in a route path must be quoted."""
    return urllib.parse.quote(str(segment), safe="")


class APIError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


@dataclass
class QueryOptions:
    """api.QueryOptions: blocking + filtering knobs."""

    namespace: str = ""
    region: str = ""
    wait_index: int = 0
    wait_time_s: float = 0.0
    prefix: str = ""
    auth_token: str = ""
    params: Dict[str, str] = field(default_factory=dict)


class APIClient:
    def __init__(self, address: str = "http://127.0.0.1:4646",
                 token: str = "", namespace: str = "default",
                 timeout: float = 305.0, region: str = "",
                 ca_cert: str = "", client_cert: str = "",
                 client_key: str = "") -> None:
        self.address = address.rstrip("/")
        self.token = token
        self.namespace = namespace
        self.region = region
        self.timeout = timeout
        # TLS (api.Client TLSConfig; env NOMAD_CACERT/NOMAD_CLIENT_CERT/
        # NOMAD_CLIENT_KEY in the CLI): a CA pins server verification,
        # a client cert/key pair enables mTLS
        self._ssl_context = None
        if bool(client_cert) != bool(client_key):
            raise ValueError(
                "client_cert and client_key must be provided together")
        if ca_cert or client_cert:
            from nomad_tpu.utils.tlsutil import client_context
            self._ssl_context = client_context(
                ca_cert, client_cert, client_key)
        self.jobs = Jobs(self)
        self.nodes = Nodes(self)
        self.allocations = Allocations(self)
        self.evaluations = Evaluations(self)
        self.deployments = Deployments(self)
        self.system = System(self)
        self.operator = Operator(self)
        self.agent = AgentAPI(self)
        self.search = Search(self)
        self.namespaces = Namespaces(self)
        self.acl = ACLAPI(self)
        self.events = Events(self)
        self.scaling = Scaling(self)
        self.csi_volumes = CSIVolumes(self)
        self.csi_plugins = CSIPlugins(self)
        self.services = Services(self)

    # -- transport -------------------------------------------------------

    def _url(self, path: str, q: Optional[QueryOptions] = None) -> str:
        params: Dict[str, str] = {}
        ns = (q.namespace if q and q.namespace else self.namespace)
        if ns:
            params["namespace"] = ns
        region = (q.region if q and q.region else self.region)
        if region:
            params["region"] = region
        if q is not None:
            if q.wait_index:
                params["index"] = str(q.wait_index)
            if q.wait_time_s:
                params["wait"] = f"{q.wait_time_s}s"
            if q.prefix:
                params["prefix"] = q.prefix
            params.update(q.params)
        qs = urllib.parse.urlencode(params)
        sep = "&" if "?" in path else "?"
        return f"{self.address}{path}" + (f"{sep}{qs}" if qs else "")

    def request(self, method: str, path: str, body: Any = None,
                q: Optional[QueryOptions] = None) -> Any:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self._url(path, q), data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        token = (q.auth_token if q and q.auth_token else self.token)
        if token:
            req.add_header("X-Nomad-Token", token)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ssl_context) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else None
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
                msg = payload.get("error", str(payload))
            except Exception:
                msg = str(e)
            raise APIError(e.code, msg) from None

    def stream(self, path: str, params: Optional[List[tuple]] = None,
               timeout: float = 60.0) -> Iterator[Any]:
        """Yield parsed NDJSON frames from a chunked streaming endpoint
        (/v1/event/stream, /v1/agent/monitor), skipping empty
        keepalive frames."""
        url = f"{self.address}{path}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(
            url,
            headers={"X-Nomad-Token": self.token} if self.token else {},
        )
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=self._ssl_context) as resp:
            for line in resp:
                line = line.strip()
                if not line or line == b"{}":
                    continue
                yield json.loads(line)

    def get(self, path: str, q: Optional[QueryOptions] = None) -> Any:
        return self.request("GET", path, None, q)

    def put(self, path: str, body: Any = None, q: Optional[QueryOptions] = None) -> Any:
        return self.request("PUT", path, body, q)

    def post(self, path: str, body: Any = None, q: Optional[QueryOptions] = None) -> Any:
        return self.request("POST", path, body, q)

    def delete(self, path: str, q: Optional[QueryOptions] = None) -> Any:
        return self.request("DELETE", path, None, q)


class _Endpoint:
    def __init__(self, client: APIClient) -> None:
        self.c = client


class Jobs(_Endpoint):
    def list(self, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get("/v1/jobs", q)

    def register(self, job: Dict, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.put("/v1/jobs", {"Job": job}, q)

    def info(self, job_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.get(f"/v1/job/{_esc(job_id)}", q)

    def deregister(self, job_id: str, purge: bool = False,
                   q: Optional[QueryOptions] = None) -> Dict:
        q = copy.deepcopy(q) if q is not None else QueryOptions()
        if purge:
            q.params["purge"] = "true"
        return self.c.delete(f"/v1/job/{_esc(job_id)}", q)

    def plan(self, job: Dict, diff: bool = False,
             q: Optional[QueryOptions] = None) -> Dict:
        return self.c.put(f"/v1/job/{_esc(job['ID'])}/plan",
                          {"Job": job, "Diff": diff}, q)

    def allocations(self, job_id: str, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get(f"/v1/job/{_esc(job_id)}/allocations", q)

    def evaluations(self, job_id: str, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get(f"/v1/job/{_esc(job_id)}/evaluations", q)

    def deployments(self, job_id: str, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get(f"/v1/job/{_esc(job_id)}/deployments", q)

    def summary(self, job_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.get(f"/v1/job/{_esc(job_id)}/summary", q)

    def versions(self, job_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.get(f"/v1/job/{_esc(job_id)}/versions", q)

    def revert(self, job_id: str, version: int,
               q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(f"/v1/job/{_esc(job_id)}/revert",
                           {"JobID": job_id, "JobVersion": version}, q)

    def stable(self, job_id: str, version: int, stable: bool,
               q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(f"/v1/job/{_esc(job_id)}/stable",
                           {"JobVersion": version, "Stable": stable}, q)

    def dispatch(self, job_id: str, meta: Optional[Dict] = None,
                 payload: bytes = b"", q: Optional[QueryOptions] = None) -> Dict:
        import base64

        return self.c.post(
            f"/v1/job/{_esc(job_id)}/dispatch",
            {"Meta": meta or {},
             "Payload": base64.b64encode(payload).decode()}, q,
        )

    def scale(self, job_id: str, group: str, count: int, message: str = "",
              q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(
            f"/v1/job/{_esc(job_id)}/scale",
            {"Target": {"Group": group}, "Count": count, "Message": message}, q,
        )

    def scale_status(self, job_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.get(f"/v1/job/{_esc(job_id)}/scale", q)

    def periodic_force(self, job_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(f"/v1/job/{_esc(job_id)}/periodic/force", {}, q)

    def parse(self, hcl: str) -> Dict:
        return self.c.post("/v1/jobs/parse", {"JobHCL": hcl})


class Nodes(_Endpoint):
    def list(self, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get("/v1/nodes", q)

    def info(self, node_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.get(f"/v1/node/{_esc(node_id)}", q)

    def allocations(self, node_id: str, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get(f"/v1/node/{_esc(node_id)}/allocations", q)

    def drain(self, node_id: str, enable: bool = True,
              deadline_s: float = 0.0, ignore_system: bool = False,
              q: Optional[QueryOptions] = None) -> Dict:
        spec = None
        if enable:
            spec = {"Deadline": int(deadline_s * 1e9),
                    "IgnoreSystemJobs": ignore_system}
        return self.c.post(f"/v1/node/{_esc(node_id)}/drain", {"DrainSpec": spec}, q)

    def eligibility(self, node_id: str, eligible: bool,
                    q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(
            f"/v1/node/{_esc(node_id)}/eligibility",
            {"Eligibility": "eligible" if eligible else "ineligible"}, q,
        )

    def evaluate(self, node_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(f"/v1/node/{_esc(node_id)}/evaluate", {}, q)

    def purge(self, node_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(f"/v1/node/{_esc(node_id)}/purge", {}, q)


class ExecSession:
    """One interactive exec stream (the SDK half of alloc exec).

    JSON-over-websocket frames mirror the reference's
    drivers.ExecTaskStreaming messages: {"stdin": {"data": b64}},
    {"stdin": {"close": true}}, {"tty_size": {...}} out;
    {"stdout"/"stderr": {"data": b64}}, {"exited", "result"} in.
    """

    def __init__(self, conn) -> None:
        self.conn = conn
        self.exit_code: Optional[int] = None

    def send_stdin(self, data: bytes) -> None:
        import base64
        import json as _json

        self.conn.send(_json.dumps(
            {"stdin": {"data": base64.b64encode(data).decode()}}).encode())

    def close_stdin(self) -> None:
        import json as _json

        self.conn.send(_json.dumps({"stdin": {"close": True}}).encode())

    def resize(self, height: int, width: int) -> None:
        import json as _json

        self.conn.send(_json.dumps(
            {"tty_size": {"height": height, "width": width}}).encode())

    def events(self) -> Iterator[Dict]:
        """Yield decoded frames until the process exits or the peer
        closes; sets ``exit_code`` when the exited frame arrives."""
        import base64
        import json as _json

        from nomad_tpu.utils import ws as wslib

        while True:
            try:
                op, payload = self.conn.recv()
            except (ConnectionError, OSError):
                return
            if op == wslib.OP_CLOSE:
                return
            if op not in (wslib.OP_TEXT, wslib.OP_BINARY):
                continue
            try:
                frame = _json.loads(payload)
            except _json.JSONDecodeError:
                continue
            for name in ("stdout", "stderr"):
                blob = frame.get(name) or {}
                if blob.get("data"):
                    frame[name]["bytes"] = base64.b64decode(blob["data"])
            yield frame
            if frame.get("exited"):
                self.exit_code = (frame.get("result") or {}).get("exit_code")
                return

    def close(self) -> None:
        self.conn.close()


class Allocations(_Endpoint):
    def list(self, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get("/v1/allocations", q)

    def info(self, alloc_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.get(f"/v1/allocation/{_esc(alloc_id)}", q)

    def stats(self, alloc_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.get(f"/v1/client/allocation/{_esc(alloc_id)}/stats", q)

    def restart(self, alloc_id: str, task: str = "",
                q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(f"/v1/client/allocation/{_esc(alloc_id)}/restart",
                           {"TaskName": task}, q)

    def signal(self, alloc_id: str, signal: str, task: str = "",
               q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(f"/v1/client/allocation/{_esc(alloc_id)}/signal",
                           {"Signal": signal, "TaskName": task}, q)

    def exec(self, alloc_id: str, task: str, cmd: List[str],
             q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(f"/v1/client/allocation/{_esc(alloc_id)}/exec",
                           {"Task": task, "Cmd": cmd}, q)

    def exec_stream(self, alloc_id: str, task: str, cmd: List[str],
                    tty: bool = False,
                    q: Optional[QueryOptions] = None) -> "ExecSession":
        """Interactive exec over a websocket (api/allocations_exec.go).

        Returns an ExecSession: write stdin bytes, iterate output
        chunks, read the exit code."""
        import json as _json

        q = q or QueryOptions()
        q.params.update({
            "task": task,
            "command": _json.dumps(cmd),
            "tty": "true" if tty else "false",
        })
        url = self.c._url(f"/v1/client/allocation/{_esc(alloc_id)}/exec", q)
        from nomad_tpu.utils import ws as wslib

        conn = wslib.connect(url, token=self.c.token,
                             tls_context=self.c._ssl_context)
        return ExecSession(conn)

    def logs(self, alloc_id: str, task: str, logtype: str = "stdout",
             offset: int = 0, limit: int = 0,
             q: Optional[QueryOptions] = None) -> str:
        q = q or QueryOptions()
        q.params.update({"task": task, "type": logtype})
        if offset:
            q.params["offset"] = str(offset)
        if limit:
            q.params["limit"] = str(limit)
        resp = self.c.get(f"/v1/client/fs/logs/{_esc(alloc_id)}", q)
        return resp.get("Data", "")

    def logs_follow(self, alloc_id: str, task: str,
                    logtype: str = "stdout", offset: int = 0,
                    timeout: float = 630.0) -> Iterator[bytes]:
        """?follow=true tail: yields raw byte chunks as they arrive.
        Byte chunks let callers resume with offset=bytes-seen."""
        q = QueryOptions()
        q.params.update({"task": task, "type": logtype, "follow": "true"})
        if offset:
            q.params["offset"] = str(offset)
        # _url stamps region/namespace like every other request
        url = self.c._url(f"/v1/client/fs/logs/{_esc(alloc_id)}", q)
        req = urllib.request.Request(
            url,
            headers={"X-Nomad-Token": self.c.token} if self.c.token else {},
        )
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout,
                    context=self.c._ssl_context) as resp:
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        return
                    yield chunk
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("error", str(e))
            except Exception:                   # noqa: BLE001
                msg = str(e)
            raise APIError(e.code, msg) from None

    def fs_ls(self, alloc_id: str, path: str = "/",
              q: Optional[QueryOptions] = None) -> List[Dict]:
        q = q or QueryOptions()
        q.params["path"] = path
        return self.c.get(f"/v1/client/fs/ls/{_esc(alloc_id)}", q)

    def fs_stat(self, alloc_id: str, path: str,
                q: Optional[QueryOptions] = None) -> Dict:
        q = q or QueryOptions()
        q.params["path"] = path
        return self.c.get(f"/v1/client/fs/stat/{_esc(alloc_id)}", q)

    def fs_cat(self, alloc_id: str, path: str,
               q: Optional[QueryOptions] = None) -> str:
        q = q or QueryOptions()
        q.params["path"] = path
        resp = self.c.get(f"/v1/client/fs/cat/{_esc(alloc_id)}", q)
        return resp.get("Data", "")

    def stop(self, alloc_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(f"/v1/allocation/{_esc(alloc_id)}/stop", {}, q)


class Evaluations(_Endpoint):
    def list(self, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get("/v1/evaluations", q)

    def info(self, eval_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.get(f"/v1/evaluation/{_esc(eval_id)}", q)

    def allocations(self, eval_id: str, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get(f"/v1/evaluation/{_esc(eval_id)}/allocations", q)


class Deployments(_Endpoint):
    def list(self, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get("/v1/deployments", q)

    def info(self, deployment_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.get(f"/v1/deployment/{_esc(deployment_id)}", q)

    def fail(self, deployment_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(f"/v1/deployment/fail/{_esc(deployment_id)}", {}, q)

    def pause(self, deployment_id: str, pause: bool = True,
              q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(f"/v1/deployment/pause/{_esc(deployment_id)}",
                           {"Pause": pause}, q)

    def promote(self, deployment_id: str, groups: Optional[List[str]] = None,
                q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post(
            f"/v1/deployment/promote/{_esc(deployment_id)}",
            {"All": groups is None, "Groups": groups}, q,
        )


class System(_Endpoint):
    def gc(self) -> None:
        self.c.put("/v1/system/gc")

    def reconcile_summaries(self) -> None:
        self.c.put("/v1/system/reconcile/summaries")


class Operator(_Endpoint):
    def scheduler_config(self) -> Dict:
        return self.c.get("/v1/operator/scheduler/configuration")

    def set_scheduler_config(self, config: Dict) -> Dict:
        return self.c.put("/v1/operator/scheduler/configuration", config)

    def autopilot_configuration(self) -> Dict:
        return self.c.get("/v1/operator/autopilot/configuration")

    def set_autopilot_configuration(self, config: Dict) -> Dict:
        return self.c.put("/v1/operator/autopilot/configuration", config)

    def autopilot_health(self) -> Dict:
        return self.c.get("/v1/operator/autopilot/health")

    def raft_configuration(self) -> Dict:
        return self.c.get("/v1/operator/raft/configuration")

    def snapshot_save(self) -> bytes:
        import base64

        res = self.c.get("/v1/operator/snapshot")
        return base64.b64decode(res["Snapshot"])

    def snapshot_restore(self, data: bytes) -> Dict:
        import base64

        return self.c.put("/v1/operator/snapshot",
                          {"Snapshot": base64.b64encode(data).decode()})


class AgentAPI(_Endpoint):
    def self(self) -> Dict:
        return self.c.get("/v1/agent/self")

    def health(self) -> Dict:
        return self.c.get("/v1/agent/health")

    def members(self) -> Dict:
        return self.c.get("/v1/agent/members")

    def metrics(self) -> Dict:
        return self.c.get("/v1/metrics")

    _PPROF_PROFILES = ("goroutine", "profile", "heap")

    def pprof(self, profile: str = "goroutine", seconds: int = 1) -> str:
        if profile not in self._PPROF_PROFILES:
            raise ValueError(
                f"unsupported profile {profile!r}; "
                f"one of {', '.join(self._PPROF_PROFILES)}"
            )
        q = QueryOptions()
        if profile == "profile":
            q.params["seconds"] = str(seconds)
        return self.c.get(f"/v1/agent/pprof/{_esc(profile)}",
                          q).get("Profile", "")

    def monitor(self, log_level: str = "info",
                timeout: float = 60.0) -> Iterator[str]:
        """Yield live log lines from /v1/agent/monitor."""
        for payload in self.c.stream("/v1/agent/monitor",
                                     [("log_level", log_level)], timeout):
            if payload.get("Data"):
                yield payload["Data"]


class Search(_Endpoint):
    def prefix(self, prefix: str, context: str = "all",
               q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post("/v1/search",
                           {"Prefix": prefix, "Context": context}, q)

    def fuzzy(self, text: str, context: str = "all",
              q: Optional[QueryOptions] = None) -> Dict:
        return self.c.post("/v1/search/fuzzy",
                           {"Text": text, "Context": context}, q)


class Namespaces(_Endpoint):
    def list(self) -> List[Dict]:
        return self.c.get("/v1/namespaces")

    def info(self, name: str) -> Dict:
        return self.c.get(f"/v1/namespace/{_esc(name)}")

    def register(self, name: str, description: str = "") -> Dict:
        return self.c.put(f"/v1/namespace/{_esc(name)}",
                          {"Name": name, "Description": description})

    def delete(self, name: str) -> Dict:
        return self.c.delete(f"/v1/namespace/{_esc(name)}")


class Scaling(_Endpoint):
    def policies(self) -> List[Dict]:
        return self.c.get("/v1/scaling/policies")

    def policy(self, policy_id: str) -> Dict:
        return self.c.get(f"/v1/scaling/policy/{_esc(policy_id)}")


class CSIVolumes(_Endpoint):
    """api/csi.go CSIVolumes."""

    def list(self, plugin_id: str = "",
             q: Optional[QueryOptions] = None) -> List[Dict]:
        q = q or QueryOptions()
        if plugin_id:
            q.params["plugin_id"] = plugin_id
        return self.c.get("/v1/volumes", q)

    def info(self, volume_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.get(f"/v1/volume/csi/{_esc(volume_id)}", q)

    def register(self, volume: Dict, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.put("/v1/volumes", {"Volumes": [volume]}, q)

    def deregister(self, volume_id: str, force: bool = False,
                   q: Optional[QueryOptions] = None) -> Dict:
        q = q or QueryOptions()
        if force:
            q.params["force"] = "true"
        return self.c.delete(f"/v1/volume/csi/{_esc(volume_id)}", q)

    def create(self, volume: Dict, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.put(
            f"/v1/volume/csi/{_esc(volume.get('ID', volume.get('id', '')))}/create",
            {"Volumes": [volume]}, q,
        )

    def delete(self, volume_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.delete(f"/v1/volume/csi/{_esc(volume_id)}/delete", q)

    def detach(self, volume_id: str, node_id: str = "", alloc_id: str = "",
               q: Optional[QueryOptions] = None) -> Dict:
        q = q or QueryOptions()
        if node_id:
            q.params["node"] = node_id
        if alloc_id:
            q.params["alloc"] = alloc_id
        return self.c.put(f"/v1/volume/csi/{_esc(volume_id)}/detach", q=q)


class CSIPlugins(_Endpoint):
    def list(self, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get("/v1/plugins", q)

    def info(self, plugin_id: str, q: Optional[QueryOptions] = None) -> Dict:
        return self.c.get(f"/v1/plugin/csi/{_esc(plugin_id)}", q)


class Services(_Endpoint):
    """api/services.go: native service discovery."""

    def list(self, q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get("/v1/services", q)

    def get(self, service_name: str,
            q: Optional[QueryOptions] = None) -> List[Dict]:
        return self.c.get(f"/v1/service/{_esc(service_name)}", q)

    def delete(self, service_name: str, service_id: str,
               q: Optional[QueryOptions] = None) -> Dict:
        return self.c.delete(
            f"/v1/service/{_esc(service_name)}/{_esc(service_id)}", q
        )


class ACLAPI(_Endpoint):
    def bootstrap(self) -> Dict:
        return self.c.post("/v1/acl/bootstrap")

    def policies(self) -> List[Dict]:
        return self.c.get("/v1/acl/policies")

    def policy(self, name: str) -> Dict:
        return self.c.get(f"/v1/acl/policy/{_esc(name)}")

    def put_policy(self, name: str, rules: str, description: str = "") -> Dict:
        return self.c.put(f"/v1/acl/policy/{_esc(name)}",
                          {"Rules": rules, "Description": description})

    def delete_policy(self, name: str) -> Dict:
        return self.c.delete(f"/v1/acl/policy/{_esc(name)}")

    def tokens(self) -> List[Dict]:
        return self.c.get("/v1/acl/tokens")

    def create_token(self, name: str = "", type: str = "client",
                     policies: Optional[List[str]] = None,
                     global_: bool = False) -> Dict:
        return self.c.put("/v1/acl/token", {
            "Name": name, "Type": type, "Policies": policies or [],
            "Global": global_,
        })

    def self_token(self) -> Dict:
        return self.c.get("/v1/acl/token/self")

    def token(self, accessor_id: str) -> Dict:
        return self.c.get(f"/v1/acl/token/{_esc(accessor_id)}")

    def create_one_time_token(self) -> Dict:
        return self.c.post("/v1/acl/token/onetime")

    def exchange_one_time_token(self, secret: str) -> Dict:
        return self.c.post("/v1/acl/token/onetime/exchange",
                           {"OneTimeSecretID": secret})

    def delete_token(self, accessor_id: str) -> Dict:
        return self.c.delete(f"/v1/acl/token/{_esc(accessor_id)}")


class Events(_Endpoint):
    def stream(self, topics: Optional[Dict[str, List[str]]] = None,
               index: int = 0, timeout: float = 60.0) -> Iterator[Dict]:
        """Yield event batches from /v1/event/stream (NDJSON frames)."""
        params = []
        for topic, keys in (topics or {"*": ["*"]}).items():
            for key in keys:
                params.append(("topic", f"{topic}:{key}"))
        if index:
            params.append(("index", str(index)))
        yield from self.c.stream("/v1/event/stream", params, timeout)
