"""Agent configuration files.

Reference behavior: command/agent/config.go:39 + config_parse.go —
the agent loads one or more HCL/JSON config files (or directories),
merges them in order (later wins), then applies CLI flags on top.
This module parses the same shape of file into AgentConfig:

    name       = "node-1"
    region     = "global"
    datacenter = "dc1"
    bind_addr  = "0.0.0.0"
    ports { http = 4646 }
    server {
      enabled           = true
      num_schedulers    = 2
      scheduler_workers = 0   # N>0: multi-process scheduler workers
    }
    client {
      enabled    = true
      node_class = "compute"
      meta { rack = "r1" }
    }
    acl { enabled = true }
    tls {
      http      = true
      ca_file   = "ca.pem"
      cert_file = "cert.pem"
      key_file  = "key.pem"
      verify_https_client = false
    }
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from nomad_tpu.jobspec.hcl import Body, parse


def load_config_files(paths: List[str], base=None):
    """Merge config files/directories into an AgentConfig
    (config.go LoadConfig/Merge semantics: later files win)."""
    from nomad_tpu.api.agent import AgentConfig

    cfg = base or AgentConfig()
    for path in paths:
        if os.path.isdir(path):
            entries = sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if f.endswith((".hcl", ".json"))
            )
            for entry in entries:
                cfg = _apply_file(cfg, entry)
        else:
            cfg = _apply_file(cfg, path)
    return cfg


def _apply_file(cfg, path: str):
    with open(path) as f:
        src = f.read()
    if path.endswith(".json"):
        data = json.loads(src)
        body = _json_to_body(data)
    else:
        body = parse(src)
    return _apply_body(cfg, body)


def _json_to_body(data: dict) -> Body:
    body = Body()
    for k, v in data.items():
        if isinstance(v, dict):
            body.blocks.append((k, [], _json_to_body(v)))
        else:
            body.attrs[k] = v
    return body


def _apply_body(cfg, body: Body):
    a = body.attrs
    if "name" in a:
        cfg.name = str(a["name"])
    if "region" in a:
        cfg.region = str(a["region"])
    if "datacenter" in a:
        cfg.datacenter = str(a["datacenter"])
    if "bind_addr" in a:
        cfg.bind_addr = str(a["bind_addr"])
    # gossip authentication key (reference agent config `encrypt`,
    # a top-level attribute)
    if "encrypt" in a:
        cfg.encrypt = str(a["encrypt"])
    # agent state dir (reference top-level `data_dir`): turns on the
    # crash-safe raft durability plane — term/vote, WAL, snapshots
    # persist under <data_dir>/raft (docs/ROBUSTNESS.md "Durability")
    if "data_dir" in a:
        cfg.data_dir = str(a["data_dir"])

    ports = body.first_block("ports")
    if ports is not None and "http" in ports[1].attrs:
        cfg.http_port = int(ports[1].attrs["http"])
    if ports is not None and "serf" in ports[1].attrs:
        cfg.serf_port = int(ports[1].attrs["serf"])

    srv = body.first_block("server")
    if srv is not None:
        sa = srv[1].attrs
        if "enabled" in sa:
            cfg.server_enabled = bool(sa["enabled"])
        if "num_schedulers" in sa:
            cfg.num_schedulers = int(sa["num_schedulers"])
        # multi-process scheduler workers (server/workerproc.py):
        # 0 = in-process threads, the bit-identical default
        if "scheduler_workers" in sa:
            cfg.scheduler_workers = int(sa["scheduler_workers"])
        if "raft_port" in sa:
            cfg.raft_port = int(sa["raft_port"])
        if "raft_peers" in sa:
            cfg.raft_peers = [str(p) for p in sa["raft_peers"]]
        if "raft_advertise" in sa:
            cfg.raft_advertise = str(sa["raft_advertise"])
        if "serf_enabled" in sa:
            cfg.serf_enabled = bool(sa["serf_enabled"])
        if "serf_port" in sa:
            cfg.serf_port = int(sa["serf_port"])
        # AOT placement-kernel warmup + adaptive wave-coalescer window
        # (ops/warmup.py, parallel/coalesce.py; see docs/PERF.md)
        if "kernel_warmup" in sa:
            cfg.kernel_warmup = bool(sa["kernel_warmup"])
        if "warmup_manifest" in sa:
            cfg.warmup_manifest = str(sa["warmup_manifest"])
        if "coalesce_adaptive" in sa:
            cfg.coalesce_adaptive = bool(sa["coalesce_adaptive"])
        if "coalesce_window_min_ms" in sa:
            cfg.coalesce_window_min_ms = float(sa["coalesce_window_min_ms"])
        if "coalesce_window_max_ms" in sa:
            cfg.coalesce_window_max_ms = float(sa["coalesce_window_max_ms"])
        # WAL fsync policy ("always" per record / "batch" group-fsync
        # at ack boundaries; raft/wal.py)
        if "raft_fsync_policy" in sa:
            cfg.raft_fsync_policy = str(sa["raft_fsync_policy"])
        # replication pipeline + leader leases (raft/node.py, ISSUE 18)
        if "raft_max_in_flight" in sa:
            cfg.raft_max_in_flight = int(sa["raft_max_in_flight"])
        if "raft_leader_lease" in sa:
            cfg.raft_leader_lease = bool(sa["raft_leader_lease"])
        if "raft_lease_fraction" in sa:
            cfg.raft_lease_fraction = float(sa["raft_lease_fraction"])
        # gossip membership seeds ("host:port"; DNS names expand to
        # every A record — join-by-DNS)
        if "server_join" in sa and isinstance(sa["server_join"], list):
            cfg.server_join = [str(x) for x in sa["server_join"]]
        # server_join stanza (agent config server_join/retry_join):
        # retry_join entries are "region@http_url" for WAN federation
        sj = srv[1].first_block("server_join")
        if sj is not None:
            ja = sj[1].attrs
            if "retry_join" in ja:
                cfg.retry_join = [str(x) for x in ja["retry_join"]]
            if "retry_max" in ja:
                cfg.retry_join_max_attempts = int(ja["retry_max"])
            if "retry_interval" in ja:
                from nomad_tpu.jobspec.hcl import duration_s

                cfg.retry_join_interval = duration_s(ja["retry_interval"])

    cli = body.first_block("client")
    if cli is not None:
        ca = cli[1].attrs
        if "enabled" in ca:
            cfg.client_enabled = bool(ca["enabled"])
        if "node_class" in ca:
            cfg.node_class = str(ca["node_class"])
        if "plugin_dir" in ca:
            cfg.plugin_dir = str(ca["plugin_dir"])
        meta = cli[1].first_block("meta")
        if meta is not None:
            cfg.meta = {str(k): str(v) for k, v in meta[1].attrs.items()}
        elif isinstance(ca.get("meta"), dict):
            cfg.meta = {str(k): str(v) for k, v in ca["meta"].items()}
        opts = cli[1].first_block("options")
        if opts is not None:
            cfg.client_options = {
                str(k): str(v) for k, v in opts[1].attrs.items()}
        elif isinstance(ca.get("options"), dict):
            cfg.client_options = {
                str(k): str(v) for k, v in ca["options"].items()}

    acl = body.first_block("acl")
    if acl is not None and "enabled" in acl[1].attrs:
        cfg.acl_enabled = bool(acl[1].attrs["enabled"])

    # vault { address = "http://..." token = "..." create_from_role = "" }
    # (command/agent/config.go Vault stanza)
    vault = body.first_block("vault")
    if vault is not None:
        va = vault[1].attrs
        cfg.vault_addr = str(va.get("address", ""))
        cfg.vault_token = str(va.get("token", ""))
        cfg.vault_token_role = str(va.get("create_from_role", ""))

    tls = body.first_block("tls")
    if tls is not None:
        ta = tls[1].attrs
        if ta.get("http") or ta.get("cert_file"):
            from nomad_tpu.utils.tlsutil import TLSConfig
            cfg.tls = TLSConfig(
                enabled=True,
                ca_file=str(ta.get("ca_file", "")),
                cert_file=str(ta.get("cert_file", "")),
                key_file=str(ta.get("key_file", "")),
                verify_https_client=bool(
                    ta.get("verify_https_client", False)),
            )
    return cfg
