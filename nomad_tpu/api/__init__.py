"""HTTP API surface: agent routes, JSON codec, SDK client.

Reference: command/agent/http.go (/v1 routes :321-411), api/api.go
(Go SDK :448). The agent serves both server-backed and client-backed
routes from one process, mirroring the reference's merged agent.
"""
