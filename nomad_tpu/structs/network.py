"""Network resources and per-node port/bandwidth accounting.

Reference behavior: nomad/structs/network.go -- ``NetworkIndex`` (:39),
``SetNode`` (:176), ``AddAllocs`` (:242), ``AddReserved`` (:298),
``AssignPorts`` (:427), ``AssignNetwork`` (:517), dynamic port range
20000..32000 (:13-19), ``Bitmap`` (nomad/structs/bitmap.go).

TPU-first design note: the port bitmap is a numpy uint64 array so the
cluster-wide "used ports" plane stacks into a ``[n_nodes, 1024]`` u64 tensor
that the device kernel can gather against for reserved-port feasibility
(ragged per-port data in a fixed-width encoding); *assignment* of specific
dynamic ports stays host-side and only runs for the selected node.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

MAX_VALID_PORT = 65536
DEFAULT_MIN_DYNAMIC_PORT = 20000
DEFAULT_MAX_DYNAMIC_PORT = 32000

_WORDS = MAX_VALID_PORT // 64  # 1024 uint64 words cover the port space


@dataclass
class Port:
    """A labeled port ask/offer (reference structs.go Port)."""

    label: str = ""
    value: int = 0           # 0 for dynamic asks; assigned value in offers
    to: int = 0              # mapped-to port inside the task namespace
    host_network: str = "default"

    def copy(self) -> "Port":
        return dataclasses.replace(self)


@dataclass
class NetworkResource:
    """A network ask or offer (reference structs.go NetworkResource)."""

    mode: str = "host"
    device: str = ""
    cidr: str = ""
    ip: str = ""
    mbits: int = 0
    reserved_ports: List[Port] = field(default_factory=list)
    dynamic_ports: List[Port] = field(default_factory=list)

    def copy(self) -> "NetworkResource":
        return dataclasses.replace(
            self,
            reserved_ports=[p.copy() for p in self.reserved_ports],
            dynamic_ports=[p.copy() for p in self.dynamic_ports],
        )

    def port_for_label(self, label: str) -> Optional[int]:
        for p in list(self.reserved_ports) + list(self.dynamic_ports):
            if p.label == label:
                return p.value
        return None


class PortBitmap:
    """Fixed 65536-bit port bitmap backed by numpy uint64 words.

    Reference: nomad/structs/bitmap.go. The numpy representation is the
    tensorization seam: ``PortBitmap.words`` rows stack into the cluster
    port-plane consumed by the JAX kernel.
    """

    __slots__ = ("words",)

    def __init__(self, words: Optional[np.ndarray] = None) -> None:
        self.words = words if words is not None else np.zeros(_WORDS, dtype=np.uint64)

    def set(self, port: int) -> None:
        self.words[port >> 6] |= np.uint64(1 << (port & 63))

    def clear(self, port: int) -> None:
        self.words[port >> 6] &= ~np.uint64(1 << (port & 63))

    def check(self, port: int) -> bool:
        return bool(self.words[port >> 6] & np.uint64(1 << (port & 63)))

    def copy(self) -> "PortBitmap":
        return PortBitmap(self.words.copy())

    def _bits_in_range(self, lo: int, hi: int) -> np.ndarray:
        """Unpack only the words covering [lo, hi] (not the full 64Ki space)."""
        wlo, whi = lo >> 6, (hi >> 6) + 1
        bits = np.unpackbits(self.words[wlo:whi].view(np.uint8), bitorder="little")
        base = wlo << 6
        return bits[lo - base : hi + 1 - base]

    def indexes_in_range(self, set_: bool, lo: int, hi: int, limit: int = 0) -> List[int]:
        """Ports in [lo, hi] whose bit equals ``set_`` (bitmap.go
        IndexesInRange). ``limit`` > 0 stops after that many matches."""
        bits = self._bits_in_range(lo, hi)
        sel = np.nonzero(bits == (1 if set_ else 0))[0]
        if limit > 0:
            sel = sel[:limit]
        return (sel + lo).tolist()

    def free_count_in_range(self, lo: int, hi: int) -> int:
        return int((self._bits_in_range(lo, hi) == 0).sum())


class NetworkIndex:
    """Per-node port and bandwidth accounting (network.go:39).

    Tracks used ports per IP and used bandwidth per device; offers
    reserved-port collision detection and dynamic-port assignment.
    """

    def __init__(self) -> None:
        self.avail_networks: List[NetworkResource] = []
        self.avail_addresses: Dict[str, List[Tuple[str, str]]] = {}  # host_network -> [(iface, ip)]
        self.avail_bandwidth: Dict[str, int] = {}
        self.used_ports: Dict[str, PortBitmap] = {}
        self.used_bandwidth: Dict[str, int] = {}
        self.min_dynamic_port = DEFAULT_MIN_DYNAMIC_PORT
        self.max_dynamic_port = DEFAULT_MAX_DYNAMIC_PORT
        self._rng = None

    def seed(self, seed: int) -> None:
        """Enable stochastic dynamic-port selection (network.go:598),
        deterministically per seed."""
        import random

        self._rng = random.Random(seed)

    # -- setup ------------------------------------------------------------

    def _used_for(self, ip: str) -> PortBitmap:
        bm = self.used_ports.get(ip)
        if bm is None:
            bm = PortBitmap()
            self.used_ports[ip] = bm
        return bm

    def set_node(self, node) -> Tuple[bool, str]:
        """Index a node's networks + agent-reserved ports (network.go:176)."""
        collide, reason = False, ""
        for n in node.node_resources.networks:
            if n.device:
                self.avail_networks.append(n)
                self.avail_bandwidth[n.device] = n.mbits
                ip = n.ip or "0.0.0.0"
                self.avail_addresses.setdefault("default", []).append((n.device, ip))

        # Node-reserved host ports collide if double-reserved.
        reserved = getattr(node.reserved_resources, "networks_ports", [])
        for port in reserved:
            if port < 0 or port >= MAX_VALID_PORT:
                return True, f"invalid port {port}"
            for ip in self._all_ips():
                used = self._used_for(ip)
                if used.check(port):
                    collide, reason = True, f"port {port} already reserved"
                else:
                    used.set(port)
        if node.node_resources.min_dynamic_port:
            self.min_dynamic_port = node.node_resources.min_dynamic_port
        if node.node_resources.max_dynamic_port:
            self.max_dynamic_port = node.node_resources.max_dynamic_port
        return collide, reason

    def _all_ips(self) -> List[str]:
        ips = [ip for addrs in self.avail_addresses.values() for _, ip in addrs]
        return ips or ["0.0.0.0"]

    def add_allocs(self, allocs) -> Tuple[bool, str]:
        """Index ports used by existing allocations (network.go:242)."""
        collide, reason = False, ""
        for alloc in allocs:
            if not alloc.terminal_status():
                ar = alloc.allocated_resources
                if ar is None:
                    continue
                for tr in ar.tasks.values():
                    for net in tr.networks:
                        c, r = self.add_reserved(net)
                        if c:
                            collide, reason = True, r
                # Group-shared ports are recorded against the node's primary
                # IP (single-address model; per-host-network routing is a
                # representational extension, not implemented).
                for port in ar.shared.ports:
                    if port.value < 0 or port.value >= MAX_VALID_PORT:
                        collide, reason = True, f"invalid port {port.value}"
                        continue
                    used = self._used_for(self._all_ips()[0])
                    if used.check(port.value):
                        collide, reason = True, f"port {port.value} already in use"
                    else:
                        used.set(port.value)
        return collide, reason

    def add_reserved(self, n: NetworkResource) -> Tuple[bool, str]:
        """Mark an offer's ports as used (network.go:298)."""
        collide, reason = False, ""
        ip = n.ip or self._all_ips()[0]
        used = self._used_for(ip)
        for port in list(n.reserved_ports) + list(n.dynamic_ports):
            if port.value >= MAX_VALID_PORT or port.value < 0:
                return True, f"invalid port {port.value}"
            if used.check(port.value):
                collide, reason = True, f"port {port.value} already in use"
            else:
                used.set(port.value)
        self.used_bandwidth[n.device] = self.used_bandwidth.get(n.device, 0) + n.mbits
        return collide, reason

    def add_reserved_ports(self, ports: List[Port]) -> Tuple[bool, str]:
        """Mark group-level allocated ports used (network.go:323)."""
        collide, reason = False, ""
        for port in ports:
            if port.value < 0 or port.value >= MAX_VALID_PORT:
                collide, reason = True, f"invalid port {port.value}"
                continue
            used = self._used_for(self._all_ips()[0])
            if used.check(port.value):
                collide, reason = True, f"port {port.value} already in use"
            else:
                used.set(port.value)
        return collide, reason

    # -- queries ----------------------------------------------------------

    def overcommitted(self) -> bool:
        """Bandwidth overcommit check (network.go:163)."""
        for device, used in self.used_bandwidth.items():
            avail = self.avail_bandwidth.get(device, 0)
            if used > avail:
                return True
        return False

    def _assign_dynamic(self, used: PortBitmap, reserved_asks: List[Port], count: int) -> Optional[List[int]]:
        """Dynamic port selection: seeded-stochastic, then precise.

        The reference tries stochastic then precise selection
        (network.go:598,640). The stochastic pass matters under
        concurrency: schedulers picking ports for the same node from
        the same snapshot must decorrelate, or every plan but the first
        is rejected by the applier's collision re-check. ``seed()``
        (per eval, like shuffleNodes util.go:464) keeps plans
        reproducible; unseeded indexes use the precise path only.
        """
        if count == 0:
            return []
        taken = {p.value for p in reserved_asks}
        if self._rng is not None:
            span = self.max_dynamic_port - self.min_dynamic_port + 1
            picked: List[int] = []
            for _ in range(20 * count + 20):
                if len(picked) == count:
                    break
                port = self.min_dynamic_port + self._rng.randrange(span)
                if port in taken or port in picked or used.check(port):
                    continue
                picked.append(port)
            if len(picked) == count:
                return picked
        out: List[int] = []
        # Over-fetch by len(taken) so reserved asks in the range can't starve us.
        candidates = used.indexes_in_range(
            False, self.min_dynamic_port, self.max_dynamic_port,
            limit=count + len(taken),
        )
        for port in candidates:
            if port in taken:
                continue
            out.append(port)
            if len(out) == count:
                return out
        return None

    def assign_ports(self, ask: NetworkResource) -> Tuple[Optional[List[Port]], str]:
        """Assign group-level ports (network.go:427). Returns (offer, err)."""
        offer: List[Port] = []
        ip = self._all_ips()[0]
        used = self._used_for(ip)
        reserved_asks = list(ask.reserved_ports)

        for port in ask.reserved_ports:
            if port.value < 0 or port.value >= MAX_VALID_PORT:
                return None, f"invalid port {port.value} (out of range)"
            if used.check(port.value):
                return None, f"reserved port collision {port.label}={port.value}"
            offer.append(Port(label=port.label, value=port.value,
                              to=port.to, host_network=port.host_network))

        dyn = self._assign_dynamic(used, reserved_asks, len(ask.dynamic_ports))
        if dyn is None:
            return None, "dynamic port selection failed"
        for port, value in zip(ask.dynamic_ports, dyn):
            to = port.to if port.to != -1 else value
            offer.append(Port(label=port.label, value=value, to=to,
                              host_network=port.host_network))
        return offer, ""

    def assign_network(self, ask: NetworkResource) -> Tuple[Optional[NetworkResource], str]:
        """Assign a legacy task-level network (network.go:517)."""
        err = "no networks available"
        for n in self.avail_networks:
            ip = n.ip or "0.0.0.0"
            avail_bw = self.avail_bandwidth.get(n.device, 0)
            used_bw = self.used_bandwidth.get(n.device, 0)
            if used_bw + ask.mbits > avail_bw:
                err = "bandwidth exceeded"
                continue
            used = self._used_for(ip)
            collision = False
            for port in ask.reserved_ports:
                if port.value < 0 or port.value >= MAX_VALID_PORT:
                    return None, f"invalid port {port.value} (out of range)"
                if used.check(port.value):
                    err = f"reserved port collision {port.label}={port.value}"
                    collision = True
                    break
            if collision:
                continue
            dyn = self._assign_dynamic(used, list(ask.reserved_ports), len(ask.dynamic_ports))
            if dyn is None:
                err = "dynamic port selection failed"
                continue
            offer = NetworkResource(
                mode=ask.mode, device=n.device, ip=ip, mbits=ask.mbits,
                reserved_ports=[p.copy() for p in ask.reserved_ports],
                dynamic_ports=[
                    Port(label=p.label, value=v, to=(p.to if p.to != -1 else v),
                         host_network=p.host_network)
                    for p, v in zip(ask.dynamic_ports, dyn)
                ],
            )
            return offer, ""
        return None, err

    # -- tensorization seam ----------------------------------------------

    def port_words(self) -> np.ndarray:
        """OR of all per-IP bitmaps -> one u64[1024] row for the node plane."""
        acc = np.zeros(_WORDS, dtype=np.uint64)
        for bm in self.used_ports.values():
            acc |= bm.words
        return acc

    def free_dynamic_count(self) -> int:
        bm = PortBitmap(self.port_words())
        return bm.free_count_in_range(self.min_dynamic_port, self.max_dynamic_port)
