"""Resource model and fit/score math.

Reference behavior: nomad/structs/structs.go (Resources :2500 area,
NodeResources :2894, NodeReservedResources :3453, AllocatedResources :3524,
ComparableResources :3970) and nomad/structs/funcs.go (AllocsFit :166,
computeFreePercentage :235, ScoreFitBinPack :259, ScoreFitSpread :286).

These are the *host-side* reference semantics; the TPU kernel in
``nomad_tpu.ops.kernel`` reproduces exactly this math as vectorized ops over
the node tensor, and the tests assert parity between the two.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from nomad_tpu.structs.network import NetworkIndex, NetworkResource, Port


# ---------------------------------------------------------------------------
# Ask-side (what a task requests)
# ---------------------------------------------------------------------------


@dataclass
class RequestedDevice:
    """A device ask, e.g. "nvidia/gpu" or "google/tpu" count=4.

    Reference: nomad/structs/devices.go + structs.go RequestedDevice.
    Name is `[vendor/]type[/model]`.
    """

    name: str = ""
    count: int = 1
    constraints: List = field(default_factory=list)   # List[Constraint]
    affinities: List = field(default_factory=list)    # List[Affinity]

    def id_tuple(self) -> Tuple[str, ...]:
        return tuple(self.name.split("/"))

    def copy(self) -> "RequestedDevice":
        return dataclasses.replace(
            self,
            constraints=[c.copy() for c in self.constraints],
            affinities=[a.copy() for a in self.affinities],
        )


@dataclass
class Resources:
    """Per-task resource ask (reference structs.go Resources).

    CPU in MHz shares, memory/disk in MB. ``cores`` reserves whole cpu
    cores (reference rank.go:462-492 cpuset handling).
    """

    cpu: int = 100
    cores: int = 0
    memory_mb: int = 300
    memory_max_mb: int = 0
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[RequestedDevice] = field(default_factory=list)

    def copy(self) -> "Resources":
        return dataclasses.replace(
            self,
            networks=[n.copy() for n in self.networks],
            devices=[d.copy() for d in self.devices],
        )


# ---------------------------------------------------------------------------
# Node-side (what a node offers)
# ---------------------------------------------------------------------------


@dataclass
class NodeCpuResources:
    """Reference structs.go NodeCpuResources."""

    cpu_shares: int = 0                 # total MHz
    total_core_count: int = 0
    reservable_cpu_cores: List[int] = field(default_factory=list)

    def shares_per_core(self) -> int:
        if self.total_core_count == 0:
            return 0
        return self.cpu_shares // self.total_core_count


@dataclass
class NodeMemoryResources:
    memory_mb: int = 0


@dataclass
class NodeDiskResources:
    disk_mb: int = 0


@dataclass
class NodeDeviceResource:
    """A homogeneous group of device instances on a node.

    Reference: nomad/structs/devices.go NodeDeviceResource -- vendor/type/name
    plus instance list; attributes drive device constraints/affinities.
    """

    vendor: str = ""
    type: str = ""
    name: str = ""
    instance_ids: List[str] = field(default_factory=list)
    attributes: Dict[str, object] = field(default_factory=dict)
    healthy_ids: Optional[List[str]] = None  # defaults to all instances

    def id_string(self) -> str:
        return f"{self.vendor}/{self.type}/{self.name}"

    def available_ids(self) -> List[str]:
        return list(self.healthy_ids if self.healthy_ids is not None else self.instance_ids)

    def matches_request(self, name: str) -> bool:
        """Match a RequestedDevice.name of the form type | vendor/type |
        vendor/type/model (reference devices.go ID matching)."""
        parts = name.split("/")
        if len(parts) == 1:
            return parts[0] == self.type
        if len(parts) == 2:
            return parts[0] == self.vendor and parts[1] == self.type
        if len(parts) == 3:
            return (
                parts[0] == self.vendor
                and parts[1] == self.type
                and parts[2] == self.name
            )
        return False


@dataclass
class NodeResources:
    """Total resources a node fingerprints (reference structs.go:2894)."""

    cpu: NodeCpuResources = field(default_factory=NodeCpuResources)
    memory: NodeMemoryResources = field(default_factory=NodeMemoryResources)
    disk: NodeDiskResources = field(default_factory=NodeDiskResources)
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[NodeDeviceResource] = field(default_factory=list)
    min_dynamic_port: int = 0  # 0 -> NetworkIndex default (20000)
    max_dynamic_port: int = 0  # 0 -> NetworkIndex default (32000)

    def comparable(self) -> "ComparableResources":
        return ComparableResources(
            cpu_shares=self.cpu.cpu_shares,
            memory_mb=self.memory.memory_mb,
            disk_mb=self.disk.disk_mb,
            reserved_cores=list(self.cpu.reservable_cpu_cores),
        )


@dataclass
class NodeReservedResources:
    """Resources the agent excludes from scheduling (structs.go:3453)."""

    cpu_shares: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    reserved_cpu_cores: List[int] = field(default_factory=list)
    networks_ports: List[int] = field(default_factory=list)  # reserved host ports

    def comparable(self) -> "ComparableResources":
        return ComparableResources(
            cpu_shares=self.cpu_shares,
            memory_mb=self.memory_mb,
            disk_mb=self.disk_mb,
            reserved_cores=list(self.reserved_cpu_cores),
        )


# ---------------------------------------------------------------------------
# Allocated (what a placement consumed)
# ---------------------------------------------------------------------------


@dataclass
class AllocatedCpuResources:
    cpu_shares: int = 0
    reserved_cores: List[int] = field(default_factory=list)


@dataclass
class AllocatedMemoryResources:
    memory_mb: int = 0
    memory_max_mb: int = 0


@dataclass
class AllocatedDeviceResource:
    vendor: str = ""
    type: str = ""
    name: str = ""
    device_ids: List[str] = field(default_factory=list)

    def id_string(self) -> str:
        return f"{self.vendor}/{self.type}/{self.name}"


@dataclass
class AllocatedTaskResources:
    cpu: AllocatedCpuResources = field(default_factory=AllocatedCpuResources)
    memory: AllocatedMemoryResources = field(default_factory=AllocatedMemoryResources)
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[AllocatedDeviceResource] = field(default_factory=list)

    def copy(self) -> "AllocatedTaskResources":
        return AllocatedTaskResources(
            cpu=dataclasses.replace(self.cpu, reserved_cores=list(self.cpu.reserved_cores)),
            memory=dataclasses.replace(self.memory),
            networks=[n.copy() for n in self.networks],
            devices=[dataclasses.replace(d, device_ids=list(d.device_ids)) for d in self.devices],
        )


@dataclass
class AllocatedSharedResources:
    """Task-group-shared resources (disk, group network/ports)."""

    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    ports: List[Port] = field(default_factory=list)


@dataclass
class AllocatedResources:
    """Per-alloc resource record: per-task map + shared (structs.go:3524)."""

    tasks: Dict[str, AllocatedTaskResources] = field(default_factory=dict)
    task_lifecycles: Dict[str, Optional[object]] = field(default_factory=dict)
    shared: AllocatedSharedResources = field(default_factory=AllocatedSharedResources)

    def comparable(self) -> "ComparableResources":
        """Flatten to the comparable form used by fit/score math.

        Reference structs.go AllocatedResources.Comparable: sums
        non-sidecar task resources (lifecycle handling simplified: all
        tasks summed), unions reserved cores, merges networks/ports.
        """
        c = ComparableResources(disk_mb=self.shared.disk_mb)
        for tr in self.tasks.values():
            c.cpu_shares += tr.cpu.cpu_shares
            c.reserved_cores = sorted(set(c.reserved_cores) | set(tr.cpu.reserved_cores))
            c.memory_mb += tr.memory.memory_mb
            c.networks.extend(tr.networks)
        c.networks.extend(self.shared.networks)
        return c


@dataclass
class ComparableResources:
    """Flattened cpu/mem/disk/networks used by scoring (structs.go:3970)."""

    cpu_shares: int = 0
    reserved_cores: List[int] = field(default_factory=list)
    memory_mb: int = 0
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)

    def add(self, other: Optional["ComparableResources"]) -> None:
        if other is None:
            return
        self.cpu_shares += other.cpu_shares
        self.reserved_cores = sorted(set(self.reserved_cores) | set(other.reserved_cores))
        self.memory_mb += other.memory_mb
        self.disk_mb += other.disk_mb
        self.networks.extend(other.networks)

    def subtract(self, other: Optional["ComparableResources"]) -> None:
        if other is None:
            return
        self.cpu_shares -= other.cpu_shares
        self.reserved_cores = sorted(set(self.reserved_cores) - set(other.reserved_cores))
        self.memory_mb -= other.memory_mb
        self.disk_mb -= other.disk_mb

    def superset(self, other: "ComparableResources") -> Tuple[bool, str]:
        """Is self a superset of other? Returns (ok, exhausted-dimension).

        Reference structs.go ComparableResources.Superset -- including the
        cpuset containment check for reserved cores (structs.go:4009).
        """
        if self.cpu_shares < other.cpu_shares:
            return False, "cpu"
        if other.reserved_cores and not set(other.reserved_cores) <= set(self.reserved_cores):
            return False, "cores"
        if self.memory_mb < other.memory_mb:
            return False, "memory"
        if self.disk_mb < other.disk_mb:
            return False, "disk"
        return True, ""

    def copy(self) -> "ComparableResources":
        return ComparableResources(
            cpu_shares=self.cpu_shares,
            reserved_cores=list(self.reserved_cores),
            memory_mb=self.memory_mb,
            disk_mb=self.disk_mb,
            networks=[n.copy() for n in self.networks],
        )


# ---------------------------------------------------------------------------
# Device accounting (reference structs/devices.go DeviceAccounter)
# ---------------------------------------------------------------------------


class DeviceAccounter:
    """Tracks device instance usage on a node to detect oversubscription."""

    def __init__(self, node) -> None:
        # {device id string: {instance id: use count}}
        self.devices: Dict[str, Dict[str, int]] = {}
        for dev in node.node_resources.devices:
            self.devices[dev.id_string()] = {iid: 0 for iid in dev.available_ids()}

    def add_allocs(self, allocs) -> bool:
        """Returns True if a collision (oversubscription) was detected."""
        collision = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            if alloc.allocated_resources is None:
                continue
            for tr in alloc.allocated_resources.tasks.values():
                for dev in tr.devices:
                    instances = self.devices.get(dev.id_string())
                    if instances is None:
                        continue
                    for iid in dev.device_ids:
                        if iid in instances:
                            instances[iid] += 1
                            if instances[iid] > 1:
                                collision = True
        return collision

    def add_reserved(self, dev: AllocatedDeviceResource) -> bool:
        collision = False
        instances = self.devices.setdefault(dev.id_string(), {})
        for iid in dev.device_ids:
            count = instances.get(iid, 0)
            if count >= 1:
                collision = True
            instances[iid] = count + 1
        return collision

    def free_instances(self, dev_id: str) -> List[str]:
        return [iid for iid, n in self.devices.get(dev_id, {}).items() if n == 0]


# ---------------------------------------------------------------------------
# Fit + score math (reference nomad/structs/funcs.go)
# ---------------------------------------------------------------------------


def allocs_fit(
    node,
    allocs,
    net_idx: Optional[NetworkIndex] = None,
    check_devices: bool = False,
) -> Tuple[bool, str, ComparableResources]:
    """Check whether a set of allocations fits on a node.

    Mirrors reference funcs.go:166 AllocsFit: sums non-terminal alloc
    utilization, rejects reserved-core overlap, requires node resources
    (minus node-reserved) to be a superset, then checks port collisions
    via the NetworkIndex and optionally device oversubscription.
    Returns (fit, exhausted_dimension, used).
    """
    used = ComparableResources()
    reserved_cores = set()
    core_overlap = False
    any_ports = False
    any_devices = False

    for alloc in allocs:
        if alloc.terminal_status():
            continue
        cr, uses_ports, uses_devices = alloc.fit_meta()
        used.add(cr)
        any_ports |= uses_ports
        any_devices |= uses_devices
        for core in cr.reserved_cores:
            if core in reserved_cores:
                core_overlap = True
            reserved_cores.add(core)

    if core_overlap:
        return False, "cores", used

    available = node.comparable_resources()
    available.subtract(node.comparable_reserved_resources())
    ok, dim = available.superset(used)
    if not ok:
        return False, dim, used

    if net_idx is None and any_ports:
        # only build the port/bandwidth index when some proposed alloc
        # actually declares networks or ports — for port-less sets no
        # collision or bandwidth use is possible, and building the
        # index per node per plan dominated the applier's profile
        net_idx = NetworkIndex()
        collide, reason = net_idx.set_node(node)
        if collide:
            return False, f"reserved node port collision: {reason}", used
        collide, reason = net_idx.add_allocs(allocs)
        if collide:
            return False, f"reserved alloc port collision: {reason}", used

    if net_idx is not None and net_idx.overcommitted():
        return False, "bandwidth exceeded", used

    if check_devices and any_devices:
        accounter = DeviceAccounter(node)
        if accounter.add_allocs(allocs):
            return False, "device oversubscribed", used

    return True, "", used


def compute_free_percentage(node, util: ComparableResources) -> Tuple[float, float]:
    """Free cpu/mem fraction after `util` is placed (funcs.go:235)."""
    res = node.comparable_resources()
    reserved = node.comparable_reserved_resources()
    node_cpu = float(res.cpu_shares)
    node_mem = float(res.memory_mb)
    if reserved is not None:
        node_cpu -= float(reserved.cpu_shares)
        node_mem -= float(reserved.memory_mb)
    # Zero-capacity guard: Go's float division yields +/-Inf and the score
    # clamp absorbs it; Python raises. Treat a zero-capacity dimension as
    # fully utilized (free = 0) -- such nodes can never improve a score.
    free_pct_cpu = 1.0 - (float(util.cpu_shares) / node_cpu) if node_cpu > 0 else 0.0
    free_pct_ram = 1.0 - (float(util.memory_mb) / node_mem) if node_mem > 0 else 0.0
    return free_pct_cpu, free_pct_ram


def _clamp_score(score: float) -> float:
    if score > 18.0:
        return 18.0
    if score < 0.0:
        return 0.0
    return score


def score_fit_binpack(node, util: ComparableResources) -> float:
    """Best-fit score in [0, 18] (funcs.go:259): 20 - (10^fc + 10^fm)."""
    fc, fm = compute_free_percentage(node, util)
    total = math.pow(10, fc) + math.pow(10, fm)
    return _clamp_score(20.0 - total)


def score_fit_spread(node, util: ComparableResources) -> float:
    """Worst-fit score in [0, 18] (funcs.go:286): (10^fc + 10^fm) - 2."""
    fc, fm = compute_free_percentage(node, util)
    total = math.pow(10, fc) + math.pow(10, fm)
    return _clamp_score(total - 2.0)
