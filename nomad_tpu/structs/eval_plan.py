"""Evaluation, Plan, PlanResult, Deployment.

Reference behavior: nomad/structs/structs.go Evaluation (:10739),
Plan (:11120), PlanResult (:11375), Deployment/DeploymentState.
"""

from __future__ import annotations

import copy as _copy
import time as _time
import uuid as _uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nomad_tpu.structs.alloc import Allocation
from nomad_tpu.structs.consts import (
    ALLOC_DESIRED_EVICT,
    ALLOC_DESIRED_STOP,
    DEPLOYMENT_STATUS_RUNNING,
    EVAL_STATUS_CANCELLED,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    EVAL_STATUS_PENDING,
)


import random as _random
import threading as _threading

#: process-local RNG seeded from real entropy ONCE. ``uuid.uuid4``
#: reads os.urandom per call — an entropy syscall that costs ~0.5ms on
#: common container kernels, and the scheduling hot path mints an id
#: per allocation, per dequeue token, and per eval copy: at bench
#: batch sizes that was several milliseconds of wall per evaluation
#: spent in getrandom(2). These ids are resource NAMES — they need
#: uniqueness, not unpredictability; a 128-bit Mersenne draw seeded
#: from urandom keeps the collision odds identical in practice.
_UUID_RNG = _random.Random(_uuid.uuid4().int)
_UUID_LOCK = _threading.Lock()


def generate_uuid() -> str:
    with _UUID_LOCK:
        bits = _UUID_RNG.getrandbits(128)
    return str(_uuid.UUID(int=bits, version=4))


@dataclass
class Evaluation:
    """A request to (re)schedule a job (structs.go:10739)."""

    id: str = field(default_factory=generate_uuid)
    namespace: str = "default"
    priority: int = 50
    type: str = "service"           # scheduler type
    triggered_by: str = ""
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    deployment_id: str = ""
    status: str = EVAL_STATUS_PENDING
    status_description: str = ""
    wait_until_s: float = 0.0        # delayed eval (epoch seconds)
    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    related_evals: List[str] = field(default_factory=list)
    # tg -> {node_id} that failed placement; used by blocked-eval dedup
    failed_tg_allocs: Dict[str, object] = field(default_factory=dict)
    class_eligibility: Dict[str, bool] = field(default_factory=dict)
    escaped_computed_class: bool = False
    quota_limit_reached: str = ""
    queued_allocations: Dict[str, int] = field(default_factory=dict)
    annotate_plan: bool = False
    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    create_time_ns: int = 0
    modify_time_ns: int = 0
    leader_ack: str = ""             # broker token

    def terminal_status(self) -> bool:
        return self.status in (
            EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED, EVAL_STATUS_CANCELLED
        )

    def should_enqueue(self) -> bool:
        return self.status in (EVAL_STATUS_PENDING,)

    def should_block(self) -> bool:
        return self.status == "blocked"

    def make_plan(self, job) -> "Plan":
        """structs.go Evaluation.MakePlan."""
        return Plan(
            eval_id=self.id,
            priority=self.priority,
            job=job,
            all_at_once=bool(job and job.all_at_once),
        )

    def copy(self) -> "Evaluation":
        # targeted copy instead of deepcopy: the worker copies every
        # dequeued eval before mutating status (worker.py), so this
        # runs once per eval on the hot path. Scalars ride a shallow
        # copy; the four mutable containers are rebuilt; only
        # failed_tg_allocs holds nested mutable state (AllocMetric)
        # and is usually empty outside blocked evals.
        new = _copy.copy(self)
        new.related_evals = list(self.related_evals)
        new.class_eligibility = dict(self.class_eligibility)
        new.queued_allocations = dict(self.queued_allocations)
        new.failed_tg_allocs = {
            tg: _copy.deepcopy(m) for tg, m in self.failed_tg_allocs.items()
        }
        return new

    def create_blocked_eval(self, class_eligibility, escaped, quota_reached, failed_tg_allocs) -> "Evaluation":
        """structs.go Evaluation.CreateBlockedEval."""
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by="queued-allocs",
            job_id=self.job_id,
            status="blocked",
            previous_eval=self.id,
            class_eligibility=dict(class_eligibility or {}),
            escaped_computed_class=escaped,
            quota_limit_reached=quota_reached,
            failed_tg_allocs=dict(failed_tg_allocs or {}),
            # inherited so BlockedEvals' missed-unblock check compares
            # against the snapshot this eval was actually scheduled from
            snapshot_index=self.snapshot_index,
        )

    def create_failed_follow_up_eval(self, wait_s: float) -> "Evaluation":
        """``wait_s`` is a delay from now; wait_until_s stores absolute
        epoch seconds (structs.go CreateFailedFollowUpEval uses
        now.Add(wait))."""
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by="failed-follow-up",
            job_id=self.job_id,
            status=EVAL_STATUS_PENDING,
            wait_until_s=_time.time() + wait_s,
            previous_eval=self.id,
        )


@dataclass
class Plan:
    """The scheduler's proposed state mutation (structs.go:11120).

    Per-node lists keep the leader's plan applier able to re-validate each
    node independently (plan_apply.go:644).
    """

    eval_id: str = ""
    eval_token: str = ""
    priority: int = 50
    all_at_once: bool = False
    job: Optional[object] = None
    # node_id -> allocs to stop/evict on that node (with updated statuses)
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    # node_id -> new/updated allocs on that node
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    # node_id -> allocs preempted to make room
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    annotations: Optional["PlanAnnotations"] = None
    deployment: Optional["Deployment"] = None
    # deployment id -> status update
    deployment_updates: List[Dict] = field(default_factory=list)
    snapshot_index: int = 0
    #: deferred host-side post-processing (AllocMetric top-k
    #: materialization, scheduler/stack.py): thunks that must run
    #: before the plan is applied but NOT on the wave-critical eval
    #: path — the batching worker runs them inside its plan window,
    #: overlapping the next wave's execute. Never serialized.
    deferred_work: List = field(default_factory=list, repr=False,
                                compare=False)

    def run_deferred(self) -> None:
        """Run + drain the deferred post-processing (idempotent; every
        submit_plan entry point calls it, first caller does the
        work). Own span: this CPU runs inside the batching worker's
        plan window — overlapping the next wave's execute — so the
        decomposition attributes it as plan post-processing, not
        wave-critical scheduling."""
        if not self.deferred_work:
            return
        from nomad_tpu.telemetry.trace import tracer

        with tracer.span("plan.deferred"):
            while self.deferred_work:
                fn = self.deferred_work.pop()
                fn()

    def append_stopped_alloc(self, alloc: Allocation, desired_desc: str, client_status: str = "", follow_up_eval_id: str = "") -> None:
        """structs.go Plan.AppendStoppedAlloc."""
        new = alloc.copy_skip_job()
        new.desired_status = ALLOC_DESIRED_STOP
        new.desired_description = desired_desc
        if client_status:
            new.client_status = client_status
        if follow_up_eval_id:
            new.follow_up_eval_id = follow_up_eval_id
        self.node_update.setdefault(alloc.node_id, []).append(new)

    def append_alloc(self, alloc: Allocation, job=None) -> None:
        """structs.go Plan.AppendAlloc."""
        if job is not None:
            alloc.job = job
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_preempted_alloc(self, alloc: Allocation, preempting_alloc_id: str) -> None:
        """structs.go Plan.AppendPreemptedAlloc."""
        new = alloc.copy_skip_job()
        new.desired_status = ALLOC_DESIRED_EVICT
        new.preempted_by_allocation = preempting_alloc_id
        new.desired_description = f"Preempted by alloc ID {preempting_alloc_id}"
        self.node_preemptions.setdefault(alloc.node_id, []).append(new)

    def is_no_op(self) -> bool:
        return (
            not self.node_update
            and not self.node_allocation
            and self.deployment is None
            and not self.deployment_updates
        )


@dataclass
class PlanResult:
    """What the plan applier actually committed (structs.go:11375)."""

    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    deployment: Optional["Deployment"] = None
    deployment_updates: List[Dict] = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0

    def full_commit(self, plan: Plan):
        """Returns (fully_committed, expected, actual)."""
        expected = sum(len(v) for v in plan.node_allocation.values())
        actual = sum(len(v) for v in self.node_allocation.values())
        return expected == actual, expected, actual

    def is_no_op(self) -> bool:
        return (
            not self.node_update
            and not self.node_allocation
            and not self.deployment_updates
            and self.deployment is None
        )


@dataclass
class PlanAnnotations:
    """`job plan` dry-run annotations (structs.go PlanAnnotations)."""

    desired_tg_updates: Dict[str, "DesiredUpdates"] = field(default_factory=dict)
    preempted_allocs: List[Dict] = field(default_factory=list)


@dataclass
class DesiredUpdates:
    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0
    canary: int = 0
    preemptions: int = 0


@dataclass
class DeploymentState:
    """Per-task-group deployment progress (structs.go DeploymentState)."""

    placed_canaries: List[str] = field(default_factory=list)
    auto_revert: bool = False
    auto_promote: bool = False
    promoted: bool = False
    desired_canaries: int = 0
    desired_total: int = 0
    placed_allocs: int = 0
    healthy_allocs: int = 0
    unhealthy_allocs: int = 0
    progress_deadline_s: float = 600.0
    require_progress_by_s: float = 0.0


@dataclass
class Deployment:
    """A rolling update of a job version (structs.go Deployment)."""

    id: str = field(default_factory=generate_uuid)
    namespace: str = "default"
    job_id: str = ""
    job_version: int = 0
    job_modify_index: int = 0
    job_spec_modify_index: int = 0
    job_create_index: int = 0
    is_multiregion: bool = False
    task_groups: Dict[str, DeploymentState] = field(default_factory=dict)
    status: str = DEPLOYMENT_STATUS_RUNNING
    status_description: str = "Deployment is running"
    create_index: int = 0
    modify_index: int = 0

    def active(self) -> bool:
        return self.status in ("running", "paused", "blocked", "unblocking", "pending")

    def requires_promotion(self) -> bool:
        return any(
            s.desired_canaries > 0 and not s.promoted for s in self.task_groups.values()
        )

    def has_auto_promote(self) -> bool:
        return bool(self.task_groups) and all(
            s.auto_promote for s in self.task_groups.values() if s.desired_canaries > 0
        )

    def copy(self) -> "Deployment":
        return _copy.deepcopy(self)


def new_deployment(job) -> Deployment:
    """structs.go NewDeployment. Per-TG DeploymentState is populated by the
    reconciler as it computes placements, matching the reference."""
    d = Deployment(
        namespace=job.namespace,
        job_id=job.id,
        job_version=job.version,
        job_modify_index=job.modify_index,
        job_create_index=job.create_index,
        status="running",
    )
    return d
