"""Job diffs for `job plan` dry-runs.

Reference behavior: nomad/structs/diff.go (~1.4k LoC): JobDiff with
Type in {None, Added, Deleted, Edited}, flat field diffs, nested object
diffs, per-task-group and per-task breakdowns. Here a generic dataclass
walker produces the same shape; field names render in the wire form the
API uses (codec.wire_name).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

DIFF_NONE = "None"
DIFF_ADDED = "Added"
DIFF_DELETED = "Deleted"
DIFF_EDITED = "Edited"

# job fields that change on every registration and carry no spec meaning
_IGNORED_FIELDS = {
    "create_index", "modify_index", "job_modify_index", "version",
    "submit_time_ns", "status", "status_description", "stable",
}


def _wire(name: str) -> str:
    from nomad_tpu.api.codec import wire_name

    return wire_name(name)


def _scalar(v: Any) -> bool:
    return v is None or isinstance(v, (str, int, float, bool, bytes))


def _fmt(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def field_diffs(old: Any, new: Any, prefix: str = "") -> List[Dict]:
    """Flat field diffs between two same-type dataclasses (diff.go
    fieldDiffs). Nested dataclasses/collections are handled by
    object_diffs; this walks only scalars."""
    out: List[Dict] = []
    if old is None and new is None:
        return out
    sample = new if new is not None else old
    for f in dataclasses.fields(sample):
        if f.name in _IGNORED_FIELDS or f.name.startswith("_"):
            continue
        ov = getattr(old, f.name, None) if old is not None else None
        nv = getattr(new, f.name, None) if new is not None else None
        if not (_scalar(ov) and _scalar(nv)):
            continue
        if ov == nv:
            continue
        if old is None:
            typ = DIFF_ADDED
        elif new is None:
            typ = DIFF_DELETED
        else:
            typ = DIFF_EDITED
        out.append({
            "Type": typ,
            "Name": prefix + _wire(f.name),
            "Old": _fmt(ov),
            "New": _fmt(nv),
        })
    return out


def object_diff(old: Any, new: Any, name: str) -> Optional[Dict]:
    """Nested object diff (diff.go ObjectDiff): recursive over dataclass
    fields; returns None when identical."""
    if old is None and new is None:
        return None
    fields = field_diffs(old, new)
    objects: List[Dict] = []
    sample = new if new is not None else old
    if dataclasses.is_dataclass(sample):
        for f in dataclasses.fields(sample):
            if f.name in _IGNORED_FIELDS or f.name.startswith("_"):
                continue
            ov = getattr(old, f.name, None) if old is not None else None
            nv = getattr(new, f.name, None) if new is not None else None
            if dataclasses.is_dataclass(ov) or dataclasses.is_dataclass(nv):
                sub = object_diff(ov, nv, _wire(f.name))
                if sub is not None:
                    objects.append(sub)
            elif isinstance(ov, dict) or isinstance(nv, dict):
                sub_fields = _map_diffs(ov or {}, nv or {})
                if sub_fields:
                    objects.append({
                        "Type": DIFF_EDITED, "Name": _wire(f.name),
                        "Fields": sub_fields, "Objects": [],
                    })
    if not fields and not objects:
        return None
    if old is None:
        typ = DIFF_ADDED
    elif new is None:
        typ = DIFF_DELETED
    else:
        typ = DIFF_EDITED
    return {"Type": typ, "Name": name, "Fields": fields, "Objects": objects}


def _map_diffs(old: Dict, new: Dict) -> List[Dict]:
    out = []
    for k in sorted(set(old) | set(new)):
        ov, nv = old.get(k), new.get(k)
        if ov == nv or not (_scalar(ov) and _scalar(nv)):
            continue
        typ = DIFF_ADDED if k not in old else DIFF_DELETED if k not in new else DIFF_EDITED
        out.append({"Type": typ, "Name": str(k), "Old": _fmt(ov), "New": _fmt(nv)})
    return out


def task_diff(old, new, name: str) -> Optional[Dict]:
    d = object_diff(old, new, name)
    if d is None:
        return None
    d["Annotations"] = []
    return d


def task_group_diff(old, new, name: str) -> Optional[Dict]:
    """Per-task-group diff with nested per-task diffs (diff.go
    TaskGroupDiff)."""
    if old is None and new is None:
        return None
    fields = field_diffs(old, new)
    old_tasks = {t.name: t for t in (old.tasks if old is not None else [])}
    new_tasks = {t.name: t for t in (new.tasks if new is not None else [])}
    tasks = []
    for tname in sorted(set(old_tasks) | set(new_tasks)):
        td = task_diff(old_tasks.get(tname), new_tasks.get(tname), tname)
        if td is not None:
            tasks.append(td)
    objects = []
    for fname in ("update", "migrate", "reschedule_policy", "restart_policy",
                  "ephemeral_disk", "scaling"):
        ov = getattr(old, fname, None) if old is not None else None
        nv = getattr(new, fname, None) if new is not None else None
        sub = object_diff(ov, nv, _wire(fname))
        if sub is not None:
            objects.append(sub)
    if not fields and not tasks and not objects:
        return None
    typ = DIFF_ADDED if old is None else DIFF_DELETED if new is None else DIFF_EDITED
    return {
        "Type": typ, "Name": name, "Fields": fields, "Objects": objects,
        "Tasks": tasks, "Updates": {},
    }


def job_diff(old, new) -> Dict:
    """Top-level job diff (diff.go Job.Diff)."""
    if old is None and new is None:
        return {"Type": DIFF_NONE, "ID": "", "Fields": [], "Objects": [],
                "TaskGroups": []}
    fields = field_diffs(old, new)
    old_tgs = {tg.name: tg for tg in (old.task_groups if old is not None else [])}
    new_tgs = {tg.name: tg for tg in (new.task_groups if new is not None else [])}
    tgs = []
    for name in sorted(set(old_tgs) | set(new_tgs)):
        d = task_group_diff(old_tgs.get(name), new_tgs.get(name), name)
        if d is not None:
            tgs.append(d)
    objects = []
    for fname in ("periodic", "parameterized", "update"):
        ov = getattr(old, fname, None) if old is not None else None
        nv = getattr(new, fname, None) if new is not None else None
        sub = object_diff(ov, nv, _wire(fname))
        if sub is not None:
            objects.append(sub)
    if old is None:
        typ = DIFF_ADDED
    elif new is None:
        typ = DIFF_DELETED
    elif not fields and not tgs and not objects:
        typ = DIFF_NONE
    else:
        typ = DIFF_EDITED
    job_id = new.id if new is not None else old.id
    return {"Type": typ, "ID": job_id, "Fields": fields, "Objects": objects,
            "TaskGroups": tgs}
