"""Constraints, affinities, spreads and their host-side evaluation.

Reference behavior: nomad/structs/structs.go Constraint (:8581),
Affinity (:8701), Spread/SpreadTarget (:8787); operand evaluation in
scheduler/feasible.go resolveTarget (:770 area) and checkConstraint (:806).

These evaluations are inherently ragged (regex, version parses, string
compares), so they run host-side and are memoized per computed node class
(the eligibility-cache idea, feasible.go:1050); the results feed the device
kernel as boolean mask planes.
"""

from __future__ import annotations

import dataclasses
import re
from dataclasses import dataclass, field
from functools import lru_cache
from typing import List, Optional, Tuple

from nomad_tpu.structs.consts import (
    CONSTRAINT_ATTRIBUTE_IS_NOT_SET,
    CONSTRAINT_ATTRIBUTE_IS_SET,
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
    CONSTRAINT_REGEX,
    CONSTRAINT_SEMVER,
    CONSTRAINT_SET_CONTAINS,
    CONSTRAINT_SET_CONTAINS_ALL,
    CONSTRAINT_SET_CONTAINS_ANY,
    CONSTRAINT_VERSION,
)


@dataclass
class Constraint:
    """A hard placement constraint (structs.go:8581)."""

    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="

    def copy(self) -> "Constraint":
        return dataclasses.replace(self)

    def __str__(self) -> str:
        return f"{self.ltarget} {self.operand} {self.rtarget}"


@dataclass
class Affinity:
    """A soft placement preference with weight in [-100, 100] (structs.go:8701)."""

    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="
    weight: int = 50

    def copy(self) -> "Affinity":
        return dataclasses.replace(self)


@dataclass
class SpreadTarget:
    value: str = ""
    percent: int = 0


@dataclass
class Spread:
    """Spread allocations over an attribute's values (structs.go:8787)."""

    attribute: str = ""
    weight: int = 50
    spread_target: List[SpreadTarget] = field(default_factory=list)

    def copy(self) -> "Spread":
        return dataclasses.replace(
            self, spread_target=[dataclasses.replace(t) for t in self.spread_target]
        )


# ---------------------------------------------------------------------------
# Target resolution (feasible.go resolveTarget)
# ---------------------------------------------------------------------------


def resolve_target(target: str, node) -> Tuple[Optional[str], bool]:
    """Resolve an interpolated target like ``${attr.kernel.name}`` on a node."""
    if target == "${node.unique.id}":
        return node.id, True
    if target == "${node.datacenter}":
        return node.datacenter, True
    if target == "${node.unique.name}":
        return node.name, True
    if target == "${node.class}":
        return node.node_class, True
    if target.startswith("${attr."):
        attr = target[len("${attr."):].rstrip("}")
        val = node.attributes.get(attr)
        return (str(val), True) if val is not None else (None, False)
    if target.startswith("${meta."):
        meta = target[len("${meta."):].rstrip("}")
        val = node.meta.get(meta)
        return (str(val), True) if val is not None else (None, False)
    # Literal (RTarget values are usually literals)
    return target, True


# ---------------------------------------------------------------------------
# Version parsing (hashicorp/go-version behavior subset)
# ---------------------------------------------------------------------------


_VERSION_RE = re.compile(
    r"^v?(\d+(?:\.\d+)*)(?:-([0-9A-Za-z.-]+))?(?:\+[0-9A-Za-z.-]+)?$"
)


@lru_cache(maxsize=4096)
def parse_version(s: str) -> Optional[Tuple[Tuple[int, ...], Tuple[str, ...]]]:
    """Parse into (numeric segments padded to 3, prerelease ids) or None."""
    m = _VERSION_RE.match(s.strip())
    if not m:
        return None
    nums = tuple(int(x) for x in m.group(1).split("."))
    nums = (nums + (0, 0, 0))[:max(3, len(nums))]
    pre = tuple(m.group(2).split(".")) if m.group(2) else ()
    return nums, pre


def _cmp_version(a, b) -> int:
    an, ap = a
    bn, bp = b
    # Pad numeric segments to equal length
    ln = max(len(an), len(bn))
    an = an + (0,) * (ln - len(an))
    bn = bn + (0,) * (ln - len(bn))
    if an != bn:
        return -1 if an < bn else 1
    # A version without prerelease sorts AFTER one with (1.0.0 > 1.0.0-beta)
    if ap == bp:
        return 0
    if not ap:
        return 1
    if not bp:
        return -1
    for x, y in zip(ap, bp):
        xn, yn = x.isdigit(), y.isdigit()
        if xn and yn:
            xi, yi = int(x), int(y)
            if xi != yi:
                return -1 if xi < yi else 1
        elif xn != yn:
            return -1 if xn else 1  # numeric ids sort before alpha
        elif x != y:
            return -1 if x < y else 1
    return -1 if len(ap) < len(bp) else (1 if len(ap) > len(bp) else 0)


@lru_cache(maxsize=4096)
def _parse_version_constraints(spec: str) -> Optional[Tuple[Tuple[str, str], ...]]:
    """Parse a constraint set like ``>= 1.2, < 2.0`` or ``~> 1.2.3``."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.match(r"^(>=|<=|!=|~>|=|>|<)?\s*(.+)$", part)
        if not m:
            return None
        op = m.group(1) or "="
        out.append((op, m.group(2).strip()))
    return tuple(out)


def check_version_constraint(version_str: str, spec: str, semver: bool = False) -> bool:
    """Does ``version_str`` satisfy constraint set ``spec``?

    Mirrors feasible.go checkVersionMatch. ``semver=True`` treats
    prereleases per semver (a prerelease only satisfies explicit-equal).
    """
    v = parse_version(str(version_str))
    if v is None:
        return False
    constraints = _parse_version_constraints(spec)
    if not constraints:
        return False
    for op, rhs in constraints:
        rv = parse_version(rhs)
        if rv is None:
            return False
        if semver and v[1] and not rv[1]:
            # semver: prerelease versions don't satisfy non-prerelease ranges
            return False
        c = _cmp_version(v, rv)
        if op == "=" and c != 0:
            return False
        if op == "!=" and c == 0:
            return False
        if op == ">" and c <= 0:
            return False
        if op == ">=" and c < 0:
            return False
        if op == "<" and c >= 0:
            return False
        if op == "<=" and c > 0:
            return False
        if op == "~>":
            # pessimistic: >= rhs AND < next significant segment bump.
            # Significance = number of numeric segments actually written in
            # the rhs (from the parsed numeric group, not string sniffing,
            # so "v1.2.3" / "1.2.3+build" parse correctly).
            if c < 0:
                return False
            m = _VERSION_RE.match(rhs.strip())
            written = len(m.group(1).split(".")) if m else 2
            rhs_nums = rv[0]
            sig = max(2, min(written, len(rhs_nums)))
            upper = list(rhs_nums[: sig - 1])
            upper[-1] += 1
            uv = (tuple(upper), ())
            if _cmp_version(v, uv) >= 0:
                return False
    return True


@lru_cache(maxsize=1024)
def _compiled_regex(pattern: str):
    try:
        return re.compile(pattern)
    except re.error:
        return None


# ---------------------------------------------------------------------------
# Operand evaluation (feasible.go:806 checkConstraint)
# ---------------------------------------------------------------------------


def check_lexical_order(op: str, lval: str, rval: str) -> bool:
    if op == "<":
        return lval < rval
    if op == "<=":
        return lval <= rval
    if op == ">":
        return lval > rval
    if op == ">=":
        return lval >= rval
    return False


def check_set_contains_all(lval: str, rval: str) -> bool:
    have = {x.strip() for x in str(lval).split(",")}
    return all(x.strip() in have for x in str(rval).split(","))


def check_set_contains_any(lval: str, rval: str) -> bool:
    have = {x.strip() for x in str(lval).split(",")}
    return any(x.strip() in have for x in str(rval).split(","))


def check_constraint(operand: str, lval, rval, lfound: bool, rfound: bool) -> bool:
    """Evaluate one constraint operand (feasible.go:806).

    distinct_hosts / distinct_property pass here -- they are enforced by
    dedicated iterators (feasible.go:526,625 -> our scheduler.feasible).
    """
    if operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
        return True
    if operand in ("=", "==", "is"):
        return lfound and rfound and str(lval) == str(rval)
    if operand in ("!=", "not"):
        # Go: !reflect.DeepEqual(lVal, rVal) -- nil vs nil is equal,
        # nil vs value is not equal (feasible.go:823).
        if not lfound and not rfound:
            return False
        if lfound != rfound:
            return True
        return str(lval) != str(rval)
    if operand in ("<", "<=", ">", ">="):
        return lfound and rfound and check_lexical_order(operand, str(lval), str(rval))
    if operand == CONSTRAINT_ATTRIBUTE_IS_SET:
        return lfound
    if operand == CONSTRAINT_ATTRIBUTE_IS_NOT_SET:
        return not lfound
    if operand == CONSTRAINT_VERSION:
        return lfound and rfound and check_version_constraint(str(lval), str(rval), semver=False)
    if operand == CONSTRAINT_SEMVER:
        return lfound and rfound and check_version_constraint(str(lval), str(rval), semver=True)
    if operand == CONSTRAINT_REGEX:
        if not (lfound and rfound):
            return False
        pat = _compiled_regex(str(rval))
        return pat is not None and pat.search(str(lval)) is not None
    if operand in (CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL):
        return lfound and rfound and check_set_contains_all(lval, rval)
    if operand == CONSTRAINT_SET_CONTAINS_ANY:
        return lfound and rfound and check_set_contains_any(lval, rval)
    return False


def check_affinity(operand: str, lval, rval, lfound: bool, rfound: bool) -> bool:
    """Affinity matching delegates to constraint matching (feasible.go:846)."""
    return check_constraint(operand, lval, rval, lfound, rfound)


def matches_affinity(affinity: Affinity, node) -> bool:
    lval, lok = resolve_target(affinity.ltarget, node)
    rval, rok = resolve_target(affinity.rtarget, node)
    return check_affinity(affinity.operand, lval, rval, lok, rok)


def node_meets_constraints(node, constraints: List[Constraint]) -> bool:
    """All-of check used by the host-side ConstraintChecker (feasible.go:730)."""
    for c in constraints:
        lval, lok = resolve_target(c.ltarget, node)
        rval, rok = resolve_target(c.rtarget, node)
        if not check_constraint(c.operand, lval, rval, lok, rok):
            return False
    return True
