"""Namespace model (reference nomad/structs/structs.go Namespace;
state table schema.go namespaces)."""

from __future__ import annotations

import re
from dataclasses import dataclass, field


@dataclass
class Namespace:
    name: str = ""
    description: str = ""
    quota: str = ""
    meta: dict = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def validate(self) -> None:
        if not re.fullmatch(r"[a-zA-Z0-9-]{1,128}", self.name):
            raise ValueError(
                f"invalid namespace name '{self.name}': must be 1-128 "
                "alphanumeric or '-' characters"
            )


DEFAULT_NAMESPACE = Namespace(
    name="default", description="Default shared namespace"
)
