"""Native service-registration model.

Reference behavior: nomad/structs/service_registration.go -- the
``ServiceRegistration`` rows written by clients when tasks with
``provider = "nomad"`` services start (Nomad 1.3's built-in service
discovery), plus the list-stub grouping the /v1/services endpoint
returns. Service *definitions* (name, port label, checks) live on
Task/TaskGroup (structs/services.go Service, see structs/job.py); this
module is the registered-instance currency.
"""

from __future__ import annotations

import copy as _copy
from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class ServiceRegistration:
    """One live instance of a service (service_registration.go)."""

    id: str = ""
    service_name: str = ""
    namespace: str = "default"
    node_id: str = ""
    datacenter: str = ""
    job_id: str = ""
    alloc_id: str = ""
    tags: List[str] = field(default_factory=list)
    address: str = ""
    port: int = 0
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "ServiceRegistration":
        return _copy.deepcopy(self)

    def validate(self) -> None:
        if not self.id:
            raise ValueError("missing service registration ID")
        if not self.service_name:
            raise ValueError(f"registration {self.id}: missing service name")
        if not self.node_id:
            raise ValueError(f"registration {self.id}: missing node ID")

    def stub(self) -> Dict:
        return {
            "ID": self.id,
            "ServiceName": self.service_name,
            "Namespace": self.namespace,
            "NodeID": self.node_id,
            "Datacenter": self.datacenter,
            "JobID": self.job_id,
            "AllocID": self.alloc_id,
            "Tags": list(self.tags),
            "Address": self.address,
            "Port": self.port,
            "CreateIndex": self.create_index,
            "ModifyIndex": self.modify_index,
        }


def registration_id(service_name: str, alloc_id: str, task_name: str = "",
                    port_label: str = "") -> str:
    """Deterministic instance id (reference uses _nomad-task-<alloc>-
    <task>-<service>-<port label> as the Consul/Nomad service id; the
    port label keeps same-named services on one task distinct)."""
    parts = ["_nomad-task", alloc_id, task_name or "group", service_name,
             port_label]
    return "-".join(p for p in parts if p)
