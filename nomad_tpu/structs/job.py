"""Job -> TaskGroup -> Task tree and lifecycle policies.

Reference behavior: nomad/structs/structs.go Job (:4071), TaskGroup (:6122),
Task (:6904), UpdateStrategy, ReschedulePolicy, RestartPolicy,
MigrateStrategy, PeriodicConfig, EphemeralDisk, ScalingPolicy.
"""

from __future__ import annotations

import copy as _copy
import dataclasses
import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nomad_tpu.structs.consts import (
    JOB_DEFAULT_PRIORITY,
    JOB_STATUS_PENDING,
    JOB_TYPE_BATCH,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSBATCH,
    JOB_TYPE_SYSTEM,
)
from nomad_tpu.structs.constraints import Affinity, Constraint, Spread
from nomad_tpu.structs.network import NetworkResource
from nomad_tpu.structs.resources import Resources


@dataclass
class UpdateStrategy:
    """Rolling-update policy (structs.go UpdateStrategy)."""

    stagger_s: float = 30.0
    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0
    progress_deadline_s: float = 600.0
    auto_revert: bool = False
    auto_promote: bool = False
    canary: int = 0

    def is_empty(self) -> bool:
        return self.max_parallel == 0

    def copy(self) -> "UpdateStrategy":
        return dataclasses.replace(self)


@dataclass
class ReschedulePolicy:
    """Reschedule failed allocs onto other nodes (structs.go ReschedulePolicy)."""

    attempts: int = 0
    interval_s: float = 0.0
    delay_s: float = 30.0
    delay_function: str = "exponential"  # constant | exponential | fibonacci
    max_delay_s: float = 3600.0
    unlimited: bool = False

    def enabled(self) -> bool:
        return self.unlimited or (self.attempts > 0 and self.interval_s > 0)

    def copy(self) -> "ReschedulePolicy":
        return dataclasses.replace(self)


DEFAULT_SERVICE_RESCHEDULE = ReschedulePolicy(
    delay_s=30.0, delay_function="exponential", max_delay_s=3600.0, unlimited=True
)
DEFAULT_BATCH_RESCHEDULE = ReschedulePolicy(
    attempts=1, interval_s=24 * 3600.0, delay_s=5.0, delay_function="constant"
)


@dataclass
class RestartPolicy:
    """In-place restart policy executed by the client (structs.go RestartPolicy)."""

    attempts: int = 2
    interval_s: float = 1800.0
    delay_s: float = 15.0
    mode: str = "fail"  # fail | delay

    def copy(self) -> "RestartPolicy":
        return dataclasses.replace(self)


@dataclass
class MigrateStrategy:
    """Drain-driven migration pacing (structs.go MigrateStrategy)."""

    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0

    def copy(self) -> "MigrateStrategy":
        return dataclasses.replace(self)


@dataclass
class PeriodicConfig:
    """Cron-style launches (structs.go PeriodicConfig)."""

    enabled: bool = False
    spec: str = ""
    spec_type: str = "cron"
    prohibit_overlap: bool = False
    timezone: str = "UTC"

    def copy(self) -> "PeriodicConfig":
        return dataclasses.replace(self)


@dataclass
class ParameterizedJobConfig:
    """Dispatchable job template (structs.go ParameterizedJobConfig)."""

    payload: str = "optional"  # optional | required | forbidden
    meta_required: List[str] = field(default_factory=list)
    meta_optional: List[str] = field(default_factory=list)

    def copy(self) -> "ParameterizedJobConfig":
        return dataclasses.replace(
            self,
            meta_required=list(self.meta_required),
            meta_optional=list(self.meta_optional),
        )


@dataclass
class EphemeralDisk:
    size_mb: int = 300
    sticky: bool = False
    migrate: bool = False

    def copy(self) -> "EphemeralDisk":
        return dataclasses.replace(self)


@dataclass
class ScalingPolicy:
    """Autoscaler-facing policy (structs.go ScalingPolicy)."""

    id: str = ""
    type: str = "horizontal"
    target: Dict[str, str] = field(default_factory=dict)
    min: int = 0
    max: int = 0
    policy: Dict[str, object] = field(default_factory=dict)
    enabled: bool = True


@dataclass
class TaskLifecycleConfig:
    """init/prestart/poststart/poststop hooks (structs.go TaskLifecycleConfig)."""

    hook: str = ""  # prestart | poststart | poststop
    sidecar: bool = False


@dataclass
class LogConfig:
    max_files: int = 10
    max_file_size_mb: int = 10


@dataclass
class Template:
    source_path: str = ""
    dest_path: str = ""
    embedded_tmpl: str = ""
    change_mode: str = "restart"
    change_signal: str = ""


@dataclass
class Vault:
    """Task Vault block (structs.go Vault): which policies the task's
    derived token carries and how a new token is delivered."""

    policies: List[str] = field(default_factory=list)
    env: bool = True               # expose VAULT_TOKEN to the task
    change_mode: str = "restart"   # restart | signal | noop
    change_signal: str = ""


@dataclass
class Service:
    """Service registration + health checks (structs/services.go).

    ``connect`` is the service-mesh stanza (structs/services.go
    ConsulConnect): ``{"sidecar_service": {"proxy": {"upstreams":
    [{"destination_name": ..., "local_bind_port": ...}],
    "local_service_port": N}}}`` for sidecar-proxied services, or
    ``{"native": true}`` for connect-native workloads.
    """

    name: str = ""
    port_label: str = ""
    provider: str = "builtin"
    tags: List[str] = field(default_factory=list)
    checks: List[Dict] = field(default_factory=list)
    connect: Dict = field(default_factory=dict)

    # -- connect helpers (services.go ConsulConnect methods) -------------

    def has_sidecar(self) -> bool:
        return bool(self.connect.get("sidecar_service") is not None)

    def is_connect_native(self) -> bool:
        return bool(self.connect.get("native"))

    def sidecar_proxy(self) -> Dict:
        sc = self.connect.get("sidecar_service") or {}
        return sc.get("proxy") or {}

    def upstreams(self) -> List[Dict]:
        return list(self.sidecar_proxy().get("upstreams") or [])

    def mesh_port_label(self) -> str:
        """The dynamic port the scheduler assigns for the sidecar's
        public (mesh) listener (jobConnectHook's injected port)."""
        return f"connect-proxy-{self.name}"


@dataclass
class VolumeRequest:
    """Group-level host/CSI volume ask (structs.go VolumeRequest)."""

    name: str = ""
    type: str = "host"  # host | csi
    source: str = ""
    read_only: bool = False
    access_mode: str = ""
    attachment_mode: str = ""
    per_alloc: bool = False


@dataclass
class Task:
    """A single task run by a driver (structs.go:6904)."""

    name: str = ""
    driver: str = "mock"
    config: Dict[str, object] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    resources: Resources = field(default_factory=Resources)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    services: List[Service] = field(default_factory=list)
    meta: Dict[str, str] = field(default_factory=dict)
    kill_timeout_s: float = 5.0
    lifecycle: Optional[TaskLifecycleConfig] = None
    log_config: LogConfig = field(default_factory=LogConfig)
    templates: List[Template] = field(default_factory=list)
    artifacts: List[Dict] = field(default_factory=list)
    vault: Optional[Vault] = None
    leader: bool = False
    kill_signal: str = ""
    user: str = ""

    def copy(self) -> "Task":
        return _copy.deepcopy(self)


@dataclass
class TaskGroup:
    """A co-scheduled set of tasks (structs.go:6122)."""

    name: str = ""
    count: int = 1
    tasks: List[Task] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    # typed so the API codec decodes group networks into real
    # NetworkResource rows (an untyped List left them as wire dicts,
    # and connect admission then saw no bridge-mode network)
    networks: List[NetworkResource] = field(default_factory=list)
    volumes: Dict[str, VolumeRequest] = field(default_factory=dict)
    services: List[Service] = field(default_factory=list)
    restart_policy: RestartPolicy = field(default_factory=RestartPolicy)
    reschedule_policy: Optional[ReschedulePolicy] = None
    ephemeral_disk: EphemeralDisk = field(default_factory=EphemeralDisk)
    update: Optional[UpdateStrategy] = None
    migrate: Optional[MigrateStrategy] = None
    meta: Dict[str, str] = field(default_factory=dict)
    stop_after_client_disconnect_s: Optional[float] = None
    max_client_disconnect_s: Optional[float] = None
    scaling: Optional[ScalingPolicy] = None

    def lookup_task(self, name: str) -> Optional[Task]:
        for t in self.tasks:
            if t.name == name:
                return t
        return None

    def copy(self) -> "TaskGroup":
        return _copy.deepcopy(self)


@dataclass
class Job:
    """The unit of submission (structs.go:4071)."""

    id: str = ""
    name: str = ""
    namespace: str = "default"
    region: str = "global"
    type: str = JOB_TYPE_SERVICE
    priority: int = JOB_DEFAULT_PRIORITY
    datacenters: List[str] = field(default_factory=lambda: ["dc1"])
    node_pool: str = "default"
    all_at_once: bool = False
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    task_groups: List[TaskGroup] = field(default_factory=list)
    update: Optional[UpdateStrategy] = None
    periodic: Optional[PeriodicConfig] = None
    parameterized: Optional[ParameterizedJobConfig] = None
    payload: bytes = b""
    meta: Dict[str, str] = field(default_factory=dict)
    version: int = 0
    status: str = JOB_STATUS_PENDING
    stop: bool = False
    stable: bool = False
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0
    parent_id: str = ""
    dispatched: bool = False
    # {"strategy": {"max_parallel": N, "on_failure": "..."},
    #  "regions": [{"name", "count", "datacenters", "meta"}, ...]}
    # (structs.go:4133 Multiregion)
    multiregion: Optional[Dict] = None
    consul_token: str = ""
    vault_token: str = ""

    # -- multiregion helpers (structs.go Multiregion) --------------------

    def multiregion_regions(self) -> List[Dict]:
        if not self.multiregion:
            return []
        return list(self.multiregion.get("regions") or [])

    def multiregion_max_parallel(self) -> int:
        """0 means every region deploys at once (reference default)."""
        if not self.multiregion:
            return 0
        strategy = self.multiregion.get("strategy") or {}
        return int(strategy.get("max_parallel", 0) or 0)

    def multiregion_on_failure(self) -> str:
        """'' (downstream regions fail), 'fail_all', or 'fail_local'."""
        if not self.multiregion:
            return ""
        strategy = self.multiregion.get("strategy") or {}
        return str(strategy.get("on_failure", "") or "")

    def multiregion_region_index(self) -> int:
        """This job copy's position in the region rollout order."""
        for i, r in enumerate(self.multiregion_regions()):
            if str(r.get("name", "")) == self.region:
                return i
        return -1

    def multiregion_starts_blocked(self) -> bool:
        """Regions past the first max_parallel wave deploy blocked and
        wait for an earlier region's success to unblock them."""
        mp = self.multiregion_max_parallel()
        if mp <= 0:
            return False
        idx = self.multiregion_region_index()
        return idx >= mp

    def validate(self) -> List[str]:
        """structs.go Job.Validate: returns a list of validation error
        strings (empty = valid). Mirrors the reference's checks: ids,
        type, priority bounds, task-group/task structure and name
        uniqueness, periodic/parameterized exclusivity."""
        errs: List[str] = []
        if not self.id:
            errs.append("missing job ID")
        elif " " in self.id:
            errs.append("job ID contains a space")
        if not self.name:
            errs.append("missing job name")
        if self.type not in (JOB_TYPE_SERVICE, JOB_TYPE_BATCH,
                             JOB_TYPE_SYSTEM, JOB_TYPE_SYSBATCH):
            errs.append(f"invalid job type: {self.type!r}")
        if not 1 <= self.priority <= 100:
            errs.append(f"job priority must be between 1 and 100, "
                        f"got {self.priority}")
        if not self.datacenters:
            errs.append("job must specify at least one datacenter")
        if not self.task_groups:
            errs.append("missing job task groups")
            return errs   # nested checks need groups (null-safe)
        if self.periodic is not None and self.parameterized is not None:
            errs.append("job can't be both periodic and parameterized")
        seen = set()
        for i, tg in enumerate(self.task_groups):
            if tg is None:
                errs.append(f"task group {i + 1} is null")
                continue
            label = tg.name or f"task group {i + 1}"
            if not tg.name:
                errs.append(f"task group {i + 1} missing name")
            elif tg.name in seen:
                errs.append(f"duplicate task group name {tg.name!r}")
            seen.add(tg.name)
            if tg.count < 0:
                errs.append(f"group {label}: count must be >= 0")
            if self.type == JOB_TYPE_SYSTEM and tg.count > 1:
                errs.append(
                    f"group {label}: system jobs can't have a count > 1")
            if not tg.tasks:
                errs.append(f"group {label}: missing tasks")
            task_names = set()
            for j, task in enumerate(tg.tasks or []):
                if task is None:
                    errs.append(f"group {label}: task {j + 1} is null")
                    continue
                tlabel = task.name or f"task {j + 1}"
                if not task.name:
                    errs.append(f"group {label}: task {j + 1} missing name")
                elif task.name in task_names:
                    errs.append(
                        f"group {label}: duplicate task name {task.name!r}")
                task_names.add(task.name)
                if not task.driver:
                    errs.append(f"group {label}, task {tlabel}: "
                                "missing driver")
                res = task.resources
                if res is not None and (res.cpu < 0 or res.memory_mb < 0):
                    errs.append(f"group {label}, task {tlabel}: "
                                "resources must be non-negative")
        for c in self.constraints or []:
            if c is not None and not c.operand:
                errs.append("constraint missing operand")
        return errs

    def namespaced_id(self) -> str:
        return f"{self.namespace}@{self.id}"

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def is_periodic(self) -> bool:
        return self.periodic is not None and self.periodic.enabled

    def is_parameterized(self) -> bool:
        return self.parameterized is not None and not self.dispatched

    def is_system(self) -> bool:
        return self.type == JOB_TYPE_SYSTEM

    def stopped(self) -> bool:
        return self.stop

    def reschedule_policy_for(self, tg_name: str) -> ReschedulePolicy:
        tg = self.lookup_task_group(tg_name)
        if tg is not None and tg.reschedule_policy is not None:
            return tg.reschedule_policy
        if self.type == JOB_TYPE_BATCH:
            return DEFAULT_BATCH_RESCHEDULE.copy()
        return DEFAULT_SERVICE_RESCHEDULE.copy()

    def required_signals(self) -> Dict[str, Dict[str, List[str]]]:
        return {}

    def spec_hash(self) -> str:
        """Content hash used for change detection (no msgpack: repr-based)."""
        material = repr(
            (
                self.id,
                self.namespace,
                self.type,
                self.priority,
                tuple(self.datacenters),
                tuple(repr(tg) for tg in self.task_groups),
                tuple(repr(c) for c in self.constraints),
                tuple(repr(a) for a in self.affinities),
                tuple(repr(s) for s in self.spreads),
            )
        )
        return hashlib.sha256(material.encode()).hexdigest()

    def copy(self) -> "Job":
        return _copy.deepcopy(self)
