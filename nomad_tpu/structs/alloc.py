"""Allocation: the scheduling currency binding a job's task group to a node.

Reference behavior: nomad/structs/structs.go Allocation (:9468),
AllocMetric, TaskState, DesiredTransition, RescheduleTracker.
"""

from __future__ import annotations

import copy as _copy
import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nomad_tpu.structs.consts import (
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_LOST,
    ALLOC_CLIENT_PENDING,
    ALLOC_CLIENT_RUNNING,
    ALLOC_CLIENT_UNKNOWN,
    ALLOC_DESIRED_EVICT,
    ALLOC_DESIRED_RUN,
    ALLOC_DESIRED_STOP,
)
from nomad_tpu.structs.resources import AllocatedResources, ComparableResources


@dataclass
class TaskEvent:
    type: str = ""
    time_ns: int = 0
    message: str = ""
    details: Dict[str, str] = field(default_factory=dict)


@dataclass
class TaskState:
    """Client-reported per-task state (structs.go TaskState)."""

    state: str = "pending"  # pending | running | dead
    failed: bool = False
    restarts: int = 0
    last_restart_ns: int = 0
    started_at_ns: int = 0
    finished_at_ns: int = 0
    events: List[TaskEvent] = field(default_factory=list)

    def successful(self) -> bool:
        return self.state == "dead" and not self.failed


@dataclass
class AllocMetric:
    """Why/how a placement decision happened (structs.go AllocMetric).

    Stored on the Allocation; surfaced in `alloc status`. The TPU kernel
    fills nodes_evaluated/filtered/exhausted from mask population counts
    and scores from the top-k output -- the batched formulation gives these
    for free (a mask reduction) where Go tallies per-iterator.
    """

    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_in_pool: int = 0
    nodes_available: Dict[str, int] = field(default_factory=dict)  # per-DC
    class_filtered: Dict[str, int] = field(default_factory=dict)
    constraint_filtered: Dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: Dict[str, int] = field(default_factory=dict)
    dimension_exhausted: Dict[str, int] = field(default_factory=dict)
    quota_exhausted: List[str] = field(default_factory=list)
    # top-K node scores: [(node_id, {scorer: score}, final)]
    score_meta: List = field(default_factory=list)
    allocation_time_ns: int = 0
    coalesced_failures: int = 0

    def exhausted_node(self, node, dimension: str) -> None:
        self.nodes_exhausted += 1
        if node is not None and node.node_class:
            self.class_exhausted[node.node_class] = (
                self.class_exhausted.get(node.node_class, 0) + 1
            )
        if dimension:
            self.dimension_exhausted[dimension] = (
                self.dimension_exhausted.get(dimension, 0) + 1
            )

    def filter_node(self, node, constraint: str) -> None:
        self.nodes_filtered += 1
        if node is not None and node.node_class:
            self.class_filtered[node.node_class] = (
                self.class_filtered.get(node.node_class, 0) + 1
            )
        if constraint:
            self.constraint_filtered[constraint] = (
                self.constraint_filtered.get(constraint, 0) + 1
            )

    def copy(self) -> "AllocMetric":
        return _copy.deepcopy(self)


@dataclass
class DesiredTransition:
    """Server-desired transitions, e.g. drain migrations (structs.go)."""

    migrate: Optional[bool] = None
    reschedule: Optional[bool] = None
    force_reschedule: Optional[bool] = None

    def should_migrate(self) -> bool:
        return bool(self.migrate)

    def should_force_reschedule(self) -> bool:
        return bool(self.force_reschedule)


@dataclass
class RescheduleEvent:
    reschedule_time_ns: int = 0
    prev_alloc_id: str = ""
    prev_node_id: str = ""
    delay_s: float = 0.0


@dataclass
class RescheduleTracker:
    events: List[RescheduleEvent] = field(default_factory=list)

    def copy(self) -> "RescheduleTracker":
        return RescheduleTracker(events=[dataclasses.replace(e) for e in self.events])


@dataclass
class NetworkStatus:
    interface_name: str = ""
    address: str = ""
    dns: Optional[Dict] = None


@dataclass
class AllocDeploymentStatus:
    healthy: Optional[bool] = None
    timestamp_ns: int = 0
    canary: bool = False
    modify_index: int = 0

    def is_healthy(self) -> bool:
        return self.healthy is True

    def is_unhealthy(self) -> bool:
        return self.healthy is False


@dataclass
class Allocation:
    """One placement of a task group on a node (structs.go:9468)."""

    id: str = ""
    namespace: str = "default"
    eval_id: str = ""
    name: str = ""               # "<job>.<group>[<index>]"
    node_id: str = ""
    node_name: str = ""
    job_id: str = ""
    job: Optional[object] = None  # snapshot of the Job at placement time
    task_group: str = ""
    allocated_resources: Optional[AllocatedResources] = None
    metrics: Optional[AllocMetric] = None
    desired_status: str = ALLOC_DESIRED_RUN
    desired_description: str = ""
    desired_transition: DesiredTransition = field(default_factory=DesiredTransition)
    client_status: str = ALLOC_CLIENT_PENDING
    client_description: str = ""
    task_states: Dict[str, TaskState] = field(default_factory=dict)
    deployment_id: str = ""
    deployment_status: Optional[AllocDeploymentStatus] = None
    reschedule_tracker: Optional[RescheduleTracker] = None
    network_status: Optional[NetworkStatus] = None
    follow_up_eval_id: str = ""
    previous_allocation: str = ""
    next_allocation: str = ""
    preempted_allocations: List[str] = field(default_factory=list)
    preempted_by_allocation: str = ""
    create_index: int = 0
    modify_index: int = 0
    alloc_modify_index: int = 0
    create_time_ns: int = 0
    modify_time_ns: int = 0
    job_version: int = 0

    # -- status algebra (structs.go Allocation.TerminalStatus etc.) ------

    def terminal_status(self) -> bool:
        """Desired stop/evict, or client terminal, is terminal."""
        if self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            return True
        return self.client_terminal_status()

    def client_terminal_status(self) -> bool:
        return self.client_status in (
            ALLOC_CLIENT_COMPLETE,
            ALLOC_CLIENT_FAILED,
            ALLOC_CLIENT_LOST,
        )

    def server_terminal_status(self) -> bool:
        return self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT)

    def running_on_client(self) -> bool:
        return self.client_status in (ALLOC_CLIENT_PENDING, ALLOC_CLIENT_RUNNING)

    def is_unknown(self) -> bool:
        return self.client_status == ALLOC_CLIENT_UNKNOWN

    def comparable_resources(self) -> ComparableResources:
        return self.fit_meta()[0]

    def fit_meta(self):
        """(comparable, uses_ports, uses_devices), memoized against the
        ``allocated_resources`` object.

        The applier's per-node re-check (plan_apply.go:644) re-flattens
        every alloc on every touched node on every plan; the flattening
        dominated that path's profile. Resources are replaced (never
        mutated in place) when an alloc changes — the same convention
        the state store's usage planes rely on — so identity of the
        AllocatedResources object is a sound cache key. Callers must
        treat the returned ComparableResources as read-only (all
        in-tree callers do: they ``add`` it into an accumulator).
        """
        ar = self.allocated_resources
        cached = getattr(self, "_fit_meta_cache", None)
        if cached is not None and cached[0] is ar:
            return cached[1]
        if ar is None:
            meta = (ComparableResources(), False, False)
        else:
            cr = ar.comparable()
            meta = (
                cr,
                bool(cr.networks) or bool(ar.shared.ports),
                any(tr.devices for tr in ar.tasks.values()),
            )
        self._fit_meta_cache = (ar, meta)
        return meta

    def port_meta(self):
        """(port_mask, ok), memoized against ``allocated_resources``.

        ``port_mask`` is an int bitmap of every concrete port this
        alloc holds (task networks' reserved + dynamic ports, group
        shared ports — exactly the set NetworkIndex.add_allocs
        indexes) — the per-node reserved-port usage plane
        (state/usage.py) and the plan applier's vectorized port check
        (server/plan_apply.py) are built from it. ``ok`` is False when
        any port is out of range: the exact walk REJECTS such an alloc
        as a collision, which a bitmap cannot express, so consumers
        must fall back. Multi-address soundness (the same port on two
        node IPs) is a NODE property — the checker gates on the node's
        address count, not here.
        """
        ar = self.allocated_resources
        cached = getattr(self, "_port_meta_cache", None)
        if cached is not None and cached[0] is ar:
            return cached[1]
        mask = 0
        ok = True
        if ar is not None:
            # 0 <= port < network.MAX_VALID_PORT; a port listed twice
            # WITHIN the alloc collides with itself in the exact walk
            # (NetworkIndex sets bits one port at a time), which a
            # bitmap cannot express — not ok
            for tr in ar.tasks.values():
                for net in tr.networks:
                    for p in list(net.reserved_ports) + list(net.dynamic_ports):
                        if p.value < 0 or p.value >= 65536 \
                                or (mask >> p.value) & 1:
                            ok = False
                            continue
                        mask |= 1 << p.value
            for p in ar.shared.ports:
                if p.value < 0 or p.value >= 65536 or (mask >> p.value) & 1:
                    ok = False
                    continue
                mask |= 1 << p.value
        meta = (mask, ok)
        self._port_meta_cache = (ar, meta)
        return meta

    def __getstate__(self):
        """Allocs ride raft entries, snapshots, and the client state DB
        (pickle); derived scratch (the fit_meta memo) must not bloat
        those wire/disk payloads."""
        state = dict(self.__dict__)
        state.pop("_fit_meta_cache", None)
        state.pop("_port_meta_cache", None)
        state.pop("_index_cache", None)
        return state

    def index(self) -> int:
        """Alloc index parsed from Name "job.group[idx]" (structs.go).

        Memoized: the reconciler's name-index bitmaps and name-ordered
        walks re-parse the same immutable name several times per eval.
        """
        cached = getattr(self, "_index_cache", None)
        if cached is not None:
            return cached
        l = self.name.rfind("[")
        r = self.name.rfind("]")
        if l == -1 or r == -1 or r < l:
            idx = -1
        else:
            try:
                idx = int(self.name[l + 1 : r])
            except ValueError:
                idx = -1
        self._index_cache = idx
        return idx

    def job_namespaced_id(self) -> str:
        return f"{self.namespace}@{self.job_id}"

    def ran_successfully(self) -> bool:
        if not self.task_states:
            return False
        return all(ts.successful() for ts in self.task_states.values())

    def should_migrate(self) -> bool:
        return self.desired_transition.should_migrate()

    def next_reschedule_time(self, policy) -> Optional[float]:
        """Compute the delay-based next reschedule time in seconds-epoch.

        Reference structs.go Allocation.NextRescheduleTime + NextDelay:
        constant/exponential/fibonacci growth capped at max_delay.
        """
        if policy is None or not policy.enabled():
            return None
        num_prior = len(self.reschedule_tracker.events) if self.reschedule_tracker else 0
        delay = self._next_delay(policy, num_prior)
        base = self.modify_time_ns / 1e9
        return base + delay

    def _next_delay(self, policy, attempts: int) -> float:
        if policy.delay_function == "constant":
            return policy.delay_s
        if policy.delay_function == "exponential":
            delay = policy.delay_s * (2 ** attempts)
            return min(delay, policy.max_delay_s)
        if policy.delay_function == "fibonacci":
            a, b = policy.delay_s, policy.delay_s
            for _ in range(attempts):
                a, b = b, a + b
            return min(a, policy.max_delay_s)
        return policy.delay_s

    def reschedule_eligible(self, policy, fail_time_s: float) -> bool:
        """Whether this failed alloc may be rescheduled (structs.go
        Allocation.RescheduleEligible / ShouldReschedule)."""
        if policy is None or not policy.enabled():
            return False
        if policy.unlimited:
            return True
        if not self.reschedule_tracker or policy.attempts == 0:
            return policy.attempts > 0
        window_start = fail_time_s - policy.interval_s
        in_window = [
            e
            for e in self.reschedule_tracker.events
            if e.reschedule_time_ns / 1e9 >= window_start
        ]
        return len(in_window) < policy.attempts

    def copy(self) -> "Allocation":
        return _copy.deepcopy(self)

    def copy_skip_job(self) -> "Allocation":
        job = self.job
        self.job = None
        try:
            c = _copy.deepcopy(self)
        finally:
            self.job = job
        c.job = job
        return c

    def stub(self) -> Dict:
        return {
            "ID": self.id,
            "Name": self.name,
            "JobID": self.job_id,
            "NodeID": self.node_id,
            "TaskGroup": self.task_group,
            "DesiredStatus": self.desired_status,
            "ClientStatus": self.client_status,
            "DeploymentID": self.deployment_id,
        }


def remove_allocs(allocs: List[Allocation], remove: List[Allocation]) -> List[Allocation]:
    """structs.RemoveAllocs: filter out `remove` by ID."""
    rm = {a.id for a in remove}
    return [a for a in allocs if a.id not in rm]


def allocs_by_node(allocs: List[Allocation]) -> Dict[str, List[Allocation]]:
    out: Dict[str, List[Allocation]] = {}
    for a in allocs:
        out.setdefault(a.node_id, []).append(a)
    return out
