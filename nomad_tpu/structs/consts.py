"""Shared constants: statuses, trigger-bys, scheduler types, operands.

Reference: nomad/structs/structs.go (status/trigger constants are spread
through the Job/Node/Alloc/Eval definitions, e.g. structs.go:4071 area for
job statuses, :10739 area for eval statuses).
"""

# --- Scheduler types (reference scheduler/scheduler.go:24-38) ---
JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"
JOB_TYPE_SYSBATCH = "sysbatch"
JOB_TYPE_CORE = "_core"

# --- Job statuses ---
JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_DEAD = "dead"

# --- Node statuses / scheduling eligibility ---
NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"
NODE_STATUS_DISCONNECTED = "disconnected"
NODE_SCHEDULING_ELIGIBLE = "eligible"
NODE_SCHEDULING_INELIGIBLE = "ineligible"

# --- Alloc desired statuses ---
ALLOC_DESIRED_RUN = "run"
ALLOC_DESIRED_STOP = "stop"
ALLOC_DESIRED_EVICT = "evict"

# --- Alloc client statuses ---
ALLOC_CLIENT_PENDING = "pending"
ALLOC_CLIENT_RUNNING = "running"
ALLOC_CLIENT_COMPLETE = "complete"
ALLOC_CLIENT_FAILED = "failed"
ALLOC_CLIENT_LOST = "lost"
ALLOC_CLIENT_UNKNOWN = "unknown"

# --- Eval statuses (structs.go Evaluation) ---
EVAL_STATUS_BLOCKED = "blocked"
EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"
EVAL_STATUS_CANCELLED = "canceled"

# --- Eval trigger reasons ---
EVAL_TRIGGER_JOB_REGISTER = "job-register"
EVAL_TRIGGER_JOB_DEREGISTER = "job-deregister"
EVAL_TRIGGER_PERIODIC_JOB = "periodic-job"
EVAL_TRIGGER_NODE_DRAIN = "node-drain"
EVAL_TRIGGER_NODE_UPDATE = "node-update"
EVAL_TRIGGER_ALLOC_STOP = "alloc-stop"
EVAL_TRIGGER_SCHEDULED = "scheduled"
EVAL_TRIGGER_ROLLING_UPDATE = "rolling-update"
EVAL_TRIGGER_DEPLOYMENT_WATCHER = "deployment-watcher"
EVAL_TRIGGER_FAILED_FOLLOW_UP = "failed-follow-up"
EVAL_TRIGGER_MAX_DISCONNECT_TIMEOUT = "max-disconnect-timeout"
EVAL_TRIGGER_MAX_PLAN_ATTEMPTS = "max-plan-attempts"
EVAL_TRIGGER_RETRY_FAILED_ALLOC = "alloc-failure"
EVAL_TRIGGER_QUEUED_ALLOCS = "queued-allocs"
EVAL_TRIGGER_PREEMPTION = "preemption"
EVAL_TRIGGER_SCALING = "job-scaling"
EVAL_TRIGGER_RECONNECT = "reconnect"

# --- Constraint operands (structs.go:8581 area; scheduler/feasible.go:806) ---
CONSTRAINT_DISTINCT_HOSTS = "distinct_hosts"
CONSTRAINT_DISTINCT_PROPERTY = "distinct_property"
CONSTRAINT_REGEX = "regexp"
CONSTRAINT_VERSION = "version"
CONSTRAINT_SEMVER = "semver"
CONSTRAINT_SET_CONTAINS = "set_contains"
CONSTRAINT_SET_CONTAINS_ALL = "set_contains_all"
CONSTRAINT_SET_CONTAINS_ANY = "set_contains_any"
CONSTRAINT_ATTRIBUTE_IS_SET = "is_set"
CONSTRAINT_ATTRIBUTE_IS_NOT_SET = "is_not_set"

# --- Deployment statuses (structs.go Deployment) ---
DEPLOYMENT_STATUS_RUNNING = "running"
DEPLOYMENT_STATUS_PAUSED = "paused"
DEPLOYMENT_STATUS_FAILED = "failed"
DEPLOYMENT_STATUS_SUCCESSFUL = "successful"
DEPLOYMENT_STATUS_CANCELLED = "cancelled"
DEPLOYMENT_STATUS_BLOCKED = "blocked"
DEPLOYMENT_STATUS_UNBLOCKING = "unblocking"
DEPLOYMENT_STATUS_PENDING = "pending"

# --- Scheduler configuration ---
SCHEDULER_ALGORITHM_BINPACK = "binpack"
SCHEDULER_ALGORITHM_SPREAD = "spread"

# Priority bounds (structs.go JobMinPriority/JobDefaultPriority/JobMaxPriority)
JOB_MIN_PRIORITY = 1
JOB_DEFAULT_PRIORITY = 50
JOB_MAX_PRIORITY = 100
CORE_JOB_PRIORITY = 200

# Max score possible from the bin-packing fit function
# (reference scheduler/rank.go:13-16 binPackingMaxFitScore).
BINPACK_MAX_FIT_SCORE = 18.0
