"""Node model: fingerprint attributes, resources, computed class.

Reference behavior: nomad/structs/structs.go Node (:1851) and
nomad/structs/node_class.go (ComputedClass -- a hash over the scheduling-
relevant subset of the node used to memoize feasibility per class).
"""

from __future__ import annotations

import copy as _copy
import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nomad_tpu.structs.consts import (
    NODE_SCHEDULING_ELIGIBLE,
    NODE_STATUS_DOWN,
    NODE_STATUS_INIT,
    NODE_STATUS_READY,
)
from nomad_tpu.structs.resources import (
    ComparableResources,
    NodeReservedResources,
    NodeResources,
)


@dataclass
class DriverInfo:
    """Per-driver fingerprint result (structs.go DriverInfo)."""

    attributes: Dict[str, str] = field(default_factory=dict)
    detected: bool = False
    healthy: bool = False
    health_description: str = ""


@dataclass
class HostVolumeConfig:
    name: str = ""
    path: str = ""
    read_only: bool = False


@dataclass
class Node:
    """A client machine in the cluster (structs.go:1851)."""

    id: str = ""
    name: str = ""
    datacenter: str = "dc1"
    node_class: str = ""
    node_pool: str = "default"
    attributes: Dict[str, object] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    node_resources: NodeResources = field(default_factory=NodeResources)
    reserved_resources: NodeReservedResources = field(default_factory=NodeReservedResources)
    drivers: Dict[str, DriverInfo] = field(default_factory=dict)
    host_volumes: Dict[str, HostVolumeConfig] = field(default_factory=dict)
    csi_node_plugins: Dict[str, Dict] = field(default_factory=dict)
    csi_controller_plugins: Dict[str, Dict] = field(default_factory=dict)
    status: str = NODE_STATUS_INIT
    scheduling_eligibility: str = NODE_SCHEDULING_ELIGIBLE
    drain: bool = False
    drain_strategy: Optional["DrainStrategy"] = None
    status_description: str = ""
    http_addr: str = ""
    secret_id: str = ""
    create_index: int = 0
    modify_index: int = 0
    last_drain: Optional[Dict] = None
    computed_class: str = ""

    # -- scheduling-facing helpers ---------------------------------------

    def ready(self) -> bool:
        """structs.go Node.Ready: status ready, not draining, eligible."""
        return (
            self.status == NODE_STATUS_READY
            and not self.drain
            and self.scheduling_eligibility == NODE_SCHEDULING_ELIGIBLE
        )

    def comparable_resources(self) -> ComparableResources:
        return self.node_resources.comparable()

    def comparable_reserved_resources(self) -> Optional[ComparableResources]:
        return self.reserved_resources.comparable()

    def terminal_status(self) -> bool:
        return self.status == NODE_STATUS_DOWN

    def compute_class(self) -> str:
        """Hash the scheduling-relevant portions of the node.

        Reference node_class.go ComputeClass: nodes with equal computed
        class are interchangeable for *class-level* feasibility checks
        (constraints on attributes/class/drivers), which lets the
        eligibility cache (feasible.go:1050) skip whole classes. Unique
        attributes (``unique.``-prefixed) are excluded.
        """
        h = hashlib.sha256()
        # datacenter is class-relevant: ${node.datacenter} constraints
        # are checked per class representative (node_class.go hashes it)
        h.update(self.datacenter.encode())
        h.update(self.node_class.encode())
        h.update(self.node_pool.encode())
        for k in sorted(self.attributes):
            if k.startswith("unique."):
                continue
            h.update(k.encode())
            h.update(str(self.attributes[k]).encode())
        for k in sorted(self.meta):
            if k.startswith("unique."):
                continue
            h.update(k.encode())
            h.update(str(self.meta[k]).encode())
        for name in sorted(self.drivers):
            d = self.drivers[name]
            h.update(name.encode())
            h.update(b"1" if (d.detected and d.healthy) else b"0")
        for dev in self.node_resources.devices:
            h.update(dev.id_string().encode())
            for k in sorted(dev.attributes):
                h.update(k.encode())
                h.update(str(dev.attributes[k]).encode())
        self.computed_class = h.hexdigest()[:16]
        return self.computed_class

    def copy(self) -> "Node":
        return _copy.deepcopy(self)

    def stub(self) -> Dict:
        return {
            "ID": self.id,
            "Name": self.name,
            "Datacenter": self.datacenter,
            "NodeClass": self.node_class,
            "Status": self.status,
            "SchedulingEligibility": self.scheduling_eligibility,
            "Drain": self.drain,
        }


class DrainStrategy:
    """structs.go DrainStrategy/DrainSpec: how long a drain may take
    and whether system jobs are left alone."""

    def __init__(self, deadline_s: float = 3600.0,
                 ignore_system_jobs: bool = False) -> None:
        import time as _time
        self.deadline_s = deadline_s
        self.ignore_system_jobs = ignore_system_jobs
        self.started_at = _time.time()

    def deadline_passed(self) -> bool:
        import time as _time
        return self.deadline_s > 0 and \
            _time.time() > self.started_at + self.deadline_s
