"""Core data model (reference: nomad/structs/).

Python dataclasses for the orchestration currency -- Job/TaskGroup/Task,
Node, Allocation, Evaluation, Plan -- plus the resource math that the
scheduler kernel reproduces on device (reference nomad/structs/funcs.go).
"""

from nomad_tpu.structs.consts import *  # noqa: F401,F403
from nomad_tpu.structs.resources import (  # noqa: F401
    AllocatedCpuResources,
    AllocatedDeviceResource,
    AllocatedMemoryResources,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    ComparableResources,
    NodeCpuResources,
    NodeDeviceResource,
    NodeDiskResources,
    NodeMemoryResources,
    NodeReservedResources,
    NodeResources,
    RequestedDevice,
    Resources,
    allocs_fit,
    compute_free_percentage,
    score_fit_binpack,
    score_fit_spread,
)
from nomad_tpu.structs.network import (  # noqa: F401
    NetworkIndex,
    NetworkResource,
    Port,
    PortBitmap,
)
from nomad_tpu.structs.constraints import (  # noqa: F401
    Affinity,
    Constraint,
    Spread,
    SpreadTarget,
    check_constraint,
    resolve_target,
)
from nomad_tpu.structs.job import (  # noqa: F401
    EphemeralDisk,
    Job,
    MigrateStrategy,
    PeriodicConfig,
    ReschedulePolicy,
    RestartPolicy,
    ScalingPolicy,
    Service,
    Task,
    TaskGroup,
    TaskLifecycleConfig,
    UpdateStrategy,
    VolumeRequest,
)
from nomad_tpu.structs.csi import (  # noqa: F401
    CSIPlugin,
    CSIVolume,
    CSIVolumeCapability,
    CSIVolumeClaim,
)
from nomad_tpu.structs.node import DriverInfo, Node  # noqa: F401
from nomad_tpu.structs.alloc import (  # noqa: F401
    AllocMetric,
    Allocation,
    DesiredTransition,
    RescheduleEvent,
    RescheduleTracker,
    TaskEvent,
    TaskState,
)
from nomad_tpu.structs.eval_plan import (  # noqa: F401
    Deployment,
    DeploymentState,
    Evaluation,
    Plan,
    PlanAnnotations,
    PlanResult,
)
