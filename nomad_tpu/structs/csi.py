"""CSI volume and plugin models.

Reference behavior: nomad/structs/csi.go (~1.5k LoC) -- the
Container-Storage-Interface data model: ``CSIVolume`` (a registered
external volume with access/attachment capabilities and live claims),
``CSIPlugin`` (the aggregated health view of a plugin's controller and
node instances across the cluster), and the claim state machine the
volume watcher drives (claim → unpublish node → unpublish controller →
free). Claim-mode admission mirrors csi.go ``CSIVolume.WriteSchedulable``
/ ``claimWrite`` / ``claimRead``.
"""

from __future__ import annotations

import copy as _copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Access modes (csi.go CSIVolumeAccessMode)
ACCESS_MODE_UNKNOWN = ""
ACCESS_MODE_SINGLE_NODE_READER = "single-node-reader-only"
ACCESS_MODE_SINGLE_NODE_WRITER = "single-node-writer"
ACCESS_MODE_MULTI_NODE_READER = "multi-node-reader-only"
ACCESS_MODE_MULTI_NODE_SINGLE_WRITER = "multi-node-single-writer"
ACCESS_MODE_MULTI_NODE_MULTI_WRITER = "multi-node-multi-writer"

# Attachment modes (csi.go CSIVolumeAttachmentMode)
ATTACHMENT_MODE_UNKNOWN = ""
ATTACHMENT_MODE_BLOCK = "block-device"
ATTACHMENT_MODE_FS = "file-system"

# Claim modes (csi.go CSIVolumeClaimMode)
CLAIM_READ = "read"
CLAIM_WRITE = "write"
CLAIM_RELEASE = "release"

# Claim states (csi.go CSIVolumeClaimState) -- the unpublish workflow
# the volume watcher steps through, in order.
CLAIM_STATE_TAKEN = "taken"
CLAIM_STATE_NODE_DETACHED = "node-detached"
CLAIM_STATE_CONTROLLER_DETACHED = "controller-detached"
CLAIM_STATE_READY_TO_FREE = "ready-to-free"

# Plugin instance health (csi.go CSIInfo)


@dataclass
class CSIVolumeClaim:
    """One alloc's claim on a volume (csi.go CSIVolumeClaim)."""

    alloc_id: str = ""
    node_id: str = ""
    external_node_id: str = ""
    mode: str = CLAIM_READ
    access_mode: str = ACCESS_MODE_UNKNOWN
    attachment_mode: str = ATTACHMENT_MODE_UNKNOWN
    state: str = CLAIM_STATE_TAKEN
    # where the claiming node actually staged/published the volume;
    # recorded at claim time so the server-side unpublish workflow
    # releases the same paths (reference keeps these in the client's
    # csimanager usage state)
    staging_path: str = ""
    target_path: str = ""

    def copy(self) -> "CSIVolumeClaim":
        return _copy.deepcopy(self)

    def release_copy(self, state: str = CLAIM_STATE_TAKEN) -> "CSIVolumeClaim":
        """A release-mode copy at the given unpublish state (the claim
        transition currency of the volume watcher / claim GC)."""
        rel = self.copy()
        rel.mode = CLAIM_RELEASE
        rel.state = state
        return rel


@dataclass
class CSIMountOptions:
    """csi.go CSIMountOptions."""

    fs_type: str = ""
    mount_flags: List[str] = field(default_factory=list)


@dataclass
class CSIVolumeCapability:
    """One (access, attachment) capability pair (csi.go
    CSIVolumeCapability; volumes may list several since 1.1)."""

    access_mode: str = ACCESS_MODE_UNKNOWN
    attachment_mode: str = ATTACHMENT_MODE_UNKNOWN


@dataclass
class CSIVolume:
    """A registered external volume (csi.go CSIVolume)."""

    id: str = ""
    namespace: str = "default"
    name: str = ""
    external_id: str = ""
    plugin_id: str = ""
    provider: str = ""
    capacity_min: int = 0
    capacity_max: int = 0
    requested_capabilities: List[CSIVolumeCapability] = field(default_factory=list)
    mount_options: CSIMountOptions = field(default_factory=CSIMountOptions)
    secrets: Dict[str, str] = field(default_factory=dict)
    parameters: Dict[str, str] = field(default_factory=dict)
    context: Dict[str, str] = field(default_factory=dict)
    topologies: List[Dict[str, str]] = field(default_factory=list)
    # live claims keyed by alloc id (csi.go ReadClaims/WriteClaims)
    read_claims: Dict[str, CSIVolumeClaim] = field(default_factory=dict)
    write_claims: Dict[str, CSIVolumeClaim] = field(default_factory=dict)
    # claims released by the scheduler but not yet unpublished
    # (csi.go PastClaims), keyed by alloc id
    past_claims: Dict[str, CSIVolumeClaim] = field(default_factory=dict)
    schedulable: bool = True
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "CSIVolume":
        return _copy.deepcopy(self)

    def validate(self) -> None:
        if not self.id:
            raise ValueError("missing volume ID")
        if not self.plugin_id:
            raise ValueError(f"volume {self.id}: missing plugin ID")
        if not self.requested_capabilities:
            raise ValueError(
                f"volume {self.id}: must include at least one capability block"
            )

    # --- claim admission (csi.go WriteSchedulable/ReadSchedulable) ------

    def _has_capability(self, access_modes: List[str]) -> bool:
        return any(
            c.access_mode in access_modes for c in self.requested_capabilities
        )

    def read_schedulable(self) -> bool:
        if not self.schedulable:
            return False
        return self._has_capability([
            ACCESS_MODE_SINGLE_NODE_READER,
            ACCESS_MODE_SINGLE_NODE_WRITER,
            ACCESS_MODE_MULTI_NODE_READER,
            ACCESS_MODE_MULTI_NODE_SINGLE_WRITER,
            ACCESS_MODE_MULTI_NODE_MULTI_WRITER,
        ])

    def write_schedulable(self) -> bool:
        if not self.schedulable:
            return False
        return self._has_capability([
            ACCESS_MODE_SINGLE_NODE_WRITER,
            ACCESS_MODE_MULTI_NODE_SINGLE_WRITER,
            ACCESS_MODE_MULTI_NODE_MULTI_WRITER,
        ])

    def write_freely(self) -> bool:
        """Can accept an additional writer right now (csi.go WriteFreeClaims)."""
        if self._has_capability([ACCESS_MODE_MULTI_NODE_MULTI_WRITER]):
            return True
        return len(self.write_claims) == 0

    def read_freely(self) -> bool:
        """Can accept an additional reader right now."""
        if self._has_capability([
            ACCESS_MODE_MULTI_NODE_READER,
            ACCESS_MODE_MULTI_NODE_SINGLE_WRITER,
            ACCESS_MODE_MULTI_NODE_MULTI_WRITER,
        ]):
            return True
        return len(self.read_claims) + len(self.write_claims) == 0

    def claimable(self, mode: str) -> bool:
        if mode == CLAIM_WRITE:
            return self.write_schedulable() and self.write_freely()
        return self.read_schedulable() and self.read_freely()

    def claim(self, claim: CSIVolumeClaim) -> None:
        """Apply one claim transition (csi.go Claim). Raises on a write
        claim the volume cannot accept."""
        if claim.mode == CLAIM_RELEASE:
            self._release(claim)
            return
        # re-claim by the same alloc is idempotent
        if claim.alloc_id in self.read_claims:
            del self.read_claims[claim.alloc_id]
        if claim.alloc_id in self.write_claims:
            del self.write_claims[claim.alloc_id]
        if claim.mode == CLAIM_WRITE:
            if not self.write_freely() and claim.alloc_id not in self.write_claims:
                raise ValueError(
                    f"volume {self.id} max write claims reached"
                )
            self.write_claims[claim.alloc_id] = claim
        else:
            self.read_claims[claim.alloc_id] = claim
        self.past_claims.pop(claim.alloc_id, None)

    def _release(self, claim: CSIVolumeClaim) -> None:
        if claim.state == CLAIM_STATE_READY_TO_FREE:
            self.read_claims.pop(claim.alloc_id, None)
            self.write_claims.pop(claim.alloc_id, None)
            self.past_claims.pop(claim.alloc_id, None)
        else:
            self.read_claims.pop(claim.alloc_id, None)
            self.write_claims.pop(claim.alloc_id, None)
            self.past_claims[claim.alloc_id] = claim

    def in_use(self) -> bool:
        return bool(self.read_claims or self.write_claims)

    def stub(self) -> Dict:
        """List-view summary in wire casing (csi.go CSIVolListStub)."""
        return {
            "ID": self.id,
            "Namespace": self.namespace,
            "Name": self.name,
            "ExternalID": self.external_id,
            "PluginID": self.plugin_id,
            "Provider": self.provider,
            "Schedulable": self.schedulable,
            "CurrentReaders": len(self.read_claims),
            "CurrentWriters": len(self.write_claims),
            "AccessMode": (
                self.requested_capabilities[0].access_mode
                if self.requested_capabilities else ""
            ),
            "AttachmentMode": (
                self.requested_capabilities[0].attachment_mode
                if self.requested_capabilities else ""
            ),
            "CreateIndex": self.create_index,
            "ModifyIndex": self.modify_index,
        }


@dataclass
class CSIPlugin:
    """Aggregated plugin health across the cluster (csi.go CSIPlugin).

    The reference maintains this as a state table updated whenever a
    node fingerprint changes (state_store.go updateNodeCSIPlugins); the
    build derives it from the nodes table on read, which keeps it
    trivially consistent with fingerprints (same approach as the
    scaling-policies view).
    """

    id: str = ""
    provider: str = ""
    version: str = ""
    controller_required: bool = False
    # node_id -> info dict (healthy, requires_topology, ...)
    controllers: Dict[str, Dict] = field(default_factory=dict)
    nodes: Dict[str, Dict] = field(default_factory=dict)

    @property
    def controllers_healthy(self) -> int:
        return sum(1 for i in self.controllers.values() if i.get("healthy"))

    @property
    def nodes_healthy(self) -> int:
        return sum(1 for i in self.nodes.values() if i.get("healthy"))

    def stub(self) -> Dict:
        return {
            "ID": self.id,
            "Provider": self.provider,
            "ControllerRequired": self.controller_required,
            "ControllersHealthy": self.controllers_healthy,
            "ControllersExpected": len(self.controllers),
            "NodesHealthy": self.nodes_healthy,
            "NodesExpected": len(self.nodes),
        }


def plugins_from_nodes(nodes) -> Dict[str, CSIPlugin]:
    """Derive the plugin table from node fingerprints
    (state_store.go updateNodeCSIPlugins semantics)."""
    plugins: Dict[str, CSIPlugin] = {}

    def get(pid: str, info: Dict) -> CSIPlugin:
        p = plugins.get(pid)
        if p is None:
            p = CSIPlugin(id=pid)
            plugins[pid] = p
        if info.get("provider"):
            p.provider = info["provider"]
        if info.get("version"):
            p.version = info["version"]
        return p

    for node in nodes:
        for pid, info in (node.csi_controller_plugins or {}).items():
            p = get(pid, info)
            p.controller_required = True
            p.controllers[node.id] = info
        for pid, info in (node.csi_node_plugins or {}).items():
            p = get(pid, info)
            p.nodes[node.id] = info
    return plugins
