"""Raft consensus node: election, replication, commit, FSM apply.

Reference behavior: hashicorp/raft v1.3.5 as wired by nomad
(server.go:1228 setupRaft, fsm.go): every authoritative mutation is a
log entry; the FSM applies committed entries in order; leadership
changes drive nomad's establishLeadership/revokeLeadership
(leader.go:54). This is a from-scratch implementation of the standard
algorithm (election timeout randomization, AppendEntries consistency
check, majority commit with current-term guard, InstallSnapshot for
lagging followers).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from nomad_tpu.raft.log import LOG_COMMAND, LOG_NOOP, LogEntry, LogStore
from nomad_tpu.raft.observe import raft_observer
from nomad_tpu.telemetry.histogram import (
    RAFT_APPEND,
    RAFT_ELECTION,
    RAFT_QUORUM,
    RAFT_REPLICATION,
    RAFT_SNAPSHOT_XFER,
    histograms,
)
from nomad_tpu.telemetry.trace import consensus_recorder, tracer
from nomad_tpu.utils.faultpoints import FaultError, fault
from nomad_tpu.utils.witness import witness_lock

# reserved msg_types for replicated membership changes, handled by the
# raft layer itself instead of the FSM (hashicorp/raft
# RemoveServer/AddVoter)
RAFT_REMOVE_PEER = "__RaftRemovePeerConfigChange__"
RAFT_ADD_PEER = "__RaftAddPeerConfigChange__"

LOG = logging.getLogger(__name__)

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class NotLeaderError(Exception):
    def __init__(self, leader: Optional[str]) -> None:
        super().__init__(f"not leader; leader is {leader}")
        self.leader = leader


class RaftConfig:
    def __init__(
        self,
        heartbeat_interval: float = 0.05,
        election_timeout_min: float = 0.15,
        election_timeout_max: float = 0.30,
        max_append_entries: int = 64,
        snapshot_threshold: int = 8192,
        max_in_flight: int = 8,
        leader_lease: bool = True,
        lease_fraction: float = 0.75,
    ) -> None:
        self.heartbeat_interval = heartbeat_interval
        self.election_timeout_min = election_timeout_min
        self.election_timeout_max = election_timeout_max
        self.max_append_entries = max_append_entries
        self.snapshot_threshold = snapshot_threshold
        #: AppendEntries batches a per-peer replicator may keep in
        #: flight before waiting for acks (hashicorp/raft's pipeline);
        #: 1 disables pipelining entirely — the replicator then runs
        #: the original synchronous send->ack->send path, bit for bit
        self.max_in_flight = max_in_flight
        #: clock-based leader lease: a quorum of AppendEntries acks
        #: within ``election_timeout_min * lease_fraction`` of their
        #: SEND stamps lets leader-side linearizable reads skip the
        #: barrier round-trip. Safety leans on the paired follower
        #: rule: no vote against a live leader within
        #: election_timeout_min of its last contact (raft §6), so a
        #: deposed leader's lease always expires before its successor
        #: can win — as long as clock RATES stay within the
        #: 1 - lease_fraction margin (offsets don't matter, both
        #: sides measure durations)
        self.leader_lease = leader_lease
        self.lease_fraction = lease_fraction


class _ApplyFuture:
    def __init__(self, index: int) -> None:
        self.index = index
        self._done = threading.Event()
        self.result: Any = None
        self.error: Optional[Exception] = None

    def respond(self, result: Any, error: Optional[Exception]) -> None:
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout: Optional[float]) -> Any:
        if not self._done.wait(timeout):
            raise TimeoutError("apply timeout")
        if self.error is not None:
            raise self.error
        return self.result


class RaftNode:
    def __init__(
        self,
        node_id: str,
        peers: List[str],
        transport,
        fsm_apply: Callable[[str, Dict], Any],
        fsm_apply_batch: Optional[
            Callable[[List[Tuple[str, Dict]]], List]] = None,
        config: Optional[RaftConfig] = None,
        snapshot_fn: Optional[Callable[[], bytes]] = None,
        restore_fn: Optional[Callable[[bytes], None]] = None,
        on_leader: Optional[Callable[[], None]] = None,
        on_follower: Optional[Callable[[], None]] = None,
        log_store: Optional[LogStore] = None,
        data_dir: Optional[str] = None,
        fsync_policy: str = "batch",
    ) -> None:
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.transport = transport
        transport.set_handler(self._handle_rpc)
        self.fsm_apply = fsm_apply
        # optional batched FSM doorway: the apply loop hands a whole
        # committed run of plain commands to one call (one FSM-lock +
        # store-root-swap span on the other side); absent, it falls
        # back to per-entry fsm_apply inside the same batch drain
        self.fsm_apply_batch = fsm_apply_batch
        self.config = config or RaftConfig()
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.on_leader = on_leader
        self.on_follower = on_follower

        # witness-created (PR 9): the stress tier checks the pipeline
        # window bookkeeping below for lock-order inversions
        self._lock = witness_lock("raft_node", rlock=True)
        self.state = FOLLOWER
        self.current_term = 0
        self.voted_for: Optional[str] = None
        # crash-safe durability plane (raft/wal.py, ISSUE 13). With a
        # data_dir this node recovers its HARD state from disk in
        # strict order: stable store (term/vote — a node that forgets
        # its vote can vote twice in one term, a raft SAFETY
        # violation) -> newest valid snapshot -> restore_fn -> WAL
        # replay into the log. Committed replayed entries re-apply
        # into the FSM through the normal apply loop once the commit
        # index advances (leader election / AppendEntries).
        self._stable = None
        self._snapshots = None
        self._durable = bool(data_dir)
        self.recovered_snapshot_index = 0
        self.replayed_entries = 0
        if data_dir:
            from nomad_tpu.raft import wal as _wal

            os.makedirs(data_dir, exist_ok=True)
            self._stable = _wal.StableStore(data_dir, owner=node_id)
            self.current_term, self.voted_for = self._stable.load()
            self._snapshots = _wal.SnapshotStore(data_dir, owner=node_id)
            snap = self._snapshots.load_newest()
            if snap is not None and self.restore_fn is not None:
                self.recovered_snapshot_index = snap[0]
                self.restore_fn(snap[2])
            store = _wal.DurableLogStore(
                os.path.join(data_dir, "wal"), fsync_policy=fsync_policy,
                owner=node_id)
            self.replayed_entries = store.replayed_entries
            if snap is not None and store.base_index() < snap[0]:
                # crash between snapshot write and the compact record:
                # the snapshot is authoritative for everything <= its
                # index, so compact the replayed log up to it
                store.compact_to(snap[0], snap[1])
            if (snap is None or snap[0] < store.base_index()) \
                    and store.base_index() > 0:
                # no snapshot at all, OR only an OLDER fallback (the
                # newest failed its CRC): either way the span up to
                # the compacted base cannot be reconstructed
                have = "no valid snapshot" if snap is None else \
                    f"newest valid snapshot is only {snap[0]}"
                raise _wal.WalCorruptionError(
                    f"{node_id}: log compacted to {store.base_index()} "
                    f"but {have} — the state below the base is "
                    "unrecoverable (refusing to boot with silent "
                    "data loss)")
            log_store = store
            _wal.wal_stats.note_recovery(node_id)
            if self.replayed_entries or snap is not None:
                LOG.info(
                    "%s: recovered from %s (term=%d vote=%s "
                    "snapshot=%d wal_entries=%d)", node_id, data_dir,
                    self.current_term, self.voted_for,
                    self.recovered_snapshot_index, self.replayed_entries)
        self.log = log_store or LogStore()
        # everything at or below the base was snapshotted from applied
        # state: committed by definition
        self.commit_index = self.log.base_index()
        self.last_applied = self.log.base_index()
        self.leader_id: Optional[str] = None
        self._last_contact = time.monotonic()
        self._votes = 0
        # set when a committed config change removed this node
        self._removed = False

        # leader volatile state
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        # last successful RPC round-trip per peer (autopilot's
        # last-contact health signal)
        self.peer_last_contact: Dict[str, float] = {}

        # --- pipelined replication (ISSUE 18) --------------------------
        # Per-peer window state, all under self._lock. A peer's
        # pipeline arms (_pipe_ok) only after a successful synchronous
        # ack proved next_index correct; any failure, term change,
        # conflict backoff, or snapshot need DRAINS the window (epoch
        # bump discards in-flight acks) and falls back to the sync
        # path. Acks are processed strictly in send order (_pipe_seq /
        # _pipe_ack_turn) so match_index/commit advance per batch
        # exactly as the synchronous path would.
        self._pipe_ok: Dict[str, bool] = {}
        self._pipe_epoch: Dict[str, int] = {}
        self._pipe_seq: Dict[str, int] = {}
        self._pipe_ack_turn: Dict[str, int] = {}
        self._pipe_inflight: Dict[str, int] = {}
        #: speculative send frontier — entries below it are in flight
        self._pipe_next: Dict[str, int] = {}
        self._pipe_cond = threading.Condition(self._lock)
        self._pipe_batches = 0
        self._pipe_drains = 0
        # per-peer wire turnstile: concurrent sender threads overlap
        # their TRANSIT (the fault seam's injected latency sleeps
        # concurrently) but hit the transport strictly in sequence
        # order — the ordered-stream property a TCP pipeline gets for
        # free, without which scheduler jitter reorders arrivals at
        # the follower and every reorder costs a conflict + drain.
        # LEAF under raft_node: _pipe_drain_locked mirrors the epoch
        # into it while holding self._lock; senders never take
        # self._lock while holding it
        self._wire_lock = witness_lock("raft_pipe_wire")
        self._wire_cond = threading.Condition(self._wire_lock)
        self._wire_turn: Dict[str, int] = {}
        self._wire_epoch: Dict[str, int] = {}

        # --- leader lease (ISSUE 18) -----------------------------------
        # per-peer newest SEND-start stamp among acked AppendEntries /
        # InstallSnapshot RPCs: the follower's no-vote window opens at
        # its RECEIVE time >= our send time, so a lease computed from
        # send stamps can never outlive the window that protects it
        self._lease_contact: Dict[str, float] = {}
        self._lease_reads_fast = 0
        self._lease_reads_barrier = 0
        # edge-detect lease expiry for the consensus event log: set on
        # a fast-path read, cleared (with one "lease_expired" timeline
        # event) the first time a read demotes to the barrier
        self._lease_was_valid = False

        self._futures: Dict[int, _ApplyFuture] = {}
        self._apply_cond = threading.Condition(self._lock)
        # --- consensus-plane observability (ISSUE 15) -------------------
        # leader-side append stamps (index -> monotonic) feed the
        # always-on quorum/replication-lag histograms: O(1) dict ops
        # per apply, pruned as the commit index advances
        self._append_stamps: Dict[int, float] = {}
        #: highest index already pruned from _append_stamps — lets the
        #: per-ack prune skip its O(stamps) scan when the floor is
        #: pinned by a lagging/dead peer
        self._stamp_floor = 0
        # (histogram op, seconds) samples collected under self._lock,
        # recorded OUTSIDE it by _obs_flush (R2: no foreign locks
        # inside the raft critical sections)
        self._obs_pending: List[Tuple[str, float]] = []
        # the newest applier's trace context, shipped inside raft RPCs
        # so one eval's span tree spans leader and followers (batch
        # envelope semantics — the waterfall claims by overlap)
        self._repl_trace_ctx: Optional[Tuple[str, int]] = None
        # open-election stamp for the election-duration observation
        self._election_started_mono: Optional[float] = None
        raft_observer.register(node_id, self)
        if self._durable and (self.replayed_entries
                              or self.recovered_snapshot_index):
            # recovered indexes ride in detail, NOT as a causal pin: a
            # recovery replays OLD indexes and must order by clock,
            # not be sorted back to where those entries first landed
            raft_observer.note_event(
                node_id, "recovery", term=self.current_term,
                detail={"replayed": self.replayed_entries,
                        "snapshot_index": self.recovered_snapshot_index})
        # one persistent replicator per peer, individually woken -- a
        # slow peer must not delay heartbeats to the others
        self._peer_wakes: Dict[str, threading.Event] = {
            p: threading.Event() for p in self.peers
        }
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        # the term whose noop barrier marks leadership fully established
        self._leader_barrier_term = -1
        # serializes FSM apply against snapshot capture so a snapshot is
        # exactly the state at last_applied (no torn snapshots);
        # witness-created so the batched drain's fsm->node->store
        # ordering is checked under the stress tier
        self._fsm_lock = witness_lock("raft_fsm")
        # request-id -> result for forwarded applies (at-most-once: a
        # retry after a dropped response must not re-apply the command)
        self._forward_results: Dict[str, Any] = {}
        self._forward_order: List[str] = []

    # --- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._shutdown.clear()
        for name, target in (
            ("raft-tick", self._run_ticker),
            ("raft-apply", self._run_apply),
        ):
            t = threading.Thread(target=target, daemon=True, name=f"{name}-{self.id}")
            self._threads.append(t)
            t.start()
        for peer in self.peers:
            t = threading.Thread(
                target=self._run_peer_replicator, args=(peer,),
                daemon=True, name=f"raft-repl-{self.id}-{peer}",
            )
            self._threads.append(t)
            t.start()

    def shutdown(self) -> None:
        self._shutdown.set()
        with self._lock:
            self._apply_cond.notify_all()
        self._wake_replicators()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
        self.transport.close()
        close = getattr(self.log, "close", None)
        if close is not None:
            close()
        if self._durable:
            from nomad_tpu.raft.wal import wal_stats

            wal_stats.note_cache(self.id, 0)
        raft_observer.unregister(self.id)

    # --- durability helpers (raft/wal.py, ISSUE 13) ---------------------

    def _persist_hard_state_locked(self) -> None:
        """Persist (current_term, voted_for). MUST complete before any
        RPC response that grants a vote or adopts the term leaves this
        node — a crash after responding but before persisting would
        let the restarted node vote again in the same term. Called
        under self._lock; the stable store's writes are monotone so a
        racing later persist can never be regressed by this one."""
        if self._stable is not None:
            self._stable.put(self.current_term, self.voted_for)

    def _sync_log(self) -> None:
        """The ack durability boundary: group-fsync every journaled
        frame (no-op for the in-memory store). Called OUTSIDE
        self._lock — an fsync must never stretch the RPC/apply
        critical sections."""
        if self._durable:
            # raft-fsync is a waterfall segment: the span window is
            # the disk wait an eval's commit actually sat behind
            with tracer.span("raft.fsync"):
                self.log.sync()

    def _note_snapshot_cache_locked(self) -> None:
        from nomad_tpu.raft.wal import wal_stats

        cache = self._snapshot_cache
        wal_stats.note_cache(self.id, len(cache[2]) if cache else 0)

    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def leader_addr(self) -> Optional[str]:
        with self._lock:
            return self.id if self.state == LEADER else self.leader_id

    def last_contact_s(self) -> float:
        """Age of this server's last leader contact (AppendEntries /
        InstallSnapshot receipt or vote grant), in seconds — the
        follower-side staleness meter the read plane stamps into
        ``X-Nomad-Last-Contact`` (ISSUE 20). 0.0 on the leader: its
        store is the state, by definition not stale."""
        with self._lock:
            if self.state == LEADER:
                return 0.0
            return max(0.0, time.monotonic() - self._last_contact)

    # --- public apply ---------------------------------------------------

    def apply(self, msg_type: str, req: Dict, timeout: float = 10.0) -> Any:
        """Append a command; block until committed + FSM-applied locally.
        On followers raises NotLeaderError (callers forward)."""
        # the leader-side entry seam: an injected error here is a raft
        # apply that failed before the append (chaos plane, ISSUE 12)
        fault("raft.apply.pre")
        if tracer.enabled:
            # cross-server propagation: the applier's trace context
            # rides the next AppendEntries so follower-side spans
            # join this eval's tree (last-writer-wins batch envelope)
            self._repl_trace_ctx = tracer.context()
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            entry = LogEntry(
                index=self.log.last_index() + 1,
                term=self.current_term,
                kind=LOG_COMMAND,
                data=(msg_type, req),
            )
            self.log.append(entry)
            self._append_stamps[entry.index] = time.monotonic()
            fut = _ApplyFuture(entry.index)
            self._futures[entry.index] = fut
        # replicators ship the in-memory entry while the leader's own
        # fsync runs (disk overlaps network — followers fsync before
        # acking anyway); the leader's own log vote counts toward
        # commit only once the entry is DURABLE, so _count_self_match
        # stays behind the sync
        self._wake_replicators()
        self._sync_log()
        self._count_self_match(entry)
        self._obs_flush()
        return fut.wait(timeout)

    def barrier(self, timeout: float = 5.0) -> None:
        """Commit a noop and wait (leadership barrier)."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            entry = LogEntry(
                index=self.log.last_index() + 1,
                term=self.current_term,
                kind=LOG_NOOP,
                data=None,
            )
            self.log.append(entry)
            self._append_stamps[entry.index] = time.monotonic()
            fut = _ApplyFuture(entry.index)
            self._futures[entry.index] = fut
        self._wake_replicators()
        self._sync_log()
        self._count_self_match(entry)
        self._obs_flush()
        fut.wait(timeout)

    def _count_self_match(self, entry: LogEntry) -> None:
        """Advance the leader's own match index for a just-synced
        entry. Concurrent appliers can sync out of order (the group
        fsync covers both), so the match only ever moves forward."""
        with self._lock:
            if self.state != LEADER or self.current_term != entry.term:
                return
            if entry.index > self.match_index.get(self.id, 0):
                self.match_index[self.id] = entry.index
            if not self.peers:
                self._advance_commit_locked()

    # --- ticker: elections + heartbeats ---------------------------------

    def _election_timeout(self) -> float:
        return random.uniform(
            self.config.election_timeout_min, self.config.election_timeout_max
        )

    def _run_ticker(self) -> None:
        timeout = self._election_timeout()
        wal_halted = False
        while not self._shutdown.is_set():
            time.sleep(self.config.heartbeat_interval / 2)
            if self._durable and getattr(self.log, "wal_failed", False):
                # fail-stop demotion (the reference panics on a boltdb
                # write error and failover follows the process death;
                # in-process we demote instead): a node that cannot
                # persist must stop LEADING — its heartbeats would
                # otherwise suppress elections forever while every
                # write fails — and must never campaign. It keeps
                # answering reads/votes; an operator (or the restart
                # harness) kills + recovers it.
                if not wal_halted:
                    wal_halted = True
                    raft_observer.note_event(
                        self.id, "wal_failed", term=self.current_term,
                        detail={"was_leader": self.is_leader()})
                    LOG.error(
                        "%s: WAL failed — halting raft leadership/"
                        "campaigns (kill + restart to recover)", self.id)
                if self.is_leader():
                    self.step_down()
                continue
            with self._lock:
                state = self.state
                elapsed = time.monotonic() - self._last_contact
                if self._removed:
                    continue   # voted off the cluster: never campaign
            if state == LEADER:
                try:
                    # leader step-down seam: an armed error here (the
                    # chaos cell's leader-kill schedule) deposes this
                    # leader mid-flight — elections, broker flush +
                    # restore, and plan-future failover all follow the
                    # exact production paths
                    fault("raft.leader.stepdown")
                except FaultError:
                    LOG.info("%s: injected leader step-down", self.id)
                    self.step_down()
                    continue
                self._wake_replicators()   # heartbeat
                continue
            if elapsed >= timeout:
                timeout = self._election_timeout()
                self._start_election()

    def _start_election(self) -> None:
        with self._lock:
            self.state = CANDIDATE
            self.current_term += 1
            term = self.current_term
            self.voted_for = self.id
            self._votes = 1
            self.leader_id = None
            self._last_contact = time.monotonic()
            last_index = self.log.last_index()
            last_term = self.log.last_term()
            peers = list(self.peers)
            # the self-vote is a vote: durable before any RequestVote
            # RPC leaves (a restarted candidate must not re-vote
            # differently in this term)
            self._persist_hard_state_locked()
            if self._election_started_mono is None:
                # first round of this election sequence: the elect
                # phase of a failover runs from HERE to leader_won,
                # covering failed rounds in between
                self._election_started_mono = time.monotonic()
        raft_observer.note_transition(self.id, "election")
        raft_observer.note_event(self.id, "election_start", term=term)
        LOG.debug("%s starting election term %d", self.id, term)
        if not peers:
            self._maybe_win_locked_check(term)
            return
        for peer in peers:
            threading.Thread(
                target=self._request_vote_from,
                args=(peer, term, last_index, last_term),
                daemon=True,
            ).start()

    def _request_vote_from(self, peer: str, term: int, last_index: int, last_term: int) -> None:
        try:
            resp = self.transport.send(
                peer, "request_vote",
                {"term": term, "candidate": self.id,
                 "last_log_index": last_index, "last_log_term": last_term},
            )
        except ConnectionError:
            return
        with self._lock:
            if self.state != CANDIDATE or self.current_term != term:
                return
            if resp["term"] > self.current_term:
                self._step_down_locked(resp["term"])
                return
            if resp.get("granted"):
                self._votes += 1
        self._maybe_win_locked_check(term)

    def _maybe_win_locked_check(self, term: int) -> None:
        became_leader = False
        with self._lock:
            n_voters = len(self.peers) + 1
            if (
                self.state == CANDIDATE
                and self.current_term == term
                and self._votes > n_voters // 2
            ):
                self.state = LEADER
                self.leader_id = self.id
                last = self.log.last_index()
                self.next_index = {p: last + 1 for p in self.peers}
                self.match_index = {p: 0 for p in self.peers}
                self.match_index[self.id] = last
                # fresh leadership: every pipeline re-arms through a
                # synchronous ack, the lease starts from zero (stamps
                # from a previous term must not validate this one)
                self._pipe_drain_all_locked()
                self._lease_contact = {}
                became_leader = True
                election_dur = (
                    time.monotonic() - self._election_started_mono
                    if self._election_started_mono is not None else None)
                self._election_started_mono = None
                LOG.info("%s became leader for term %d", self.id, term)
        if became_leader:
            raft_observer.note_transition(self.id, "leader")
            raft_observer.note_event(self.id, "leader_won", term=term)
            if election_dur is not None:
                # election duration feeds the histogram + consensus
                # recorder: a slow election (repeated timeouts, vote
                # churn) is a tail event worth a captured record
                histograms.get(RAFT_ELECTION).record(election_dur)
                consensus_recorder.observe(
                    RAFT_ELECTION, election_dur, server_id=self.id)
            # commit a barrier noop from this term; on_leader fires when
            # it applies (guarantees the FSM has all prior state)
            with self._lock:
                entry = LogEntry(
                    index=self.log.last_index() + 1,
                    term=term,
                    kind=LOG_NOOP,
                    data=None,
                )
                self.log.append(entry)
                self._leader_barrier_term = term
            self._wake_replicators()
            self._sync_log()
            self._count_self_match(entry)

    def step_down(self) -> None:
        """Voluntarily abandon leadership (hashicorp/raft's leadership
        transfer, minus the hand-off): become a follower in the current
        term, fail pending futures, and let a peer's election timeout
        pick the next leader. The chaos cell's leader-kill schedule
        drives this through the ``raft.leader.stepdown`` fault point."""
        with self._lock:
            if self.state != LEADER:
                return
            self._step_down_locked(self.current_term)

    def _step_down_locked(self, term: int) -> None:
        was_leader = self.state == LEADER
        self.state = FOLLOWER
        self._election_started_mono = None
        if term > self.current_term:
            # only a NEW term clears the vote -- resetting within the
            # same term would allow double-voting
            self.current_term = term
            self.voted_for = None
            # adopted term durable before any response built on it
            # leaves this node (the stable store's no-change fast path
            # makes the equal-term calls free)
            self._persist_hard_state_locked()
            raft_observer.note_transition(self.id, "term")
            raft_observer.note_event(self.id, "term_adopt", term=term)
        self._last_contact = time.monotonic()
        if was_leader:
            # deposed: in-flight pipeline acks are void, and the lease
            # dies with the leadership (lease_valid gates on LEADER
            # anyway; clearing the stamps keeps a re-election from
            # inheriting them)
            self._pipe_drain_all_locked()
            self._lease_contact = {}
            raft_observer.note_transition(self.id, "stepdown")
            raft_observer.note_event(
                self.id, "stepdown", term=self.current_term,
                detail={"was_leader": True})
            # fail pending futures; a new leader owns them now
            for fut in self._futures.values():
                fut.respond(None, NotLeaderError(self.leader_id))
            self._futures.clear()
            if self.on_follower is not None:
                threading.Thread(target=self.on_follower, daemon=True).start()

    # --- replication (leader) -------------------------------------------

    def _wake_replicators(self) -> None:
        # snapshot under the lock: membership changes (gossip-driven
        # add/remove_peer) mutate the dict concurrently with the
        # ticker's iteration
        with self._lock:
            wakes = list(self._peer_wakes.values())
        for ev in wakes:
            ev.set()

    def _run_peer_replicator(self, peer: str) -> None:
        wake = self._peer_wakes[peer]
        while not self._shutdown.is_set():
            wake.wait(self.config.heartbeat_interval)
            wake.clear()
            if self._shutdown.is_set():
                return
            with self._lock:
                if peer not in self.peers:
                    return   # removed from the voting set (autopilot)
                if self.state != LEADER:
                    continue
            try:
                self._replicate_to(peer)
            except Exception as e:              # noqa: BLE001
                LOG.debug("%s: replicate to %s failed: %s", self.id, peer, e)

    def _replicate_to(self, peer: str) -> None:
        """Per-peer replication dispatch. The pipelined path needs an
        ARMED window (a prior synchronous ack proved next_index) and
        ``max_in_flight > 1``; everything else — first contact,
        conflict backoff, snapshot catch-up, and the
        ``max_in_flight=1`` configuration — runs the original
        synchronous send->ack->send path unchanged."""
        if self.config.max_in_flight > 1:
            with self._lock:
                pipelined = self._pipe_ok.get(peer, False)
            if pipelined:
                self._replicate_pipelined(peer)
                return
        self._replicate_sync(peer)

    def _replicate_sync(self, peer: str) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            term = self.current_term
            next_idx = self.next_index.get(peer, self.log.last_index() + 1)
            base = self.log.base_index()
            need_snapshot = next_idx <= base
        if need_snapshot and self._snapshot_cache is None:
            # log is compacted past the peer but no snapshot bytes are
            # in memory (restart from a compacted log, or the cache was
            # dropped after the fleet caught up): PREFER the on-disk
            # snapshot file over re-forcing an FSM capture (ISSUE 13
            # satellite) — only capture anew when no durable file
            # covers the base
            if not self._load_disk_snapshot_cache():
                self.force_snapshot()
        with self._lock:
            if self.state != LEADER or self.current_term != term:
                return
            if need_snapshot:
                if self._snapshot_cache is None:
                    LOG.error(
                        "%s: peer %s needs snapshot but none available",
                        self.id, peer,
                    )
                    return
                snapshot_req = self._build_snapshot_req_locked()
            else:
                snapshot_req = None
                prev_index = next_idx - 1
                prev_term = self.log.term_at(prev_index)
                if prev_term is None:
                    return
                entries = self.log.entries_from(
                    next_idx, self.config.max_append_entries
                )
                commit = self.commit_index
        # lease stamp = SEND-start (before the seam: an injected delay
        # only makes the stamp conservative). The follower's no-vote
        # window opens at its receive time >= this stamp, so a lease
        # extended from here can never outlive that window.
        t_start = time.monotonic()
        # replication seam: injected errors/latency here are dropped or
        # slow AppendEntries RPCs — the replicator's retry-next-wake
        # path (ConnectionError treatment below) must absorb them
        fault("raft.replicate.send")
        try:
            if snapshot_req is not None:
                # index-pinned CREATOR event: the send precedes every
                # follower's snapshot_install for this index, so the
                # timeline's skew estimator can anchor the index at
                # this stamp (telemetry/timeline._estimate_offsets)
                raft_observer.note_event(
                    self.id, "snapshot_sent", term=term,
                    index=snapshot_req["last_index"])
                xfer_t0 = time.monotonic()
                resp = self.transport.send(peer, "install_snapshot", snapshot_req)
                histograms.get(RAFT_SNAPSHOT_XFER).record(
                    time.monotonic() - xfer_t0)
                raft_observer.note_snapshot_xfer(
                    self.id, "sent", len(snapshot_req["data"] or b""))
                with self._lock:
                    if resp["term"] > self.current_term:
                        self._step_down_locked(resp["term"])
                        return
                    self.next_index[peer] = snapshot_req["last_index"] + 1
                    self.match_index[peer] = snapshot_req["last_index"]
                    self.peer_last_contact[peer] = time.monotonic()
                    self._note_lease_contact_locked(peer, t_start)
                    self._maybe_drop_snapshot_cache_locked()
                return
            req = {"term": term, "leader": self.id,
                   "prev_log_index": prev_index,
                   "prev_log_term": prev_term,
                   "entries": entries, "leader_commit": commit}
            if entries and tracer.enabled:
                # ship the applier's trace context and span the RPC:
                # raft-replicate is the waterfall's network segment
                ctx = self._repl_trace_ctx
                if ctx is not None:
                    req["trace"] = ctx
                with tracer.attach(ctx), tracer.span("raft.replicate"):
                    resp = self.transport.send(peer, "append_entries", req)
            else:
                resp = self.transport.send(peer, "append_entries", req)
        except ConnectionError:
            return
        lag_s = None
        with self._lock:
            if self.state != LEADER or self.current_term != term:
                return
            if resp["term"] > self.current_term:
                self._step_down_locked(resp["term"])
                return
            self.peer_last_contact[peer] = time.monotonic()
            # the follower answered IN OUR TERM: its election timer
            # reset on receipt, so even a conflict reply extends the
            # lease window (the stamp is the send start, see above)
            self._note_lease_contact_locked(peer, t_start)
            if resp.get("success"):
                # next_index is now PROVEN for this peer: arm the
                # pipelined window (no-op at max_in_flight=1 — the
                # dispatch never consults _pipe_ok then)
                self._pipe_ok[peer] = True
                if entries:
                    newest = entries[-1].index
                    stamp = self._append_stamps.get(newest)
                    if stamp is not None:
                        lag_s = time.monotonic() - stamp
                        self._obs_pending.append((RAFT_REPLICATION, lag_s))
                    self.match_index[peer] = newest
                    self.next_index[peer] = newest + 1
                    self._advance_commit_locked()
                    self._maybe_drop_snapshot_cache_locked()
                    if self.next_index[peer] <= self.log.last_index():
                        self._wake_replicators()
            else:
                # follower log conflict: back off (fast with hint)
                hint = resp.get("conflict_index")
                self.next_index[peer] = max(
                    1, hint if hint else self.next_index.get(peer, 2) - 1
                )
                self._wake_replicators()
        if entries and resp.get("success"):
            raft_observer.note_replicated(
                self.id, peer, len(entries),
                lag_ms=round(lag_s * 1e3, 3) if lag_s is not None
                else None)
        self._obs_flush()

    # --- pipelined replication (ISSUE 18) -------------------------------

    def _replicate_pipelined(self, peer: str) -> None:
        """Fill the peer's in-flight window: cut AppendEntries batches
        from the speculative frontier (``_pipe_next``) and hand each to
        a short-lived sender thread — up to ``max_in_flight`` at once.
        Acks are serialized in send order by :meth:`_pipe_ack`. The
        transport send itself always happens OUTSIDE self._lock (R2)."""
        cfg = self.config
        while not self._shutdown.is_set():
            with self._lock:
                if self.state != LEADER or not self._pipe_ok.get(peer):
                    return
                if self._pipe_inflight.get(peer, 0) >= cfg.max_in_flight:
                    return      # window full; freed slots re-wake us
                term = self.current_term
                epoch = self._pipe_epoch.get(peer, 0)
                next_idx = self._pipe_next.get(
                    peer, 0) or self.next_index.get(
                        peer, self.log.last_index() + 1)
                if next_idx <= self.log.base_index():
                    # compacted past the peer: InstallSnapshot stays
                    # serial — drain and let the sync path take over
                    self._pipe_drain_locked(peer)
                    self._wake_peer(peer)
                    return
                prev_index = next_idx - 1
                prev_term = self.log.term_at(prev_index)
                if prev_term is None:
                    self._pipe_drain_locked(peer)
                    self._wake_peer(peer)
                    return
                entries = self.log.entries_from(
                    next_idx, cfg.max_append_entries)
                if not entries:
                    if self._pipe_inflight.get(peer, 0):
                        return  # in-flight batches double as heartbeats
                    break       # idle: sync heartbeat keeps the lease
                commit = self.commit_index
                seq = self._pipe_seq.get(peer, 0)
                self._pipe_seq[peer] = seq + 1
                self._pipe_inflight[peer] = (
                    self._pipe_inflight.get(peer, 0) + 1)
                self._pipe_next[peer] = entries[-1].index + 1
                self._pipe_batches += 1
                ctx = self._repl_trace_ctx
            req = {"term": term, "leader": self.id,
                   "prev_log_index": prev_index,
                   "prev_log_term": prev_term,
                   "entries": entries, "leader_commit": commit}
            threading.Thread(
                target=self._pipe_send,
                args=(peer, epoch, seq, req, ctx),
                daemon=True,
                name=f"raft-pipe-{self.id}-{peer}-{seq}",
            ).start()
        # fell through: nothing in flight and nothing to send — run an
        # idle heartbeat on the sync path (leadership + lease refresh)
        self._replicate_sync(peer)

    def _pipe_send(self, peer: str, epoch: int, seq: int, req: Dict,
                   ctx: Optional[Tuple[str, int]]) -> None:
        """One in-flight batch: transit outside every lock, then send
        through the peer's wire turnstile (strict sequence order —
        the ordered stream a real pipeline rides), then hand the
        response (None on any failure) to the in-order ack stage."""
        t_start = time.monotonic()
        resp = None
        stale = False
        try:
            # same replication seam as the sync path: injected
            # errors/latency are dropped or slow pipelined RPCs and
            # surface as a drain + sync retry. Runs BEFORE the
            # turnstile so in-flight transits overlap.
            fault("raft.replicate.send")
            with self._wire_cond:
                while (not self._shutdown.is_set()
                       and self._wire_epoch.get(peer, 0) == epoch
                       and self._wire_turn.get(peer, 0) != seq):
                    self._wire_cond.wait(0.05)
                stale = (self._shutdown.is_set()
                         or self._wire_epoch.get(peer, 0) != epoch)
            if not stale:
                # we OWN the turn until we bump it below: no later
                # batch can reach the transport before us, and the
                # turnstile lock itself is not held across the send
                try:
                    if req["entries"] and tracer.enabled:
                        if ctx is not None:
                            req["trace"] = ctx
                        with tracer.attach(ctx), \
                                tracer.span("raft.replicate"):
                            resp = self.transport.send(
                                peer, "append_entries", req)
                    else:
                        resp = self.transport.send(
                            peer, "append_entries", req)
                finally:
                    with self._wire_cond:
                        if self._wire_epoch.get(peer, 0) == epoch:
                            self._wire_turn[peer] = seq + 1
                        self._wire_cond.notify_all()
        except (ConnectionError, FaultError):
            resp = None
        except Exception as e:                      # noqa: BLE001
            LOG.debug("%s: pipelined send to %s failed: %s",
                      self.id, peer, e)
            resp = None
        self._pipe_ack(peer, epoch, seq, req, resp, t_start)

    def _pipe_ack(self, peer: str, epoch: int, seq: int, req: Dict,
                  resp: Optional[Dict], t_start: float) -> None:
        """Process one batch's ack IN SEND ORDER: wait for our turn,
        then run the exact synchronous success/failure bookkeeping.
        A failed or out-of-term ack drains the window — every batch
        behind it is discarded (their acks become stale-epoch no-ops)
        and the peer falls back to the sync path."""
        entries = req["entries"]
        lag_s = None
        ok = False
        refill = False
        with self._lock:
            while (not self._shutdown.is_set()
                   and self._pipe_epoch.get(peer, 0) == epoch
                   and self._pipe_ack_turn.get(peer, 0) != seq):
                self._pipe_cond.wait(0.1)
            if (self._shutdown.is_set()
                    or self._pipe_epoch.get(peer, 0) != epoch):
                # drained while we waited: the window was reset; the
                # follower may have appended anyway — duplicates are
                # idempotent on the sync retry
                self._pipe_cond.notify_all()
                return
            self._pipe_ack_turn[peer] = seq + 1
            self._pipe_inflight[peer] = max(
                0, self._pipe_inflight.get(peer, 0) - 1)
            self._pipe_cond.notify_all()
            if self.state != LEADER or self.current_term != req["term"]:
                self._pipe_drain_locked(peer)
                return
            if resp is None:
                self._pipe_drain_locked(peer)
                self._wake_peer(peer)
                return
            if resp["term"] > self.current_term:
                self._pipe_drain_locked(peer)
                self._step_down_locked(resp["term"])
                return
            self.peer_last_contact[peer] = time.monotonic()
            self._note_lease_contact_locked(peer, t_start)
            if resp.get("success"):
                ok = True
                newest = entries[-1].index
                stamp = self._append_stamps.get(newest)
                if stamp is not None:
                    lag_s = time.monotonic() - stamp
                    self._obs_pending.append((RAFT_REPLICATION, lag_s))
                if newest > self.match_index.get(peer, 0):
                    self.match_index[peer] = newest
                if newest + 1 > self.next_index.get(peer, 0):
                    self.next_index[peer] = newest + 1
                self._advance_commit_locked()
                self._maybe_drop_snapshot_cache_locked()
                frontier = self._pipe_next.get(
                    peer, 0) or self.next_index.get(peer, 0)
                if frontier <= self.log.last_index():
                    refill = True
            else:
                # conflict: resolution is SERIAL by design — back off
                # next_index with the hint, drain, go sync
                hint = resp.get("conflict_index")
                self.next_index[peer] = max(
                    1, hint if hint else self.next_index.get(peer, 2) - 1)
                self._pipe_drain_locked(peer)
                self._wake_peer(peer)
                return
        if ok:
            raft_observer.note_replicated(
                self.id, peer, len(entries),
                lag_ms=round(lag_s * 1e3, 3) if lag_s is not None
                else None)
        self._obs_flush()
        if refill:
            self._wake_peer(peer)

    def _wake_peer(self, peer: str) -> None:
        with self._lock:
            wake = self._peer_wakes.get(peer)
        if wake is not None:
            wake.set()

    def _pipe_drain_locked(self, peer: str) -> None:
        """Reset the peer's window (caller holds self._lock): bump the
        epoch so in-flight acks discard themselves, zero the sequence
        counters, disarm — the next contact goes through the sync path
        and re-arms on success."""
        self._pipe_epoch[peer] = self._pipe_epoch.get(peer, 0) + 1
        self._pipe_seq[peer] = 0
        self._pipe_ack_turn[peer] = 0
        self._pipe_inflight[peer] = 0
        self._pipe_next.pop(peer, None)
        if self._pipe_ok.get(peer):
            self._pipe_drains += 1
        self._pipe_ok[peer] = False
        self._pipe_cond.notify_all()
        # release wire-turnstile waiters: they see the epoch move and
        # discard without sending (raft_node -> raft_pipe_wire is the
        # only edge between these locks; senders never take self._lock
        # while holding the turnstile)
        with self._wire_cond:
            self._wire_epoch[peer] = self._pipe_epoch[peer]
            self._wire_turn[peer] = 0
            self._wire_cond.notify_all()

    def _pipe_drain_all_locked(self) -> None:
        for p in self.peers:
            self._pipe_drain_locked(p)

    # --- leader lease (ISSUE 18) ----------------------------------------

    def _note_lease_contact_locked(self, peer: str, t_start: float) -> None:
        """Record an acked RPC's SEND-start stamp (monotone per peer)."""
        if t_start > self._lease_contact.get(peer, 0.0):
            self._lease_contact[peer] = t_start

    def _lease_window(self) -> float:
        return (self.config.election_timeout_min
                * self.config.lease_fraction)

    def _lease_quorum_stamp_locked(self) -> Optional[float]:
        """The send stamp at which a quorum (self + enough peers) had
        acked — the lease extends ``_lease_window()`` past it. None
        when no quorum of peers has ever acked this leadership."""
        if not self.peers:
            return time.monotonic()
        need = (len(self.peers) + 1) // 2   # peers needed beyond self
        if need == 0:
            return time.monotonic()
        stamps = sorted((self._lease_contact.get(p, 0.0)
                         for p in self.peers), reverse=True)
        stamp = stamps[need - 1]
        return stamp if stamp > 0.0 else None

    def lease_valid(self) -> bool:
        """True while this leader's clock-based lease holds: a quorum
        of AppendEntries acks with send stamps within
        ``election_timeout_min * lease_fraction``. While True,
        leader-side linearizable reads may skip the barrier round-trip
        (server.py linearizable_read); on False they demote to the
        leader barrier. Never true off-leader or with leases off."""
        with self._lock:
            return self._lease_valid_locked()

    def _lease_valid_locked(self) -> bool:
        if self.state != LEADER or not self.config.leader_lease:
            return False
        stamp = self._lease_quorum_stamp_locked()
        if stamp is None:
            return False
        return time.monotonic() - stamp <= self._lease_window()

    def note_lease_read(self, fast: bool) -> None:
        """Server-side accounting: a linearizable read served off the
        lease fast path (True) or demoted to the barrier (False). A
        held->lapsed transition lands one ``lease_expired`` event in
        the consensus timeline (raft/observe.py) so chaos cells can
        line lease loss up against partitions and elections."""
        expired_term = None
        with self._lock:
            if fast:
                self._lease_reads_fast += 1
                self._lease_was_valid = True
            else:
                self._lease_reads_barrier += 1
                if self._lease_was_valid:
                    self._lease_was_valid = False
                    expired_term = self.current_term
        if expired_term is not None:
            raft_observer.note_event(
                self.id, "lease_expired", term=expired_term)

    def _build_snapshot_req_locked(self) -> Dict:
        # the request carries the CACHE's own (index, term) — never
        # pair base_index with possibly-newer cache bytes (a capture
        # racing a replicator between cache-set and compact would
        # otherwise ship state@applied labeled as state@base, and the
        # follower would re-apply the span in between twice)
        index, term, data = self._snapshot_cache
        return {
            "term": self.current_term,
            "leader": self.id,
            "last_index": index,
            "last_term": term,
            "data": data,
        }

    def _load_disk_snapshot_cache(self) -> bool:
        """Re-arm the in-memory snapshot cache from the newest on-disk
        snapshot file when it covers the compacted base. Returns True
        when the cache is usable afterward."""
        if self._snapshots is None:
            return False
        snap = self._snapshots.load_newest()
        if snap is None:
            return False
        with self._lock:
            if snap[0] < self.log.base_index():
                return False     # disk older than the base: re-force
            self._snapshot_cache = snap
            self._note_snapshot_cache_locked()
        return True

    def _maybe_drop_snapshot_cache_locked(self) -> None:
        """ISSUE 13 satellite: the cache was unbounded and unmetered.
        Once every peer's match index covers the base, no lagging
        follower can need it — drop the bytes (the on-disk file, or a
        fresh force, serves any later straggler)."""
        if self._snapshot_cache is None:
            return
        base = self.log.base_index()
        if all(self.match_index.get(p, 0) >= base for p in self.peers):
            self._snapshot_cache = None
            self._note_snapshot_cache_locked()

    def _advance_commit_locked(self) -> None:
        """Majority match with current-term guard (Raft section 5.4.2)."""
        matches = sorted(self.match_index.values(), reverse=True)
        n_voters = len(self.peers) + 1
        majority_idx = matches[n_voters // 2] if len(matches) >= n_voters else 0
        if majority_idx > self.commit_index:
            term_at = self.log.term_at(majority_idx)
            if term_at == self.current_term:
                self.commit_index = majority_idx
                # quorum latency = leader append -> majority commit;
                # sampled at the advancing index, recorded outside
                # this lock by whichever caller flushes next
                stamp = self._append_stamps.get(majority_idx)
                if stamp is not None:
                    self._obs_pending.append(
                        (RAFT_QUORUM, time.monotonic() - stamp))
                self._apply_cond.notify_all()
        # prune stamps only once EVERY peer has acked them (and commit
        # has passed): the laggard's stamp must survive to its own ack
        # so the per-peer replication-lag sample and cluster_health's
        # LagMs measure the slowest peer, not just the majority
        floor = self.commit_index
        if self.peers:
            floor = min(min(self.match_index.get(p, 0)
                            for p in self.peers), floor)
        if floor > self._stamp_floor:
            for idx in [i for i in self._append_stamps if i <= floor]:
                del self._append_stamps[idx]
            self._stamp_floor = floor

    def _obs_flush(self) -> None:
        """Record the latency samples the locked sections collected.
        Called OUTSIDE self._lock; histogram records are the always-on
        O(µs) budget, the quorum waterfall span only exists when
        tracing is on."""
        with self._lock:
            if len(self._append_stamps) > 4096:
                # a dead peer pins the min-match prune floor; shed the
                # oldest stamps but keep the live tail so quorum and
                # healthy-peer ack samples survive the guard. Runs
                # BEFORE the empty-pending bail: a leader without
                # quorum collects no samples at all, which is exactly
                # when stamps grow unboundedly
                for idx in sorted(self._append_stamps)[:-1024]:
                    del self._append_stamps[idx]
            if not self._obs_pending:
                return
            pending, self._obs_pending = self._obs_pending, []
        enabled = tracer.enabled
        for op, dur in pending:
            histograms.get(op).record(dur)
            if enabled and op == RAFT_QUORUM:
                # retroactive leaf record: the waterfall claims it by
                # overlap with the eval's commit window
                tracer.record("raft.quorum", dur)

    # --- apply loop -----------------------------------------------------

    #: committed entries drained per apply wakeup — bounds one batch's
    #: future-response burst and event list during post-restart catch-up
    _APPLY_BATCH_MAX = 1024

    def _run_apply(self) -> None:
        """Batched apply drain (ISSUE 18): each wakeup takes the FULL
        committed-but-unapplied range (capped) and applies it as ONE
        batch — one _fsm_lock span, and (through fsm_apply_batch) one
        store write-txn root swap + one event-stream publish stamp —
        instead of the seed's per-entry lock/notify churn."""
        while not self._shutdown.is_set():
            with self._lock:
                if self.last_applied >= self.commit_index:
                    self._apply_cond.wait(0.2)
                if self._shutdown.is_set():
                    return
                if self.last_applied >= self.commit_index:
                    continue
                start = self.last_applied + 1
                end = min(self.commit_index,
                          start + self._APPLY_BATCH_MAX - 1)
                batch = [(i, self.log.get(i), self._futures.pop(i, None))
                         for i in range(start, end + 1)]
                barrier_term = self._leader_barrier_term
                is_leader = self.state == LEADER
            barrier_hit = self._apply_committed_batch(
                batch, barrier_term, is_leader)
            if barrier_hit:
                with self._lock:
                    self._leader_barrier_term = -1
                if self.on_leader is not None:
                    threading.Thread(
                        target=self.on_leader, daemon=True).start()
            self._maybe_snapshot()

    def _apply_committed_batch(self, batch, barrier_term: int,
                               is_leader: bool) -> bool:
        """Apply one committed range under ONE _fsm_lock hold.

        Contiguous runs of plain commands go through ``fsm_apply_batch``
        (one store root swap on the other side) when wired, else
        per-entry ``fsm_apply`` inside the same hold. Membership
        changes and noops break runs and apply inline, preserving
        strict log order. Futures respond AFTER the lock drops.
        Returns whether the leadership barrier noop applied."""
        barrier_hit = False
        responses: List[Tuple[Optional[_ApplyFuture], Any,
                              Optional[Exception]]] = []
        with self._fsm_lock:
            with self._lock:
                frontier = self.last_applied
            run: List[Tuple[str, Dict]] = []
            run_futs: List[Optional[_ApplyFuture]] = []

            def flush_run() -> None:
                if not run:
                    return
                if self.fsm_apply_batch is not None:
                    # raft-apply is the waterfall envelope around the
                    # FSM's own fsm.apply span (leaf-out: fsm claims
                    # first, this span keeps the dispatch residue)
                    with tracer.span("raft.apply"):
                        try:
                            results = self.fsm_apply_batch(list(run))
                        except Exception as e:      # noqa: BLE001
                            # the batch doorway contains per-entry
                            # failures itself; anything escaping it
                            # must not kill the apply loop
                            results = [(None, e)] * len(run)
                else:
                    results = []
                    with tracer.span("raft.apply"):
                        for msg_type, req in run:
                            try:
                                results.append(
                                    (self.fsm_apply(msg_type, req), None))
                            except Exception as e:  # noqa: BLE001
                                results.append((None, e))
                for fut, (result, error) in zip(run_futs, results):
                    if error is not None:
                        LOG.warning("%s: FSM apply failed: %s",
                                    self.id, error)
                    responses.append((fut, result, error))
                run.clear()
                run_futs.clear()

            applied_to = frontier
            for index, entry, fut in batch:
                if index <= frontier:
                    # a snapshot install moved the applied frontier
                    # while this batch waited on _fsm_lock: the
                    # restored state already CONTAINS these entries —
                    # applying them now would double-apply
                    if fut is not None:
                        responses.append((fut, None, None))
                    continue
                applied_to = index
                if entry is None:
                    continue
                if entry.kind == LOG_COMMAND:
                    msg_type, req = entry.data
                    if msg_type in (RAFT_REMOVE_PEER, RAFT_ADD_PEER):
                        # replicated membership change: applied on
                        # every replica at the same log position —
                        # flush first so log order is preserved
                        flush_run()
                        try:
                            if msg_type == RAFT_REMOVE_PEER:
                                self._apply_remove_peer(req["peer"])
                            else:
                                self._apply_add_peer(req["peer"])
                            responses.append((fut, index, None))
                        except Exception as e:      # noqa: BLE001
                            LOG.warning(
                                "%s: FSM apply %s failed: %s",
                                self.id, msg_type, e)
                            responses.append((fut, None, e))
                        continue
                    # committed-entry apply seam, fired per entry as
                    # the run assembles. NOTE: error injection here on
                    # a REPLICATED cluster diverges replicas (the
                    # entry applies on the others) — the reference
                    # panics for the same reason; chaos schedules use
                    # latency only on clusters, errors only
                    # single-server (docs/ROBUSTNESS.md)
                    try:
                        fault("raft.fsm.apply")
                    except Exception as e:          # noqa: BLE001
                        LOG.warning("%s: FSM apply %s failed: %s",
                                    self.id, msg_type, e)
                        responses.append((fut, None, e))
                        continue
                    run.append((msg_type, req))
                    run_futs.append(fut)
                    continue
                # noop (possibly the leadership barrier)
                if (entry.kind == LOG_NOOP and is_leader
                        and entry.term == barrier_term):
                    barrier_hit = True
                if fut is not None:
                    responses.append((fut, None, None))
            flush_run()
            with self._lock:
                if applied_to > self.last_applied:
                    self.last_applied = applied_to
        for fut, result, error in responses:
            if fut is not None:
                fut.respond(result, error)
        return barrier_hit

    # --- snapshots ------------------------------------------------------

    #: (index, term, data) of the newest captured snapshot — the index
    #: pairing travels WITH the bytes (see _build_snapshot_req_locked)
    _snapshot_cache: Optional[Tuple[int, int, bytes]] = None

    def _maybe_snapshot(self) -> None:
        if self.snapshot_fn is None:
            return
        with self._lock:
            applied = self.last_applied
            base = self.log.base_index()
        if applied - base < self.config.snapshot_threshold:
            return
        self.force_snapshot()

    def force_snapshot(self) -> None:
        """Operator snapshot (nomad /v1/operator/snapshot analog).

        Holding _fsm_lock quiesces the apply loop so the captured bytes
        are exactly the state at last_applied -- compacting to any other
        index would lose or double-apply entries on restore.

        Durable order (ISSUE 13): snapshot FILE first, then the WAL
        compact record, then superseded-segment deletion — a crash at
        any seam recovers from the newer of (previous snapshot + full
        WAL) or (new snapshot + suffix)."""
        if self.snapshot_fn is None:
            return
        with self._fsm_lock:
            with self._lock:
                applied = self.last_applied
            data = self.snapshot_fn()
            with self._lock:
                term = self.log.term_at(applied) or self.current_term
                self._snapshot_cache = (applied, term, data)
                self._note_snapshot_cache_locked()
            if self._snapshots is not None:
                self._snapshots.save(applied, term, data)
            self.log.compact_to(applied, term)
        self.log.persist()

    # --- RPC handlers ---------------------------------------------------

    def _handle_rpc(self, method: str, req: Dict) -> Dict:
        if method == "request_vote":
            return self._on_request_vote(req)
        if method == "append_entries":
            return self._on_append_entries(req)
        if method == "install_snapshot":
            return self._on_install_snapshot(req)
        if method == "forward_apply":
            return self._on_forward_apply(req)
        if method == "read_index":
            return self._on_read_index(req)
        raise ValueError(f"unknown raft RPC {method}")

    def _on_request_vote(self, req: Dict) -> Dict:
        with self._lock:
            if (self.config.leader_lease
                    and self.state == FOLLOWER
                    and self.leader_id is not None
                    and req["candidate"] != self.leader_id
                    and time.monotonic() - self._last_contact
                    < self.config.election_timeout_min):
                # lease-safety half of the leader lease (raft §6 /
                # CheckQuorum): while this follower heard its leader
                # within election_timeout_min it refuses votes WITHOUT
                # adopting the candidate's term — otherwise any
                # partitioned rejoiner could depose a leader whose
                # clock lease (a strict fraction of this window) is
                # still live, and a lease-read would go stale
                return {"term": self.current_term, "granted": False}
            if req["term"] > self.current_term:
                self._step_down_locked(req["term"])
            granted = False
            # a candidate this replica knows was removed from the
            # voting set cannot get our vote (post-removal rejoin guard)
            known_voter = req["candidate"] in self.peers
            if known_voter and req["term"] == self.current_term and (
                self.voted_for is None or self.voted_for == req["candidate"]
            ):
                # candidate's log must be at least as up-to-date
                my_last_term = self.log.last_term()
                my_last_index = self.log.last_index()
                if (req["last_log_term"], req["last_log_index"]) >= (
                    my_last_term, my_last_index,
                ):
                    granted = True
                    self.voted_for = req["candidate"]
                    self._last_contact = time.monotonic()
                    # the vote is durable BEFORE the grant leaves: a
                    # crash after responding must restart remembering
                    # who this term's vote went to
                    self._persist_hard_state_locked()
            return {"term": self.current_term, "granted": granted}

    def _on_append_entries(self, req: Dict) -> Dict:
        if req.get("entries") and tracer.enabled:
            # adopt the leader-shipped trace context so this
            # follower's spans land in the originating eval's tree —
            # the cross-server propagation ISSUE 15 adds
            with tracer.attach(req.get("trace")), \
                    tracer.span("raft.follower.append"):
                return self._append_entries_observed(req)
        return self._append_entries_observed(req)

    def _append_entries_observed(self, req: Dict) -> Dict:
        t0 = time.monotonic() if req.get("entries") else 0.0
        with self._lock:
            resp, dirty = self._append_entries_locked(req)
        if dirty:
            # the success ack PROMISES the appended/truncated suffix
            # survives a crash: group-fsync before it leaves (outside
            # the lock — an fsync must not stall the RPC plane).
            # Heartbeats and rejections stay fsync-free.
            self._sync_log()
        if t0:
            # follower append handling incl. its group fsync: the
            # always-on distribution + the consensus flight recorder
            dur = time.monotonic() - t0
            histograms.get(RAFT_APPEND).record(dur)
            consensus_recorder.observe(
                RAFT_APPEND, dur, server_id=self.id,
                trace_id=(req.get("trace") or ("",))[0])
        return resp

    def _append_entries_locked(self, req: Dict) -> Tuple[Dict, bool]:
        if req["term"] < self.current_term:
            return {"term": self.current_term, "success": False}, False
        if req["term"] > self.current_term or self.state != FOLLOWER:
            self._step_down_locked(req["term"])
        self.current_term = req["term"]
        self.leader_id = req["leader"]
        self._last_contact = time.monotonic()

        prev_index = req["prev_log_index"]
        prev_term = req["prev_log_term"]
        if prev_index > 0:
            local_term = self.log.term_at(prev_index)
            if local_term is None:
                return {
                    "term": self.current_term, "success": False,
                    "conflict_index": self.log.last_index() + 1,
                }, False
            if local_term != prev_term:
                return {
                    "term": self.current_term, "success": False,
                    "conflict_index": max(1, prev_index - 1),
                }, False
        dirty = False
        for entry in req["entries"]:
            local = self.log.get(entry.index)
            if local is not None and local.term != entry.term:
                self.log.truncate_from(entry.index)
                local = None
                dirty = True
            if local is None:
                if self.log.last_index() + 1 == entry.index:
                    self.log.append(entry)
                    dirty = True
                # else: gap; leader will back off via conflict_index
        # commit may only advance to the last entry VERIFIED by this
        # batch -- a stale uncommitted tail beyond it must not be
        # applied (Raft figure 2: min(leaderCommit, index of last
        # new entry))
        last_verified = (
            req["entries"][-1].index if req["entries"] else prev_index
        )
        if req["leader_commit"] > self.commit_index:
            new_commit = min(req["leader_commit"], last_verified)
            if new_commit > self.commit_index:
                self.commit_index = new_commit
                self._apply_cond.notify_all()
        return {"term": self.current_term, "success": True}, dirty

    def _on_install_snapshot(self, req: Dict) -> Dict:
        with self._lock:
            if req["term"] < self.current_term:
                return {"term": self.current_term}
            self._step_down_locked(req["term"])
            self.current_term = req["term"]
            self.leader_id = req["leader"]
            self._last_contact = time.monotonic()
            if req["data"] is None:
                # never wipe local state for an empty snapshot
                return {"term": self.current_term}
        raft_observer.note_snapshot_xfer(
            self.id, "received", len(req["data"]))
        raft_observer.note_event(
            self.id, "snapshot_install", term=req["term"],
            index=req["last_index"])
        if self._snapshots is not None:
            # the multi-MB durable file write runs OUTSIDE self._lock
            # (an fsync must not stall the RPC/ticker plane) and disk
            # lands BEFORE the log compaction: a crash in between
            # recovers from this file plus the uncompacted WAL; the
            # reverse order is the seed's unrecoverable
            # compacted-log-without-snapshot state
            self._snapshots.save(
                req["last_index"], req["last_term"], req["data"])
        # _fsm_lock quiesces the apply loop for the whole swap (the
        # force_snapshot lock order); restore + compact + truncate +
        # counter updates are ONE section under self._lock — the
        # re-validate, the restore, and the log surgery must be atomic
        # against a concurrent AppendEntries from a newer leader, or
        # the truncate could delete an entry the append already
        # counted into commit_index (the apply loop would then skip it
        # silently — replica divergence). The restore/compact cost
        # under the raft lock is the pre-existing trade on this rare
        # path; the multi-MB file save above stays outside.
        with self._fsm_lock:
            with self._lock:
                # re-validate after the unlocked write: a newer term
                # may have arrived, or this snapshot may be STALE
                # (local state already at/past it — restoring would
                # rewind the FSM); the file above is kept either way
                if req["term"] < self.current_term:
                    return {"term": self.current_term}
                if req["last_index"] <= max(self.log.base_index(),
                                            self.last_applied):
                    return {"term": self.current_term}
                if self.restore_fn is not None:
                    self.restore_fn(req["data"])
                self.log.compact_to(req["last_index"], req["last_term"])
                self.log.truncate_from(req["last_index"] + 1)
                if req["last_index"] > self.commit_index:
                    self.commit_index = req["last_index"]
                self.last_applied = req["last_index"]
                resp = {"term": self.current_term}
        self._sync_log()
        return resp

    def _on_forward_apply(self, req: Dict) -> Dict:
        """Leader-side handler for follower-forwarded applies
        (rpc.go:537 forwarding). request_id gives at-most-once: a retry
        after a dropped response returns the cached result instead of
        re-applying."""
        request_id = req.get("request_id")
        if request_id is not None:
            with self._lock:
                if request_id in self._forward_results:
                    return {"ok": True, "result": self._forward_results[request_id]}
        try:
            ctx = req.get("trace")
            if ctx is not None and tracer.enabled:
                # forwarded applies keep the origin server's trace id:
                # the leader-side spans (fsync/quorum/apply) join the
                # forwarding eval's tree
                with tracer.attach(tuple(ctx)), \
                        tracer.span("raft.forward.apply"):
                    result = self.apply(req["msg_type"], req["req"],
                                        timeout=10.0)
            else:
                result = self.apply(req["msg_type"], req["req"],
                                    timeout=10.0)
        except NotLeaderError as e:
            return {"ok": False, "not_leader": True, "leader": e.leader}
        if request_id is not None:
            with self._lock:
                self._forward_results[request_id] = result
                self._forward_order.append(request_id)
                while len(self._forward_order) > 1024:
                    self._forward_results.pop(self._forward_order.pop(0), None)
        return {"ok": True, "result": result}

    def _on_read_index(self, req: Dict) -> Dict:
        """Leader-side half of the ReadIndex fence (raft §6.4,
        server/readplane.py ISSUE 20): confirm we are STILL leader —
        via the lease when it holds, via a committed barrier when it
        lapsed — then vouch for the current commit index. The
        forwarding follower waits for its own apply loop to reach that
        index and serves locally; only the fence crosses the wire."""
        with self._lock:
            if self.state != LEADER:
                return {"ok": False, "not_leader": True,
                        "leader": self.leader_id}
            leased = self._lease_valid_locked()
            index = self.commit_index
            term = self.current_term
        if not leased:
            try:
                self.barrier()
            except NotLeaderError as e:
                return {"ok": False, "not_leader": True,
                        "leader": e.leader}
            with self._lock:
                if self.state != LEADER:
                    return {"ok": False, "not_leader": True,
                            "leader": self.leader_id}
                index = self.commit_index
                term = self.current_term
        return {"ok": True, "index": index, "term": term,
                "leader": self.id}

    def forward_apply(self, msg_type: str, req: Dict, timeout: float = 10.0) -> Any:
        """Follower-side: route an apply to the current leader."""
        import uuid
        request_id = str(uuid.uuid4())   # stable across retries
        deadline = time.time() + timeout
        while time.time() < deadline:
            leader = self.leader_addr()
            if leader is None or leader == self.id:
                if self.is_leader():
                    return self.apply(msg_type, req, timeout)
                time.sleep(0.05)
                continue
            fwd = {"msg_type": msg_type, "req": req,
                   "request_id": request_id}
            if tracer.enabled:
                ctx = tracer.context()
                if ctx is not None:
                    fwd["trace"] = ctx
            try:
                resp = self.transport.send(
                    leader, "forward_apply", fwd,
                    timeout=timeout,
                )
            except ConnectionError:
                time.sleep(0.05)
                continue
            if resp.get("ok"):
                return resp["result"]
            time.sleep(0.05)
        raise TimeoutError("could not reach a leader")

    def stats(self) -> Dict:
        with self._lock:
            return {
                "state": self.state,
                "term": self.current_term,
                "leader": self.leader_id,
                "commit_index": self.commit_index,
                "last_applied": self.last_applied,
                "last_log_index": self.log.last_index(),
            }

    # --- consensus-plane observability (ISSUE 15) -----------------------

    def observe_gauges(self) -> Dict:
        """Live gauges for the observer's per-server snapshot (the
        exporter's ``server_id``-labeled series)."""
        now = time.monotonic()
        with self._lock:
            last_log = self.log.last_index()
            leader = self.state == LEADER
            lease_stamp = (self._lease_quorum_stamp_locked()
                           if leader else None)
            return {
                "state": self.state,
                "is_leader": 1 if leader else 0,
                "term": self.current_term,
                "commit_index": self.commit_index,
                "last_applied": self.last_applied,
                "last_log_index": last_log,
                "peer_lag_entries": {
                    p: last_log - self.match_index.get(p, 0)
                    for p in self.peers
                } if leader else {},
                "peer_last_contact_s": {
                    p: round(now - self.peer_last_contact[p], 3)
                    for p in self.peers if p in self.peer_last_contact
                },
                # pipeline window health (ISSUE 18)
                "pipeline_inflight": {
                    p: self._pipe_inflight.get(p, 0)
                    for p in self.peers
                } if leader else {},
                # _pipe_ok is recorded even at max_in_flight=1 (the
                # sync path arms it; the dispatcher just never asks) —
                # the gauge reports 0 unless the window is enabled
                "pipeline_armed": sum(
                    1 for p in self.peers if self._pipe_ok.get(p))
                if leader and self.config.max_in_flight > 1 else 0,
                "pipeline_batches": self._pipe_batches,
                "pipeline_drains": self._pipe_drains,
                # leader lease (ISSUE 18)
                "lease_valid": 1 if self._lease_valid_locked() else 0,
                "lease_age_s": round(now - lease_stamp, 4)
                if lease_stamp is not None else None,
                "lease_reads_fast": self._lease_reads_fast,
                "lease_reads_barrier": self._lease_reads_barrier,
            }

    def cluster_health(self) -> Dict:
        """The autopilot-style per-peer view /v1/operator/
        cluster-health renders: this server's identity + raft state,
        and (leader-side) each peer's match index, entry/ms lag, and
        last-contact age. Lag in ms is the age of the oldest entry the
        peer has NOT acked — 0 when fully caught up, null when the
        age is UNKNOWN (this leader holds no stamp for that entry:
        inherited from a previous leader after failover, or shed by
        the growth guard) so a lagging peer can never read as
        "caught up in ms"."""
        now = time.monotonic()
        with self._lock:
            last_log = self.log.last_index()
            peers = []
            for p in self.peers:
                match = self.match_index.get(p, 0)
                lag_entries = max(last_log - match, 0)
                lag_ms: Optional[float] = 0.0
                if lag_entries and self.state == LEADER:
                    stamp = self._append_stamps.get(match + 1)
                    lag_ms = round((now - stamp) * 1e3, 3) \
                        if stamp is not None else None
                contact = self.peer_last_contact.get(p)
                contact_ms = round((now - contact) * 1e3, 3) \
                    if contact is not None else None
                peers.append({
                    "Id": p,
                    "MatchIndex": match,
                    "LagEntries": lag_entries,
                    "LagMs": lag_ms,
                    "LastContactMs": contact_ms,
                    "Healthy": bool(
                        contact is not None
                        and now - contact
                        < 10 * self.config.heartbeat_interval
                        and lag_entries < 1024),
                })
            return {
                "ServerId": self.id,
                "State": self.state,
                "Term": self.current_term,
                "Leader": self.id if self.state == LEADER
                else self.leader_id,
                "CommitIndex": self.commit_index,
                "LastApplied": self.last_applied,
                "LastLogIndex": last_log,
                "Peers": peers,
            }

    # --- membership + health (autopilot's raft surface) -----------------

    def add_peer(self, peer: str) -> None:
        """Replicated membership addition (raft AddVoter; the serf
        member-join -> addRaftPeer flow, reference leader.go:1182):
        commits a config-change entry so every replica starts
        replicating to the new server at the same log position. The
        new server itself boots with the full peer set in its static
        config (agent server_join) and catches up via AppendEntries
        or InstallSnapshot. Same restart caveat as remove_peer:
        membership is re-derived from static config + gossip on
        process restart (a compaction past this entry does not replay
        it); the entry protects against failover amnesia within a
        process lifetime, and the membership layer re-adds live peers
        on its first gossip exchange after a restart."""
        self.apply(RAFT_ADD_PEER, {"peer": peer})

    def _apply_add_peer(self, peer: str) -> None:
        if peer == self.id:
            with self._lock:
                self._removed = False   # re-added after a removal
            return
        with self._lock:
            if peer in self.peers:
                return
            self.peers.append(peer)
            self.next_index[peer] = self.log.last_index() + 1
            self.match_index[peer] = 0
            self._peer_wakes[peer] = threading.Event()
            running = bool(self._threads) and not self._shutdown.is_set()
        if running:
            t = threading.Thread(
                target=self._run_peer_replicator, args=(peer,),
                daemon=True, name=f"raft-repl-{self.id}-{peer}",
            )
            self._threads.append(t)
            t.start()
        LOG.info("%s: added raft peer %s", self.id, peer)

    def remove_peer(self, peer: str) -> None:
        """Replicated membership change (raft RemoveServer; autopilot
        dead-server cleanup): commits a config-change entry through the
        log so every replica -- including a future leader -- drops the
        peer at the same position. Single-server changes only (no joint
        consensus), matching hashicorp/raft's RemoveServer. Note:
        membership is re-derived from static config on process restart;
        the entry protects against failover amnesia, not restarts."""
        self.apply(RAFT_REMOVE_PEER, {"peer": peer})

    def _apply_remove_peer(self, peer: str) -> None:
        if peer == self.id:
            # we were voted off the island: stop participating
            with self._lock:
                self._removed = True
                self.state = FOLLOWER
                self.peers = []
            LOG.info("%s: removed from the cluster by config change", self.id)
            return
        with self._lock:
            if peer not in self.peers:
                return
            self.peers.remove(peer)
            self.next_index.pop(peer, None)
            self.match_index.pop(peer, None)
            self.peer_last_contact.pop(peer, None)
            # stranded in-flight acks see the epoch bump and discard
            # (the bumped epoch entry itself stays so they CAN see it)
            self._pipe_drain_locked(peer)
            self._pipe_ok.pop(peer, None)
            self._lease_contact.pop(peer, None)
            wake = self._peer_wakes.pop(peer, None)
        if wake is not None:
            wake.set()
        LOG.info("%s: removed raft peer %s", self.id, peer)

    def server_health(self) -> List[Dict]:
        """Per-peer health view (autopilot ServerHealth): last contact
        age and log lag, leader's perspective."""
        now = time.monotonic()
        with self._lock:
            last_log = self.log.last_index()
            return [
                {
                    "id": p,
                    "last_contact_s": (
                        now - self.peer_last_contact[p]
                        if p in self.peer_last_contact else float("inf")
                    ),
                    "match_index": self.match_index.get(p, 0),
                    "log_lag": last_log - self.match_index.get(p, 0),
                }
                for p in self.peers
            ]
