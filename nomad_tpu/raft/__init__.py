"""Consensus + replication (reference: hashicorp/raft via nomad/fsm.go,
nomad/raft_rpc.go, server.go:1228 setupRaft).

The reference replicates every authoritative state mutation through a
Raft log applied to the FSM on each server. This package provides the
same contract: ``RaftNode.apply(msg_type, req)`` returns once the entry
is committed and applied locally; leadership changes drive the server's
establish/revoke hooks (leader.go:54 monitorLeadership analog).

Transports are pluggable: ``InmemTransport`` wires nodes in one process
(the reference's raft.InmemTransport used by every multi-server Go
test); ``TcpTransport`` carries the same RPCs between processes.
"""

from nomad_tpu.raft.log import LogEntry, LogStore
from nomad_tpu.raft.node import RaftNode, RaftConfig, NotLeaderError
from nomad_tpu.raft.transport import InmemTransport, TransportRegistry

__all__ = [
    "InmemTransport",
    "LogEntry",
    "LogStore",
    "NotLeaderError",
    "RaftConfig",
    "RaftNode",
    "TransportRegistry",
]
