"""Raft log entries and the log store.

Reference behavior: hashicorp/raft's LogStore backed by raft-boltdb
(go.mod:80); here an in-memory list with optional file persistence
(the boltdb analog) and snapshot-driven truncation.
"""

from __future__ import annotations

import os
import pickle
import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional

# Entry kinds
LOG_COMMAND = "command"
LOG_NOOP = "noop"            # barrier entry a new leader commits
LOG_CONFIG = "configuration"  # membership change


@dataclass
class LogEntry:
    index: int
    term: int
    kind: str = LOG_COMMAND
    # command payload: (msg_type, req) for the FSM
    data: Any = None


class LogStore:
    """Append-only log with prefix truncation after snapshots.

    Indexes are 1-based (raft convention); ``base`` is the index of the
    last entry compacted into a snapshot.
    """

    def __init__(self, path: Optional[str] = None) -> None:
        self._lock = threading.RLock()
        self._entries: List[LogEntry] = []
        self._base_index = 0     # last snapshotted index
        self._base_term = 0
        self._path = path
        if path and os.path.exists(path):
            self._load()

    # --- persistence (raft-boltdb analog) -------------------------------

    def _load(self) -> None:
        with open(self._path, "rb") as f:
            payload = pickle.load(f)
        self._entries = payload["entries"]
        self._base_index = payload["base_index"]
        self._base_term = payload["base_term"]

    def sync(self) -> None:
        """Durability boundary: a no-op for the in-memory store.
        DurableLogStore (raft/wal.py) overrides it with the WAL's
        group fsync; raft/node.py calls it before any ack that
        promises the entries survive a crash."""

    def persist(self) -> None:
        if not self._path:
            return
        tmp = self._path + ".tmp"
        with self._lock:
            payload = {
                "entries": list(self._entries),
                "base_index": self._base_index,
                "base_term": self._base_term,
            }
        with open(tmp, "wb") as f:
            pickle.dump(payload, f)
        os.replace(tmp, self._path)

    # --- accessors ------------------------------------------------------

    def last_index(self) -> int:
        with self._lock:
            if self._entries:
                return self._entries[-1].index
            return self._base_index

    def last_term(self) -> int:
        with self._lock:
            if self._entries:
                return self._entries[-1].term
            return self._base_term

    def base_index(self) -> int:
        with self._lock:
            return self._base_index

    def term_at(self, index: int) -> Optional[int]:
        with self._lock:
            if index == 0:
                return 0
            if index == self._base_index:
                return self._base_term
            entry = self._get_locked(index)
            return entry.term if entry is not None else None

    def get(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            return self._get_locked(index)

    def _get_locked(self, index: int) -> Optional[LogEntry]:
        pos = index - self._base_index - 1
        if pos < 0 or pos >= len(self._entries):
            return None
        return self._entries[pos]

    def entries_from(self, index: int, max_entries: int = 64) -> List[LogEntry]:
        with self._lock:
            pos = index - self._base_index - 1
            if pos < 0:
                pos = 0
            return list(self._entries[pos:pos + max_entries])

    # --- mutation -------------------------------------------------------

    def append(self, entry: LogEntry) -> None:
        with self._lock:
            self._entries.append(entry)

    def truncate_from(self, index: int) -> None:
        """Drop entries >= index (conflict resolution on followers)."""
        with self._lock:
            pos = index - self._base_index - 1
            if pos < 0:
                pos = 0
            del self._entries[pos:]

    def compact_to(self, index: int, term: int) -> None:
        """Drop entries <= index after they are in a snapshot."""
        with self._lock:
            pos = index - self._base_index
            if pos > 0:
                del self._entries[:pos]
            self._base_index = index
            self._base_term = term
