"""Raft transports: in-memory (tests) and TCP (real clusters).

Reference behavior: nomad/raft_rpc.go ``RaftLayer`` carries raft RPCs
over the server's multiplexed TCP listener; Go tests use
raft.InmemTransport. RPCs here: request_vote, append_entries,
install_snapshot -- plus ``forward`` so followers can route
``apply`` calls to the leader (the analog of rpc.go:537 leader
forwarding).
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, Callable, Dict, Optional

Handler = Callable[[str, Dict], Dict]


class TransportRegistry:
    """Shared address space for in-memory transports (one per test)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._nodes: Dict[str, "InmemTransport"] = {}
        # partition simulation: set of (src, dst) pairs that drop
        self._cut: set = set()

    def register(self, addr: str, transport: "InmemTransport") -> None:
        """Register (or RE-register: a restarted server takes over its
        address — the old, closed transport stops resolving)."""
        with self._lock:
            self._nodes[addr] = transport

    def unregister(self, addr: str, transport: "InmemTransport") -> None:
        """Identity-guarded removal: only the transport that owns the
        address slot may vacate it (a restarted server's replacement
        must not be torn down by the dead one's late close)."""
        with self._lock:
            if self._nodes.get(addr) is transport:
                del self._nodes[addr]

    def lookup(self, addr: str) -> Optional["InmemTransport"]:
        with self._lock:
            return self._nodes.get(addr)

    def partition(self, a: str, b: str) -> None:
        """Cut connectivity both ways (fault injection)."""
        with self._lock:
            self._cut.add((a, b))
            self._cut.add((b, a))

    def heal(self) -> None:
        with self._lock:
            self._cut.clear()

    def is_cut(self, src: str, dst: str) -> bool:
        with self._lock:
            return (src, dst) in self._cut


class InmemTransport:
    """Direct-call transport (raft.InmemTransport analog)."""

    def __init__(self, addr: str, registry: TransportRegistry) -> None:
        self.addr = addr
        self.registry = registry
        self._handler: Optional[Handler] = None
        self._closed = False
        registry.register(addr, self)

    def set_handler(self, handler: Handler) -> None:
        self._handler = handler

    def send(self, target: str, method: str, req: Dict, timeout: float = 1.0) -> Dict:
        if self._closed:
            raise ConnectionError(f"transport at {self.addr} is closed")
        if self.registry.is_cut(self.addr, target):
            raise ConnectionError(f"partitioned: {self.addr} -> {target}")
        peer = self.registry.lookup(target)
        if peer is None or peer._handler is None or peer._closed:
            raise ConnectionError(f"no transport at {target}")
        return peer._handler(method, req)

    def close(self) -> None:
        """Go dark: a killed/shut-down node must stop answering AND
        stop originating (the restart harness re-registers a fresh
        transport at the same address)."""
        self._closed = True
        self.registry.unregister(self.addr, self)


class _TcpHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        try:
            while True:
                header = self.rfile.read(4)
                if len(header) < 4:
                    return
                (length,) = struct.unpack(">I", header)
                payload = self.rfile.read(length)
                method, req = pickle.loads(payload)
                resp = self.server.rpc_handler(method, req)  # type: ignore[attr-defined]
                out = pickle.dumps(resp)
                self.wfile.write(struct.pack(">I", len(out)) + out)
        except (ConnectionError, EOFError, OSError):
            return


class TcpTransport:
    """Length-prefixed pickle frames over TCP.

    The codec is trusted-cluster-internal, exactly like the reference's
    msgpack RPC (rpc.go:363): peers are authenticated by network
    position (and mTLS when enabled at the listener); payloads are
    never accepted from untrusted sources.
    """

    def __init__(self, bind_addr: str = "127.0.0.1", port: int = 0) -> None:
        self._server = socketserver.ThreadingTCPServer(
            (bind_addr, port), _TcpHandler, bind_and_activate=True
        )
        self._server.daemon_threads = True
        self._server.rpc_handler = self._dispatch  # type: ignore[attr-defined]
        self.addr = "%s:%d" % self._server.server_address
        self._handler: Optional[Handler] = None
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"raft-tcp-{self.addr}",
        )
        self._thread.start()
        self._conn_lock = threading.Lock()
        self._conns: Dict[str, socket.socket] = {}
        self._closed = False
        # one in-flight request per target connection: concurrent sends
        # on a shared socket would interleave frames / cross responses
        self._target_locks: Dict[str, threading.Lock] = {}

    def set_handler(self, handler: Handler) -> None:
        self._handler = handler

    def _dispatch(self, method: str, req: Dict) -> Dict:
        if self._closed:
            # server.shutdown() only stops NEW connections; handler
            # threads for established peer connections would keep
            # answering and make a stopped node look alive
            raise ConnectionError("transport closed")
        if self._handler is None:
            raise ConnectionError("handler not installed")
        return self._handler(method, req)

    def send(self, target: str, method: str, req: Dict, timeout: float = 2.0) -> Dict:
        payload = pickle.dumps((method, req))
        with self._conn_lock:
            tlock = self._target_locks.setdefault(target, threading.Lock())
        with tlock:
            return self._send_locked(target, payload, timeout)

    def _send_locked(self, target: str, payload: bytes, timeout: float) -> Dict:
        with self._conn_lock:
            conn = self._conns.get(target)
        try:
            if conn is None:
                host, port = target.rsplit(":", 1)
                conn = socket.create_connection((host, int(port)), timeout=timeout)
                with self._conn_lock:
                    self._conns[target] = conn
            conn.settimeout(timeout)
            conn.sendall(struct.pack(">I", len(payload)) + payload)
            header = self._recv_exact(conn, 4)
            (length,) = struct.unpack(">I", header)
            return pickle.loads(self._recv_exact(conn, length))
        except (OSError, EOFError) as e:
            with self._conn_lock:
                self._conns.pop(target, None)
            try:
                if conn is not None:
                    conn.close()
            except OSError:
                pass
            raise ConnectionError(f"rpc to {target} failed: {e}") from e

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise EOFError("connection closed")
            buf += chunk
        return buf

    def close(self) -> None:
        self._closed = True
        self._server.shutdown()
        self._server.server_close()
        with self._conn_lock:
            for c in self._conns.values():
                try:
                    c.close()
                except OSError:
                    pass
            self._conns.clear()
