"""Crash-safe raft durability: segmented WAL, stable store, snapshots.

Reference behavior: nomad wires hashicorp/raft to raft-boltdb (the log
+ stable store; server.go:1228 setupRaft) and a FileSnapshotStore — a
server that is killed recovers its term, vote, log, and FSM from its
data dir. This module is that plane for our raft (ISSUE 13):

- :class:`WriteAheadLog` — an append-only SEGMENTED journal of
  CRC32-framed records. Frame = ``>II`` header (payload length,
  crc32(payload)) + payload. Torn-tail recovery: a bad frame at the
  tail of the NEWEST segment is a torn write — the file is truncated
  at the frame boundary and replay stops (a clean prefix). A bad frame
  anywhere else (a sealed segment, or followed by parseable frames) is
  CORRUPTION and raises :class:`WalCorruptionError` — loud, never a
  silent divergence.
- :class:`DurableLogStore` — the raft LogStore journaled through the
  WAL: every append/truncate/compact is a framed record; replay
  rebuilds the in-memory log bit-identically.
- :class:`StableStore` — the tiny atomic-rename+fsync store for
  ``(current_term, voted_for)``, the raft HARD state: a restarted node
  that forgets its vote can vote twice in one term — a safety
  violation, not a liveness gap. Writes are monotone (a racing stale
  writer can never regress a newer persisted term/vote).
- :class:`SnapshotStore` — CRC-framed ``snapshot-<index>-<term>``
  files, written tmp + fsync + atomic rename, keep-last-2 with
  fallback to the older file when the newest fails its CRC.

Fsync policy (the ``fsync_policy`` knob, ServerConfig/HCL):

- ``"always"`` — every journaled record fsyncs on the writer thread
  before it returns. Maximum paranoia, one fsync per record.
- ``"batch"`` (default) — records are written+flushed immediately but
  fsync happens at the ACK boundaries (:meth:`WriteAheadLog.sync`),
  GROUP-COALESCED: concurrent syncers ride one fsync (the first
  through the gate fsyncs everything written so far; waiters whose
  frames that fsync covered return without their own). The PR 10/11
  batched-commit windows (wave group commit, eval group commit,
  client-update fan-in) already collapse a wave's writes into one
  raft apply, so the steady path pays roughly one fsync per wave, not
  per eval (docs/PERF.md "Group-fsync amortization").

Correctness ordering lives in raft/node.py: term/vote persist BEFORE
any RPC response that grants a vote or adopts a term; follower append
and leader replicate sync BEFORE ack.

Fail-stop: any write/fsync failure (real IO error or the injected
``wal.frame.torn`` / ``wal.sync`` fault points) marks the WAL failed
and every later write raises — a node that cannot persist must stop
acking, exactly like the reference panicking on a boltdb write error.
The raft ticker then DEMOTES the node (step down, never campaign)
so a healthy peer takes over; the restart harness kills + recovers
it — replay truncates the torn tail and the cluster re-replicates.

Recovery order (the restart constructor path, raft/node.py):
stable store → newest valid snapshot → ``restore_fn`` → WAL replay
into the log → committed entries re-apply into the FSM through the
normal apply loop as the commit index advances.

Counters land in :data:`wal_stats` and are exported as the
``nomad_tpu_raft_durability_*`` / ``nomad_tpu_raft_snapshot_*``
Prometheus series (telemetry/exporter.py); fsync latency records into
the ``wal_fsync`` op of ``nomad_tpu_latency_seconds``.
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from nomad_tpu.raft.log import LogEntry, LogStore
from nomad_tpu.telemetry.histogram import WAL_FSYNC, histograms
from nomad_tpu.telemetry.trace import consensus_recorder
from nomad_tpu.utils.faultpoints import FaultError, fault
from nomad_tpu.utils.witness import witness_lock

LOG = logging.getLogger(__name__)

#: frame header: payload length + crc32(payload)
_FRAME = struct.Struct(">II")
#: sanity bound on a single frame's payload (a flipped length byte
#: must not read as a plausible frame)
MAX_FRAME_BYTES = 64 * 1024 * 1024
#: rotate the live segment past this size
DEFAULT_SEGMENT_BYTES = 4 * 1024 * 1024


class WalCorruptionError(RuntimeError):
    """Unrecoverable on-disk state: mid-file corruption, a torn tail
    in a SEALED segment, or a compacted log with no valid snapshot.
    Deliberately loud — recovery never silently diverges."""


class DurabilityStats:
    """Process-wide durability accounting (every WAL/StableStore/
    SnapshotStore feeds it; multi-server tests share one). Gauge-like
    values (cache/disk snapshot bytes) are kept per owner and summed
    at snapshot time so co-resident servers never clobber each other.

    ISSUE 15: every note site also carries an ``owner`` (the server
    id), accumulated per owner so co-resident ``make_cluster`` servers
    stop blending into one truth — the exporter renders
    :meth:`per_server` with a ``server_id`` label next to the
    process-wide aggregates."""

    _PER_KEYS = ("frames", "fsyncs", "wal_fsyncs",
                 "fsync_batch_frames", "bytes_written",
                 "replayed_entries", "torn_truncations", "recoveries")

    def __init__(self) -> None:
        self._lock = witness_lock("wal.DurabilityStats._lock")
        self.fsyncs = 0
        self.frames = 0
        self.bytes_written = 0
        self.replayed_entries = 0
        self.torn_truncations = 0
        self.recoveries = 0
        self.snapshots_written = 0
        self.snapshots_pruned = 0
        self.snapshots_invalid = 0
        self._cache_bytes: Dict[str, int] = {}
        self._disk_bytes: Dict[str, int] = {}
        #: owner -> per-server counters (_PER_KEYS)
        self._per: Dict[str, Dict[str, int]] = {}
        #: owner -> live WAL occupancy (segments, pending frames, ...)
        self._occupancy: Dict[str, Dict[str, int]] = {}

    def _bump_locked(self, owner: str, key: str, n: int) -> None:
        if not owner:
            return
        row = self._per.get(owner)
        if row is None:
            row = self._per[owner] = {k: 0 for k in self._PER_KEYS}
        row[key] += n

    def note_frame(self, nbytes: int, owner: str = "") -> None:
        with self._lock:
            self.frames += 1
            self.bytes_written += nbytes
            self._bump_locked(owner, "frames", 1)
            self._bump_locked(owner, "bytes_written", nbytes)

    def note_fsync(self, owner: str = "", covered_frames: int = 0,
                   wal: bool = False) -> None:
        """One fsync; ``covered_frames`` is the group-fsync batch
        occupancy (how many journaled frames this sync made durable —
        the amortization the batched-commit windows buy). ``wal``
        marks WAL record fsyncs (group syncs + rotation seals): only
        those enter ``fsync_batch_avg``'s denominator, so stable-store
        term persists and snapshot-file fsyncs — which cover no frames
        by construction — cannot dilute the amortization gauge."""
        with self._lock:
            self.fsyncs += 1
            self._bump_locked(owner, "fsyncs", 1)
            if wal:
                self._bump_locked(owner, "wal_fsyncs", 1)
            if covered_frames:
                self._bump_locked(owner, "fsync_batch_frames",
                                  covered_frames)

    def note_replay(self, entries: int, owner: str = "") -> None:
        with self._lock:
            self.replayed_entries += entries
            self._bump_locked(owner, "replayed_entries", entries)

    def note_torn(self, owner: str = "") -> None:
        with self._lock:
            self.torn_truncations += 1
            self._bump_locked(owner, "torn_truncations", 1)

    def note_recovery(self, owner: str = "") -> None:
        with self._lock:
            self.recoveries += 1
            self._bump_locked(owner, "recoveries", 1)

    def note_wal_state(self, owner: str, segments: int,
                       pending_frames: int, live_segment_bytes: int,
                       failed: bool) -> None:
        """WAL occupancy gauge feed (segment count, frames written but
        not yet covered by an fsync, live-segment fill, fail-stop
        flag). Updated at sync/rotate/close — gauge cadence, not
        per-frame."""
        if not owner:
            return
        with self._lock:
            self._occupancy[owner] = {
                "segments": segments,
                "pending_frames": pending_frames,
                "live_segment_bytes": live_segment_bytes,
                "wal_failed": 1 if failed else 0,
            }

    def note_snapshot(self, written: int = 0, pruned: int = 0,
                      invalid: int = 0) -> None:
        with self._lock:
            self.snapshots_written += written
            self.snapshots_pruned += pruned
            self.snapshots_invalid += invalid

    def note_cache(self, owner: str, nbytes: int) -> None:
        """Meter one raft node's in-memory snapshot cache (ISSUE 13
        satellite: the cache was unbounded AND unmetered)."""
        with self._lock:
            if nbytes:
                self._cache_bytes[owner] = nbytes
            else:
                self._cache_bytes.pop(owner, None)

    def note_disk(self, owner: str, nbytes: int) -> None:
        with self._lock:
            if nbytes:
                self._disk_bytes[owner] = nbytes
            else:
                self._disk_bytes.pop(owner, None)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "fsyncs": self.fsyncs,
                "frames": self.frames,
                "bytes_written": self.bytes_written,
                "replayed_entries": self.replayed_entries,
                "torn_truncations": self.torn_truncations,
                "recoveries": self.recoveries,
                "snapshots_written": self.snapshots_written,
                "snapshots_pruned": self.snapshots_pruned,
                "snapshots_invalid": self.snapshots_invalid,
                "snapshot_cache_bytes": sum(self._cache_bytes.values()),
                "snapshot_disk_bytes": sum(self._disk_bytes.values()),
            }

    def per_server(self) -> Dict[str, Dict]:
        """Per-owner durability counters + WAL occupancy (ISSUE 15:
        the per-replica view the exporter labels with ``server_id``)."""
        with self._lock:
            out: Dict[str, Dict] = {}
            for owner in set(self._per) | set(self._occupancy):
                row = dict(self._per.get(
                    owner, {k: 0 for k in self._PER_KEYS}))
                row.update(self._occupancy.get(owner, {}))
                wal_fsyncs = row.get("wal_fsyncs", 0)
                row["fsync_batch_avg"] = round(
                    row.get("fsync_batch_frames", 0) / wal_fsyncs, 4) \
                    if wal_fsyncs else 0.0
                out[owner] = row
            return out

    def reset_stats(self) -> None:
        with self._lock:
            self.fsyncs = 0
            self.frames = 0
            self.bytes_written = 0
            self.replayed_entries = 0
            self.torn_truncations = 0
            self.recoveries = 0
            self.snapshots_written = 0
            self.snapshots_pruned = 0
            self.snapshots_invalid = 0
            self._cache_bytes.clear()
            self._disk_bytes.clear()
            self._per.clear()
            self._occupancy.clear()


#: process-wide durability counters (telemetry/exporter.py source)
wal_stats = DurabilityStats()


def frame(payload: bytes) -> bytes:
    """One CRC32-framed record: length + crc + payload."""
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def _parse_frame(data: bytes, offset: int) -> Optional[Tuple[int, bytes]]:
    """Parse one frame at ``offset``. Returns (next_offset, payload),
    or None when no valid frame starts there (short header, insane
    length, short payload, or CRC mismatch)."""
    end = len(data)
    if offset + _FRAME.size > end:
        return None
    length, crc = _FRAME.unpack_from(data, offset)
    if length > MAX_FRAME_BYTES or offset + _FRAME.size + length > end:
        return None
    payload = data[offset + _FRAME.size: offset + _FRAME.size + length]
    if zlib.crc32(payload) != crc:
        return None
    return offset + _FRAME.size + length, payload


def _valid_frame_follows(data: bytes, offset: int) -> bool:
    """Does any parseable frame start past a bad frame? If yes, the
    bad frame is mid-file CORRUPTION (a torn write can only ever be
    the last thing that hit the file). The scan runs to end-of-file —
    a bounded window would let a corrupted frame LARGER than the
    window hide the acked frames beyond it behind a "torn tail"
    truncation, the silent divergence this module forbids. Recovery
    is rare and segments are bounded; candidate offsets with
    implausible lengths fail before any CRC work."""
    for pos in range(offset + 1, len(data)):
        if _parse_frame(data, pos) is not None:
            return True
    return False


def _fsync_dir(path: str) -> None:
    """Directory fsync so a rename/creat survives the crash too. Best
    effort: not every filesystem supports fsync on a directory fd."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


# --- stable store --------------------------------------------------------

#: stable payload: term (u64) + voted-for length (u16) + utf-8 bytes
_STABLE = struct.Struct(">QH")


class StableStore:
    """Durable ``(current_term, voted_for)`` — the raft HARD state.

    One tiny file, written tmp + fsync + atomic rename (+ dir fsync).
    Writes are MONOTONE: term never regresses and a vote within a term
    is never un-cast, so racing writers (a vote grant racing a term
    adoption) can persist in any order without the durable state ever
    being older than any response already sent. Unchanged writes are
    free (the heartbeat path calls through here every term touch).
    """

    def __init__(self, data_dir: str, owner: str = "") -> None:
        self._dir = data_dir
        self._path = os.path.join(data_dir, "stable")
        self._owner = owner
        self._lock = witness_lock("wal.StableStore._lock")
        self._term = 0
        self._vote: Optional[str] = None
        self._loaded = False

    def load(self) -> Tuple[int, Optional[str]]:
        """Read the persisted hard state; (0, None) when none exists.
        A CRC mismatch is loud: the write path's atomic rename means a
        torn stable file cannot happen — a bad one is real corruption."""
        with self._lock:
            if self._loaded:
                return self._term, self._vote
            self._loaded = True
            if not os.path.exists(self._path):
                return 0, None
            with open(self._path, "rb") as f:
                data = f.read()
            parsed = _parse_frame(data, 0)
            if parsed is None:
                raise WalCorruptionError(
                    f"stable store {self._path} failed its CRC check")
            _, payload = parsed
            term, vlen = _STABLE.unpack_from(payload, 0)
            vote = payload[_STABLE.size:_STABLE.size + vlen].decode(
                "utf-8") if vlen else None
            self._term, self._vote = term, vote
            return term, vote

    def put(self, term: int, voted_for: Optional[str]) -> None:
        """Persist, monotone. Must complete BEFORE any RPC response
        that grants a vote or adopts the term (raft/node.py)."""
        vote_bytes = voted_for.encode("utf-8") if voted_for else b""
        payload = _STABLE.pack(term, len(vote_bytes)) + vote_bytes
        blob = frame(payload)
        with self._lock:
            if term < self._term:
                return              # stale racer: durable state is newer
            if term == self._term and (voted_for == self._vote
                                       or voted_for is None):
                return              # no change / never un-cast a vote
            tmp = self._path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path)
            _fsync_dir(self._dir)
            self._term, self._vote = term, voted_for
            wal_stats.note_fsync(self._owner)


# --- snapshot store ------------------------------------------------------

#: snapshot payload prefix: index (u64) + term (u64); data follows
_SNAP = struct.Struct(">QQ")
_SNAP_KEEP = 2


class SnapshotStore:
    """CRC-framed ``snapshot-<index>-<term>.snap`` files; atomic
    rename, keep-last-:data:`_SNAP_KEEP` with CRC-validated fallback to
    the older file. The on-disk file is PREFERRED over re-forcing an
    FSM capture when a lagging peer needs a snapshot (raft/node.py)."""

    def __init__(self, data_dir: str, owner: str = "") -> None:
        self._dir = data_dir
        self._owner = owner or data_dir
        self._lock = witness_lock("wal.SnapshotStore._lock")

    def _paths(self) -> List[Tuple[int, int, str]]:
        """(index, term, path) for every snapshot file, newest first."""
        out = []
        try:
            names = os.listdir(self._dir)
        except OSError:
            return []
        for name in names:
            if not (name.startswith("snapshot-") and name.endswith(".snap")):
                continue
            parts = name[len("snapshot-"):-len(".snap")].split("-")
            if len(parts) != 2:
                continue
            try:
                out.append((int(parts[0]), int(parts[1]),
                            os.path.join(self._dir, name)))
            except ValueError:
                continue
        out.sort(reverse=True)
        return out

    def save(self, index: int, term: int, data: bytes) -> str:
        """Write ``snapshot-<index>-<term>`` durably; prune to the
        newest :data:`_SNAP_KEEP`. Called BEFORE WAL compaction so a
        crash between the two recovers from this file + the full WAL."""
        payload = _SNAP.pack(index, term) + data
        blob = frame(payload)
        path = os.path.join(self._dir, f"snapshot-{index:020d}-{term}.snap")
        with self._lock:
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                # mid-snapshot-write seam (chaos plane): a kill here
                # leaves only the tmp file — recovery ignores it and
                # falls back to the previous snapshot + the uncompacted
                # WAL; an error propagates (the capture fails whole)
                fault("wal.snapshot.write")
                os.fsync(f.fileno())
            os.replace(tmp, path)
            _fsync_dir(self._dir)
            wal_stats.note_fsync(self._owner)
            wal_stats.note_snapshot(written=1)
            pruned = 0
            for _, _, old in self._paths()[_SNAP_KEEP:]:
                try:
                    os.unlink(old)
                    pruned += 1
                except OSError:
                    pass
            if pruned:
                wal_stats.note_snapshot(pruned=pruned)
            wal_stats.note_disk(self._owner, sum(
                os.path.getsize(p) for _, _, p in self._paths()))
        return path

    def load_newest(self) -> Optional[Tuple[int, int, bytes]]:
        """Newest snapshot that passes its CRC, or None. An invalid
        newest file falls back to the older one (keep-last-2 is FOR
        this: a crash mid-rename or bit rot must not strand the node)."""
        with self._lock:
            for index, term, path in self._paths():
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError:
                    continue
                parsed = _parse_frame(data, 0)
                if parsed is None:
                    LOG.warning("snapshot %s failed CRC; trying older",
                                path)
                    wal_stats.note_snapshot(invalid=1)
                    continue
                _, payload = parsed
                pidx, pterm = _SNAP.unpack_from(payload, 0)
                if pidx != index or pterm != term:
                    wal_stats.note_snapshot(invalid=1)
                    continue
                return index, term, payload[_SNAP.size:]
            return None


# --- the segmented WAL ---------------------------------------------------

class WriteAheadLog:
    """Append-only segmented journal of CRC-framed records.

    Segments are ``wal-<seq>.seg``; the newest is live, the rest are
    sealed (fsynced at rotation). Per-segment max-touched-index makes
    post-compaction deletion safe: a sealed segment whose every record
    touches indexes <= the snapshot index is wholly superseded by it.
    """

    def __init__(self, wal_dir: str, fsync_policy: str = "batch",
                 segment_max_bytes: int = DEFAULT_SEGMENT_BYTES,
                 owner: str = "") -> None:
        if fsync_policy not in ("always", "batch"):
            raise ValueError(
                f"fsync_policy must be 'always' or 'batch', "
                f"got {fsync_policy!r}")
        os.makedirs(wal_dir, exist_ok=True)
        self.dir = wal_dir
        self.fsync_policy = fsync_policy
        self.segment_max_bytes = segment_max_bytes
        self.owner = owner
        self._lock = witness_lock("wal.WriteAheadLog._lock")
        self._sync_lock = witness_lock("wal.WriteAheadLog._sync_lock")
        self._file = None
        self._seq = 0
        self._size = 0
        self._written = 0            # frames written (monotonic)
        self._synced = 0             # frames covered by an fsync
        self._max_touched = 0        # current segment
        self._sealed: List[Tuple[int, int, str]] = []  # (seq, max_idx, path)
        self._failed = False

    # -- recovery ---------------------------------------------------------

    def _segment_paths(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("wal-") and name.endswith(".seg"):
                try:
                    out.append((int(name[4:-4]), os.path.join(self.dir, name)))
                except ValueError:
                    continue
        out.sort()
        return out

    def replay(self) -> List[Any]:
        """Read every segment in order; return the decoded records.
        Torn-tail semantics: a bad frame in the NEWEST segment with no
        parseable frame after it truncates the file there (a clean
        prefix — counted in ``torn_truncations``); anything else
        raises :class:`WalCorruptionError`. Leaves the WAL positioned
        to append to the newest segment (or a fresh one)."""
        records: List[Any] = []
        segments = self._segment_paths()
        self._sealed = []
        for pos, (seq, path) in enumerate(segments):
            last_segment = pos == len(segments) - 1
            with open(path, "rb") as f:
                data = f.read()
            offset = 0
            seg_max = 0
            while offset < len(data):
                parsed = _parse_frame(data, offset)
                if parsed is None:
                    if not last_segment:
                        raise WalCorruptionError(
                            f"bad frame at {path}:{offset} in a SEALED "
                            "segment (rotation fsynced it whole): "
                            "mid-log corruption, refusing to guess")
                    if _valid_frame_follows(data, offset):
                        raise WalCorruptionError(
                            f"bad frame at {path}:{offset} followed by "
                            "parseable frames: mid-log corruption, not "
                            "a torn tail; refusing to silently drop "
                            "acknowledged records")
                    # a genuine torn tail: truncate at the frame
                    # boundary and recover the clean prefix
                    LOG.warning("wal: truncating torn tail at %s:%d "
                                "(%d bytes dropped)", path, offset,
                                len(data) - offset)
                    with open(path, "r+b") as f:
                        f.truncate(offset)
                        f.flush()
                        os.fsync(f.fileno())
                    wal_stats.note_torn(self.owner)
                    break
                offset, payload = parsed
                record = pickle.loads(payload)
                records.append(record)
                seg_max = max(seg_max, _record_touched(record))
            if last_segment:
                self._seq = seq
                self._size = offset
                self._max_touched = seg_max
            else:
                self._sealed.append((seq, seg_max, path))
        if segments:
            self._file = open(segments[-1][1], "ab")
        else:
            self._open_segment(0)
        self._note_occupancy_locked()
        return records

    def _open_segment(self, seq: int) -> None:
        self._seq = seq
        self._size = 0
        self._max_touched = 0
        path = os.path.join(self.dir, f"wal-{seq:08d}.seg")
        self._file = open(path, "ab")
        _fsync_dir(self.dir)

    # -- writes -----------------------------------------------------------

    def encode(self, record: Any) -> bytes:
        """Pickle a record OUTSIDE any lock (graftcheck R2: callers
        hold the log store lock around write(), never around this)."""
        return pickle.dumps(record)

    def write(self, payload: bytes, touched: int = 0) -> None:
        """Append one framed record to the live segment (flush, no
        fsync under the batch policy — sync() is the durability
        boundary). Failure is fail-stop."""
        blob = frame(payload)
        with self._lock:
            if self._failed:
                raise WalCorruptionError(
                    "wal is failed (a previous write/fsync error); "
                    "the node must restart and recover")
            try:
                try:
                    # torn-write seam (chaos plane): a fire writes only
                    # a PREFIX of the frame — exactly what a crash
                    # mid-write leaves — then fails the WAL (fail-stop:
                    # nothing may be journaled after a torn frame, or
                    # recovery would read mid-file garbage)
                    fault("wal.frame.torn")
                except FaultError:
                    self._file.write(blob[: max(len(blob) // 2, 1)])
                    self._file.flush()
                    raise
                self._file.write(blob)
                self._file.flush()
            except BaseException:
                self._failed = True
                raise
            self._written += 1
            self._size += len(blob)
            self._max_touched = max(self._max_touched, touched)
            wal_stats.note_frame(len(blob), self.owner)
            if self._size >= self.segment_max_bytes:
                self._rotate_locked()
        if self.fsync_policy == "always":
            self.sync()

    @property
    def failed(self) -> bool:
        return self._failed

    def _rotate_locked(self) -> None:
        """Seal the live segment (fsync whole) and open the next.
        Everything written so far is in the sealed file, so the synced
        watermark jumps to the written watermark."""
        f = self._file
        f.flush()
        os.fsync(f.fileno())
        f.close()
        wal_stats.note_fsync(self.owner,
                             covered_frames=self._written - self._synced,
                             wal=True)
        path = os.path.join(self.dir, f"wal-{self._seq:08d}.seg")
        self._sealed.append((self._seq, self._max_touched, path))
        self._synced = self._written
        self._open_segment(self._seq + 1)
        self._note_occupancy_locked()

    def _note_occupancy_locked(self) -> None:
        wal_stats.note_wal_state(
            self.owner, segments=len(self._sealed) + 1,
            pending_frames=self._written - self._synced,
            live_segment_bytes=self._size, failed=self._failed)

    def sync(self) -> None:
        """Make every written frame durable. Group-coalesced: the
        first syncer through the gate fsyncs everything written so
        far; concurrent syncers whose frames that fsync covered return
        without touching the disk (the group-commit discipline the
        batched raft applies upstream already shape the traffic for)."""
        with self._lock:
            if self._failed:
                raise WalCorruptionError("wal is failed; restart to recover")
            if self._synced >= self._written:
                return
        # kill-between-frame-write-and-fsync seam (chaos plane): the
        # frames are in the page cache but NOT durable — a kill here is
        # the canonical torn-tail crash recovery must absorb
        fault("wal.sync")
        t0 = time.perf_counter()
        with self._sync_lock:
            with self._lock:
                target = self._written
                if self._synced >= target:
                    return
                f = self._file
            try:
                os.fsync(f.fileno())
            except BaseException:
                with self._lock:
                    # a racing rotation seals (fsyncs) the captured
                    # file and swaps in a fresh one — its ValueError/
                    # EBADF here is NOT a disk failure: the rotation
                    # already made everything we cover durable
                    if self._synced >= target:
                        return
                    self._failed = True
                raise
            with self._lock:
                # batch occupancy is claimed AT the watermark move: a
                # rotation racing this sync already counted (and
                # advanced past) these frames — claiming them again
                # would double-count fsync_batch_frames
                covered = max(target - self._synced, 0)
                if target > self._synced:
                    self._synced = target
                self._note_occupancy_locked()
        dur = time.perf_counter() - t0
        wal_stats.note_fsync(self.owner, covered_frames=covered,
                             wal=True)
        histograms.get(WAL_FSYNC).record(dur)
        # consensus flight recorder: a group fsync past the adaptive
        # p99 bar gets captured for /v1/operator/slow-raft (ISSUE 15)
        consensus_recorder.observe(WAL_FSYNC, dur, server_id=self.owner)

    def compact_through(self, index: int) -> None:
        """Delete sealed segments wholly superseded by a snapshot at
        ``index``. Caller must have journaled + synced the compact
        record first (a crash after deletion must still replay it).
        STRICTLY below: a sealed segment whose max touched index
        EQUALS the compaction index may hold the compact record
        itself (the journaling write can trigger the rotation that
        seals it) — deleting it would erase the only record that
        re-bases the log, leaving replay mid-stream at base 0."""
        with self._lock:
            keep = []
            for seq, max_idx, path in self._sealed:
                if max_idx < index:
                    try:
                        os.unlink(path)
                    except OSError:
                        keep.append((seq, max_idx, path))
                else:
                    keep.append((seq, max_idx, path))
            self._sealed = keep

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.flush()
                    self._file.close()
                except OSError:
                    pass
                self._file = None


# --- WAL record codec ----------------------------------------------------

def _record_touched(record: Tuple) -> int:
    """The highest log index a record's information touches (segment
    deletion safety: a sealed segment is deletable only when every
    record in it touches indexes at or below the snapshot)."""
    kind = record[0]
    if kind == "entry":
        return record[1]
    # ("truncate", index) / ("compact", index, term)
    return record[1]


def replay_records(records: List[Tuple]):
    """Reconstruct (base_index, base_term, entries) from a record
    stream, INDEX-keyed — never positional. After a compaction deletes
    superseded segments the retained stream can start mid-log (its
    first appends sit above a base whose compact record was itself in
    a deleted segment), so positional replay through the live
    LogStore arithmetic would mis-aim truncates until the first
    retained compact record lands. Index-keyed replay is exact for
    every stream the write path can produce AND for every prefix of
    one (the torn-tail fuzz's divergence oracle reuses it)."""
    entries: List[LogEntry] = []
    base_index = 0
    base_term = 0
    for record in records:
        kind = record[0]
        if kind == "entry":
            _, index, term, ekind, data = record
            # a re-append at an existing index is the journaled form
            # of conflict resolution: it supersedes the old suffix
            while entries and entries[-1].index >= index:
                entries.pop()
            entries.append(
                LogEntry(index=index, term=term, kind=ekind, data=data))
        elif kind == "truncate":
            while entries and entries[-1].index >= record[1]:
                entries.pop()
        elif kind == "compact":
            index, term = record[1], record[2]
            if index >= base_index:
                base_index, base_term = index, term
                while entries and entries[0].index <= index:
                    entries.pop(0)
        else:
            raise WalCorruptionError(
                f"unknown wal record kind {kind!r}")
    return base_index, base_term, entries


class DurableLogStore(LogStore):
    """The raft LogStore journaled through a :class:`WriteAheadLog`.

    Every mutation appends a framed record inside the same lock scope
    as the in-memory change (journal order == memory order); recovery
    replays the records into a bit-identical log. ``sync()`` is the
    ack boundary the raft node calls before responding durably.
    """

    def __init__(self, wal_dir: str, fsync_policy: str = "batch",
                 segment_max_bytes: int = DEFAULT_SEGMENT_BYTES,
                 owner: str = "") -> None:
        super().__init__()
        self._wal = WriteAheadLog(wal_dir, fsync_policy=fsync_policy,
                                  segment_max_bytes=segment_max_bytes,
                                  owner=owner)
        records = self._wal.replay()
        base_index, base_term, entries = replay_records(records)
        # the recovered log must be contiguous from its base — a hole
        # means the record stream lost something it should not have
        # (e.g. a deleted segment that was still load-bearing): refuse
        # loudly, never serve positional reads over a gapped list
        expect = base_index + 1
        for e in entries:
            if e.index != expect:
                raise WalCorruptionError(
                    f"recovered log is not contiguous: expected index "
                    f"{expect}, found {e.index} (base {base_index}) — "
                    "refusing to boot over a gapped log")
            expect += 1
        self._base_index = base_index
        self._base_term = base_term
        self._entries = entries
        self.replayed_entries = len(entries)
        wal_stats.note_replay(self.replayed_entries, owner)

    @property
    def wal(self) -> WriteAheadLog:
        return self._wal

    @property
    def wal_failed(self) -> bool:
        return self._wal.failed

    # -- journaled mutators ----------------------------------------------

    def append(self, entry: LogEntry) -> None:
        payload = self._wal.encode(
            ("entry", entry.index, entry.term, entry.kind, entry.data))
        with self._lock:
            super().append(entry)
            self._wal.write(payload, touched=entry.index)

    def truncate_from(self, index: int) -> None:
        payload = self._wal.encode(("truncate", index))
        with self._lock:
            super().truncate_from(index)
            self._wal.write(payload, touched=index)

    def compact_to(self, index: int, term: int) -> None:
        payload = self._wal.encode(("compact", index, term))
        with self._lock:
            super().compact_to(index, term)
            self._wal.write(payload, touched=index)
        # the compact record must be durable BEFORE superseded
        # segments disappear (crash in between must still replay it)
        self._wal.sync()
        self._wal.compact_through(index)

    # -- durability boundary ---------------------------------------------

    def sync(self) -> None:
        self._wal.sync()

    def persist(self) -> None:
        """No-op: the WAL is the persistence; the base class's
        whole-log pickle rewrite (the seed behavior ISSUE 13 replaces)
        would double-write everything per snapshot."""

    def close(self) -> None:
        self._wal.close()
