"""Consensus-plane observability: per-server raft stats + event log.

ISSUE 15: PRs 12-13 made the cluster plane real but observability-dark
— ``make_cluster`` servers blended into one process-global registry,
and a red chaos run was diagnosed by reading logs. This module is the
per-server substrate the rest of the consensus observability layer
builds on:

- :class:`RaftObserver` — a process-wide registry of per-``server_id``
  consensus stats (term/state/commit gauges read live from the node,
  election/term/step-down transition counters, per-peer replication
  lag, snapshot-transfer meters). Exported with a ``server_id`` label
  (telemetry/exporter.py), so a 3-node in-process cluster reports
  three distinguishable truths instead of one blended one.
- the **consensus event log** — a bounded, monotonic-stamped ring of
  election/term/leadership/recovery events across every server in the
  process. The failover timeline (telemetry/timeline.py) merges it
  with fault-point firings and span streams into the causally-ordered
  ``CHAOS_TIMELINE.json`` artifact the chaos/restart cells emit.

Cost discipline: recording a transition event is one bounded deque
append under a small witness lock — elections and step-downs are rare.
Per-RPC costs live in raft/node.py and are O(ns-µs) with tracing off
(a dict store for the append stamp, an always-on histogram record per
commit advance — the PR 8 histogram budget).
"""

from __future__ import annotations

import time
import weakref
from collections import deque
from typing import Dict, List, Optional

from nomad_tpu.utils.witness import witness_lock

__all__ = ["RaftObserver", "raft_observer"]

#: consensus event kinds the timeline understands (docs/TELEMETRY.md
#: "Consensus plane"); anything else is carried verbatim
EVENT_KINDS = (
    "election_start", "leader_won", "term_adopt", "stepdown",
    "killed", "wal_failed", "recovery", "snapshot_install",
    "established", "revoked", "converged", "lease_expired",
)

#: most servers ever tracked (tests boot hundreds of short-lived
#: servers; the observer must not grow with them)
_MAX_SERVERS = 64
#: consensus events retained (a chaos cell produces tens, not
#: thousands — elections are rare by construction)
_MAX_EVENTS = 4096


class _ServerObs:
    """One server's consensus counters. The live gauges (term, state,
    commit index, per-peer lag) are read from the node itself at
    snapshot time through a weakref — counters survive the node."""

    __slots__ = ("server_id", "node_ref", "transitions",
                 "replicated_entries", "peer_lag_ms", "xfer_bytes",
                 "registered_mono")

    def __init__(self, server_id: str) -> None:
        self.server_id = server_id
        self.node_ref = None
        #: kind -> count (election/leader/term/stepdown/recovery)
        self.transitions: Dict[str, int] = {}
        #: peer -> entries acked by that peer (leader-side)
        self.replicated_entries: Dict[str, int] = {}
        #: peer -> newest observed append->ack lag in ms (leader-side)
        self.peer_lag_ms: Dict[str, float] = {}
        #: direction ("sent"/"received") -> snapshot transfer bytes
        self.xfer_bytes: Dict[str, int] = {}
        self.registered_mono = time.monotonic()


class RaftObserver:
    """Process-wide per-server consensus stats + the shared event log.

    Lock order: the observer lock is a LEAF — nothing is called while
    holding it except dict/deque operations. Live-node reads at
    snapshot time happen OUTSIDE the lock (the node's own lock guards
    them), so ``observer -> node`` never nests.
    """

    def __init__(self) -> None:
        self._lock = witness_lock("raft.observe.RaftObserver._lock")
        self._servers: Dict[str, _ServerObs] = {}
        self._events: deque = deque(maxlen=_MAX_EVENTS)

    # --- registration ----------------------------------------------------

    def register(self, server_id: str, node=None) -> None:
        """Register (or RE-register: a restarted server takes over its
        id, keeping accumulated counters for timeline continuity)."""
        with self._lock:
            obs = self._servers.get(server_id)
            if obs is None:
                if len(self._servers) >= _MAX_SERVERS:
                    oldest = min(self._servers.values(),
                                 key=lambda o: o.registered_mono)
                    del self._servers[oldest.server_id]
                obs = self._servers[server_id] = _ServerObs(server_id)
            obs.registered_mono = time.monotonic()
            obs.node_ref = weakref.ref(node) if node is not None else None

    def unregister(self, server_id: str) -> None:
        """Drop the live-node ref (shutdown); counters + events stay."""
        with self._lock:
            obs = self._servers.get(server_id)
            if obs is not None:
                obs.node_ref = None

    # --- recording -------------------------------------------------------

    def note_event(self, server_id: str, kind: str,
                   term: Optional[int] = None,
                   index: Optional[int] = None,
                   detail: Optional[Dict] = None) -> None:
        """Append one consensus event to the shared ring (the timeline
        feed). Bounded; cheap; safe from any thread."""
        ev = {
            "t": time.monotonic(),
            "wall": time.time(),
            "server": server_id,
            "kind": kind,
        }
        if term is not None:
            ev["term"] = term
        if index is not None:
            ev["index"] = index
        if detail:
            ev["detail"] = detail
        with self._lock:
            self._events.append(ev)

    def note_transition(self, server_id: str, kind: str) -> None:
        with self._lock:
            obs = self._servers.get(server_id)
            if obs is not None:
                obs.transitions[kind] = obs.transitions.get(kind, 0) + 1

    def note_replicated(self, server_id: str, peer: str, entries: int,
                        lag_ms: Optional[float] = None) -> None:
        """Leader-side: ``entries`` acked by ``peer``; ``lag_ms`` is
        the newest append->ack latency when an append stamp existed."""
        with self._lock:
            obs = self._servers.get(server_id)
            if obs is None:
                return
            obs.replicated_entries[peer] = (
                obs.replicated_entries.get(peer, 0) + entries)
            if lag_ms is not None:
                obs.peer_lag_ms[peer] = lag_ms

    def note_snapshot_xfer(self, server_id: str, direction: str,
                           nbytes: int) -> None:
        with self._lock:
            obs = self._servers.get(server_id)
            if obs is not None:
                obs.xfer_bytes[direction] = (
                    obs.xfer_bytes.get(direction, 0) + nbytes)

    # --- introspection ---------------------------------------------------

    def staleness_ms(self, server_id: str) -> Optional[float]:
        """Leader-attributed replication staleness for ``server_id``:
        the newest append->ack lag any leader recorded for it as a
        peer (ISSUE 20 read plane). Multiple observers may carry an
        entry (a deposed leader's last measurement lingers); the MAX
        wins — the meter may overstate staleness, never understate
        it. None when no leader ever measured this server."""
        with self._lock:
            worst: Optional[float] = None
            for obs in self._servers.values():
                lag = obs.peer_lag_ms.get(server_id)
                if lag is not None and (worst is None or lag > worst):
                    worst = lag
            return worst

    def events(self, since_mono: float = 0.0) -> List[Dict]:
        """The consensus event ring, oldest first (the timeline feed).
        ``since_mono`` filters to events at/after a monotonic stamp."""
        with self._lock:
            out = list(self._events)
        if since_mono:
            out = [e for e in out if e["t"] >= since_mono]
        return out

    def snapshot(self) -> Dict[str, Dict]:
        """Per-server stats for the exporter: counters from the
        observer, live gauges from the node (read outside the lock)."""
        with self._lock:
            rows = [(obs.server_id, obs.node_ref,
                     dict(obs.transitions),
                     dict(obs.replicated_entries),
                     dict(obs.peer_lag_ms), dict(obs.xfer_bytes))
                    for obs in self._servers.values()]
        out: Dict[str, Dict] = {}
        for sid, ref, transitions, replicated, lag_ms, xfer in rows:
            row = {
                "transitions": transitions,
                "replicated_entries": replicated,
                "peer_lag_ms": lag_ms,
                "snapshot_xfer_bytes": xfer,
                "live": False,
            }
            node = ref() if ref is not None else None
            if node is not None:
                try:
                    row.update(node.observe_gauges())
                    row["live"] = True
                except Exception:               # noqa: BLE001
                    pass        # node mid-shutdown: counters only
            out[sid] = row
        return out

    def reset_stats(self) -> None:
        """Clear counters + events (burst windowing, telemetry.reset).
        Registrations (live-node refs) survive."""
        with self._lock:
            self._events.clear()
            for obs in self._servers.values():
                obs.transitions.clear()
                obs.replicated_entries.clear()
                obs.peer_lag_ms.clear()
                obs.xfer_bytes.clear()


#: process-wide observer (telemetry/exporter.py + timeline feed)
raft_observer = RaftObserver()
