"""Server gossip membership (server/membership.py; reference
nomad/serf.go + hashicorp/serf SWIM): liveness-probed member status,
failure detection, graceful leave, refutation, join-by-DNS, and the
leader's membership-driven raft peer add/remove
(leader.go:1182-1345)."""

import socket
import time

import pytest

from nomad_tpu.server.membership import (
    ALIVE,
    FAILED,
    LEFT,
    MEMBER_FAILED,
    MEMBER_JOIN,
    MEMBER_LEAVE,
    Membership,
    expand_join_addrs,
)


def _wait(fn, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if fn():
                return True
        except Exception:                        # noqa: BLE001
            pass
        time.sleep(0.05)
    return False


def _mk(name, **kw):
    kw.setdefault("probe_interval", 0.1)
    kw.setdefault("probe_timeout", 0.25)
    kw.setdefault("suspect_timeout", 0.5)
    m = Membership(name=name, **kw)
    m.start()
    return m


@pytest.fixture()
def trio():
    ms = [_mk(f"srv-{i}", tags={"idx": str(i)}) for i in range(3)]
    seed = [(ms[0].host, ms[0].port)]
    for m in ms[1:]:
        m.join(seed)
    try:
        yield ms
    finally:
        for m in ms:
            m.shutdown(leave=False)


class TestMembership:
    def test_join_converges_to_full_view(self, trio):
        for m in trio:
            assert _wait(lambda m=m: len(m.members()) == 3), \
                f"{m.name} sees {m.members()}"
            assert all(r["Status"] == ALIVE for r in m.members())
        # tags gossiped through the seed, not just direct contacts
        view = {r["Name"]: r for r in trio[2].members()}
        assert view["srv-1"]["Tags"]["idx"] == "1"

    def test_member_join_events_fire(self):
        events = []
        a = _mk("a", on_event=lambda k, m: events.append((k, m["Name"])))
        b = _mk("b")
        try:
            b.join([(a.host, a.port)])
            assert _wait(lambda: (MEMBER_JOIN, "b") in events)
        finally:
            a.shutdown(leave=False)
            b.shutdown(leave=False)

    def test_crashed_member_detected_as_failed(self, trio):
        events = []
        trio[0].on_event(lambda k, m: events.append((k, m["Name"])))
        for m in trio:
            assert _wait(lambda m=m: len(m.members()) == 3)
        trio[2]._abort()   # crash: no leave message
        assert _wait(
            lambda: trio[0].member_status("srv-2") == FAILED, timeout=15)
        assert (MEMBER_FAILED, "srv-2") in events
        # dissemination: the non-probing observer converges too
        assert _wait(
            lambda: trio[1].member_status("srv-2") == FAILED, timeout=15)

    def test_graceful_leave_is_not_a_failure(self, trio):
        events = []
        trio[0].on_event(lambda k, m: events.append((k, m["Name"])))
        for m in trio:
            assert _wait(lambda m=m: len(m.members()) == 3)
        trio[2].shutdown(leave=True)
        assert _wait(lambda: trio[0].member_status("srv-2") == LEFT,
                     timeout=15)
        assert (MEMBER_LEAVE, "srv-2") in events
        assert (MEMBER_FAILED, "srv-2") not in events

    def test_false_suspicion_is_refuted(self, trio):
        for m in trio:
            assert _wait(lambda m=m: len(m.members()) == 3)
        # inject a rumor: srv-0 gossips that srv-2 is suspect at its
        # current incarnation; srv-2 must bump + re-assert aliveness
        with trio[0]._lock:
            target = trio[0]._members["srv-2"]
            target.status = "suspect"
            trio[0]._suspect_since["srv-2"] = time.monotonic()
        assert _wait(
            lambda: trio[0].member_status("srv-2") == ALIVE, timeout=15), \
            trio[0].members()

    def test_expand_join_addrs_resolves_dns(self):
        out = expand_join_addrs(["localhost:4649"])
        assert ("127.0.0.1", 4649) in out
        # port defaulting
        out = expand_join_addrs(["127.0.0.1"], default_port=4648)
        assert ("127.0.0.1", 4648) in out
        # unresolvable names are skipped, not fatal
        assert expand_join_addrs(["no-such-host.invalid:1"]) == []


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class TestAgentMembership:
    """The serf.go flow end-to-end: HA agents discover each other via
    gossip, `server members` reflects liveness, and the leader prunes
    a crashed server's raft peer without operator action."""

    @pytest.fixture()
    def ha_trio(self):
        from nomad_tpu.api.agent import Agent, AgentConfig

        ports = _free_ports(3)
        peers = [f"127.0.0.1:{p}" for p in ports]
        agents = []
        try:
            for i in range(3):
                a = Agent(AgentConfig(
                    name=f"srv-{i}", num_schedulers=1,
                    raft_port=ports[i], raft_peers=peers,
                    serf_probe_interval=0.1, serf_suspect_timeout=0.5,
                ))
                a.start()
                agents.append(a)
                if i > 0:
                    # join the first agent's membership endpoint
                    a._serf.join([(agents[0]._serf.host,
                                   agents[0]._serf.port)])
            assert _wait(
                lambda: any(x.server.is_leader() for x in agents),
                timeout=30)
            yield agents
        finally:
            for a in agents:
                try:
                    a.shutdown()
                except Exception:                # noqa: BLE001
                    pass

    def test_members_reflect_gossip_and_leader_flag(self, ha_trio):
        for a in ha_trio:
            assert _wait(lambda a=a: len(a.members()) == 3, timeout=15), \
                a.members()
        # the Leader flag rides gossip AFTER the election settles:
        # asserting it at the instant the member count converges raced
        # under suite CPU contention — wait for the flag like the count
        assert _wait(
            lambda: sum(1 for m in ha_trio[1].members()
                        if m.get("Leader")) == 1,
            timeout=15), ha_trio[1].members()

    def test_crashed_server_reaped_from_raft_peers(self, ha_trio):
        for a in ha_trio:
            assert _wait(lambda a=a: len(a.members()) == 3, timeout=15)
        leader = next(a for a in ha_trio if a.server.is_leader())
        victim = next(a for a in ha_trio if a is not leader)
        victim_raft = victim.server.raft.id
        victim_name = victim.config.name
        # crash: kill membership without leave, then the server itself
        victim._serf._abort()
        victim.server.shutdown()
        # the leader's failure detector marks it failed...
        assert _wait(
            lambda: leader._serf.member_status(victim_name) == FAILED,
            timeout=20), leader.members()
        # ...and membership-driven reconcile prunes the raft peer
        assert _wait(
            lambda: victim_raft not in leader.server.raft.peers,
            timeout=20), leader.server.raft.peers
        # the cluster still has a functioning leader
        assert _wait(lambda: any(
            a is not victim and a.server.is_leader() for a in ha_trio))


class TestGossipAuth:
    """HMAC-authenticated gossip (agent `encrypt` config; serf keyring
    analog). Closes the forged member-leave takedown: without a key,
    one spoofed UDP datagram removed a live server from the cluster
    view (and, via reconcile, the raft voter set)."""

    def test_keyed_cluster_converges(self):
        a = _mk("auth-a", encrypt="cluster-secret")
        b = _mk("auth-b", encrypt="cluster-secret")
        try:
            b.join([(a.host, a.port)])
            assert _wait(lambda: a.member_status("auth-b") == ALIVE)
            assert _wait(lambda: b.member_status("auth-a") == ALIVE)
        finally:
            a.shutdown(leave=False)
            b.shutdown(leave=False)

    def test_forged_leave_rejected_without_key(self):
        """An attacker on the segment (no key) cannot make a keyed
        member believe its peer left."""
        import json as _json

        a = _mk("auth-a", encrypt="cluster-secret")
        b = _mk("auth-b", encrypt="cluster-secret")
        try:
            b.join([(a.host, a.port)])
            assert _wait(lambda: a.member_status("auth-b") == ALIVE)
            # forge an unsigned leave claiming to be b
            forged = _json.dumps({
                "t": "leave", "from": "auth-b", "region": a.region,
                "mem": [["auth-b", b.host, b.port, 1 << 31, LEFT, {}]],
            }).encode()
            attacker = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            before = a.rx_rejected
            for _ in range(3):
                attacker.sendto(forged, (a.host, a.port))
            attacker.close()
            assert _wait(lambda: a.rx_rejected >= before + 3)
            # b is still alive in a's view: the takedown failed
            assert a.member_status("auth-b") == ALIVE
        finally:
            a.shutdown(leave=False)
            b.shutdown(leave=False)

    def test_wrong_key_rejected(self):
        a = _mk("auth-a", encrypt="right-key")
        c = _mk("auth-c", encrypt="wrong-key")
        try:
            c.join([(a.host, a.port)])
            time.sleep(0.5)
            assert a.member_status("auth-c") is None
            assert a.rx_rejected > 0
        finally:
            a.shutdown(leave=False)
            c.shutdown(leave=False)

    def test_unkeyed_cluster_still_accepts_plain(self):
        a = _mk("plain-a")
        b = _mk("plain-b")
        try:
            b.join([(a.host, a.port)])
            assert _wait(lambda: a.member_status("plain-b") == ALIVE)
            assert a.rx_rejected == 0
        finally:
            a.shutdown(leave=False)
            b.shutdown(leave=False)


class TestJoinAddrParsing:
    """expand_join_addrs IPv6 handling: bracketed [addr]:port, bare
    IPv6 literals, and AF_INET-restricted resolution (the membership
    socket is IPv4; AAAA records would probe into a black hole)."""

    def test_parse_entry_shapes(self):
        from nomad_tpu.server.membership import parse_join_entry

        assert parse_join_entry("10.0.0.1:4700") == ("10.0.0.1", 4700)
        assert parse_join_entry("10.0.0.1") == ("10.0.0.1", 4648)
        assert parse_join_entry("srv.example:9000") == ("srv.example", 9000)
        assert parse_join_entry("[::1]:4700") == ("::1", 4700)
        assert parse_join_entry("[fe80::1]") == ("fe80::1", 4648)
        # bare IPv6 literal: NOT split at the last colon
        assert parse_join_entry("fe80::1") == ("fe80::1", 4648)
        assert parse_join_entry("2001:db8::2:1") == ("2001:db8::2:1", 4648)

    def test_ipv4_entries_resolve(self):
        out = expand_join_addrs(["127.0.0.1:4701", "127.0.0.1"])
        assert ("127.0.0.1", 4701) in out
        assert ("127.0.0.1", 4648) in out

    def test_ipv6_literal_skipped_on_ipv4_socket(self):
        # an AF_INET lookup cannot yield a dialable target for ::1 —
        # the entry is skipped with a warning, not mis-resolved
        out = expand_join_addrs(["[::1]:4700", "fe80::1"])
        assert out == []

    def test_ipv6_family_opt_in(self):
        out = expand_join_addrs(["[::1]:4700"], family=socket.AF_INET6)
        assert ("::1", 4700) in [a[:2] for a in out]
