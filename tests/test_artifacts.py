"""Artifact fetching (getter.py + the task-runner prestart hook).

Reference: client/allocrunner/taskrunner/artifact_hook.go,
getter/getter.go. HTTP sources are served by a local stdlib server
(the environment has no egress); git sources clone a local repo.
"""

import hashlib
import http.server
import os
import subprocess
import tarfile
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.client.getter import ArtifactError, fetch_artifact


@pytest.fixture()
def http_root(tmp_path_factory):
    root = tmp_path_factory.mktemp("artifacts")
    handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(  # noqa: E731
        *a, directory=str(root), **kw)
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield root, f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


class TestFetchArtifact:
    def test_http_download_with_checksum(self, http_root, tmp_path):
        root, base = http_root
        (root / "tool.txt").write_bytes(b"#!/bin/sh\necho tool\n")
        digest = hashlib.sha256(b"#!/bin/sh\necho tool\n").hexdigest()
        dest = fetch_artifact(
            {"source": f"{base}/tool.txt",
             "options": {"checksum": f"sha256:{digest}"}},
            str(tmp_path),
        )
        assert open(os.path.join(dest, "tool.txt")).read().startswith("#!")

    def test_checksum_mismatch_removes_file(self, http_root, tmp_path):
        root, base = http_root
        (root / "bad.bin").write_bytes(b"payload")
        with pytest.raises(ArtifactError, match="checksum mismatch"):
            fetch_artifact(
                {"source": f"{base}/bad.bin",
                 "options": {"checksum": "sha256:" + "0" * 64}},
                str(tmp_path),
            )
        assert not os.path.exists(tmp_path / "local" / "bad.bin")

    def test_archive_auto_unpacks(self, http_root, tmp_path):
        root, base = http_root
        pkg = root / "pkg"
        pkg.mkdir()
        (pkg / "bin.sh").write_text("echo packaged\n")
        with tarfile.open(root / "pkg.tar.gz", "w:gz") as t:
            t.add(str(pkg / "bin.sh"), arcname="bin.sh")
        dest = fetch_artifact(
            {"source": f"{base}/pkg.tar.gz", "destination": "local/pkg"},
            str(tmp_path),
        )
        assert open(os.path.join(dest, "bin.sh")).read() == "echo packaged\n"
        assert not os.path.exists(os.path.join(dest, "pkg.tar.gz"))

    def test_git_clone(self, tmp_path):
        repo = tmp_path / "srcrepo"
        repo.mkdir()
        subprocess.run(["git", "init", "-q"], cwd=repo, check=True)
        (repo / "hello.txt").write_text("from-git\n")
        subprocess.run(["git", "add", "."], cwd=repo, check=True)
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t",
             "commit", "-qm", "init"], cwd=repo, check=True)
        task_dir = tmp_path / "task"
        task_dir.mkdir()
        dest = fetch_artifact(
            {"source": f"git::file://{repo}", "destination": "local/repo"},
            str(task_dir),
        )
        assert open(os.path.join(dest, "hello.txt")).read() == "from-git\n"

    def test_destination_escape_rejected(self, tmp_path):
        with pytest.raises(ArtifactError, match="escapes"):
            fetch_artifact(
                {"source": "/etc/hostname", "destination": "../../escape"},
                str(tmp_path),
            )


class TestArtifactHookEndToEnd:
    def test_job_binary_arrives_via_artifact(self, http_root):
        """A job whose executable arrives via an artifact block runs it
        (artifact_hook.go end-to-end)."""
        root, base = http_root
        (root / "runme.sh").write_text("#!/bin/sh\necho artifact-ran\n")
        agent = Agent(AgentConfig.dev())
        agent.start()
        try:
            api = APIClient(agent.http_addr)
            job = mock.job()
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "raw_exec"
            task.artifacts = [{"source": f"{base}/runme.sh"}]
            task.config = {"command": "/bin/sh",
                           "args": ["-c",
                                    "sh $NOMAD_TASK_DIR/runme.sh; sleep 30"]}
            agent.server.job_register(job)
            deadline = time.time() + 20
            logged = ""
            while time.time() < deadline:
                allocs = api.jobs.allocations(job.id)
                running = [a for a in allocs
                           if a["ClientStatus"] == "running"]
                if running:
                    logged = api.allocations.logs(running[0]["ID"], "web")
                    if "artifact-ran" in logged:
                        break
                time.sleep(0.2)
            assert "artifact-ran" in logged
        finally:
            agent.shutdown()

    def test_failed_download_fails_task_setup(self):
        agent = Agent(AgentConfig.dev())
        agent.start()
        try:
            api = APIClient(agent.http_addr)
            job = mock.job()
            job.task_groups[0].count = 1
            job.task_groups[0].restart_policy.attempts = 0
            task = job.task_groups[0].tasks[0]
            task.driver = "raw_exec"
            task.artifacts = [
                {"source": "http://127.0.0.1:1/never-there.bin"}]
            task.config = {"command": "/bin/true"}
            agent.server.job_register(job)
            deadline = time.time() + 25
            saw_event = False
            while time.time() < deadline and not saw_event:
                for a in api.jobs.allocations(job.id):
                    info = api.allocations.info(a["ID"])
                    events = (info.get("TaskStates", {})
                              .get("web", {}).get("Events", []))
                    if any("Failed Artifact Download" in
                           str(e.get("DisplayMessage", "")) +
                           str(e.get("Message", "")) for e in events):
                        saw_event = True
                time.sleep(0.3)
            assert saw_event, "no Failed Artifact Download event"
        finally:
            agent.shutdown()
