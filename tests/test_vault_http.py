"""HTTP Vault provider against a stub server speaking the REAL Vault
wire shapes.

Reference behavior: nomad/vault.go vaultClient — token derivation via
the token(-role) create API, renewal via renew-accessor, revocation
via revoke-accessor, all under X-Vault-Token. The stub implements the
actual endpoint paths and response JSON (auth block with client_token/
accessor/lease_duration; KV v2 data.data envelope), so the provider is
exercised against the protocol, not a lookalike.
"""

import json
import threading
import time

import pytest

from nomad_tpu.server.secrets import HTTPVaultProvider, VaultManager


class _FakeVault:
    """Minimal Vault HTTP server: real paths, real JSON shapes."""

    ROOT = "root-token"

    def __init__(self) -> None:
        self.tokens = {}         # accessor -> {token, ttl, policies}
        self.by_token = {}
        self.secrets = {
            "secret/data/db": {"data": {
                "data": {"password": "hunter2"},
                "metadata": {"version": 1},
            }},
            "kv1/legacy": {"data": {"value": "old-school"}},
        }
        self.create_calls = []
        self.renew_calls = []

    def _auth_block(self, entry):
        return {"auth": {
            "client_token": entry["token"],
            "accessor": entry["accessor"],
            "lease_duration": int(entry["ttl"]),
            "renewable": True,
            "token_policies": list(entry["policies"]),
        }}

    def handle(self, method, path, body, token):
        import secrets as _s

        if path.startswith("auth/token/create"):
            if token != self.ROOT:
                return 403, {}
            role = path.split("/", 3)[3] if path.count("/") >= 3 else ""
            self.create_calls.append(role)
            entry = {
                "token": f"hvs.{_s.token_urlsafe(18)}",
                "accessor": _s.token_urlsafe(12),
                "ttl": int(str(body.get("ttl", "3600s")).rstrip("s")),
                "policies": body.get("policies") or [],
            }
            self.tokens[entry["accessor"]] = entry
            self.by_token[entry["token"]] = entry
            return 200, self._auth_block(entry)
        if path == "auth/token/renew-accessor":
            acc = body.get("accessor", "")
            entry = self.tokens.get(acc)
            if entry is None:
                # real Vault wire behavior: 400 "invalid accessor"
                return 400, {"errors": ["invalid accessor"]}
            self.renew_calls.append(acc)
            return 200, self._auth_block(entry)
        if path == "auth/token/revoke-accessor":
            entry = self.tokens.pop(body.get("accessor", ""), None)
            if entry is not None:
                self.by_token.pop(entry["token"], None)
            return 200, {}
        if path == "auth/token/lookup-self":
            if token in self.by_token or token == self.ROOT:
                return 200, {"data": {"id": token}}
            return 403, {}
        # KV reads: policy-checked against the presented token
        if token != self.ROOT and token not in self.by_token:
            return 403, {}
        if path in self.secrets:
            return 200, self.secrets[path]
        return 404, {}


@pytest.fixture()
def fake_vault():
    import http.server
    import socketserver

    fake = _FakeVault()

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):  # noqa: N802
            pass

        def _serve(self, method):
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length) or b"{}") \
                if length else {}
            token = self.headers.get("X-Vault-Token", "")
            assert self.path.startswith("/v1/")
            code, resp = fake.handle(method, self.path[4:], body, token)
            data = json.dumps(resp).encode()
            self.send_response(code)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802
            self._serve("GET")

        def do_POST(self):  # noqa: N802
            self._serve("POST")

    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Handler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    fake.addr = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        yield fake
    finally:
        srv.shutdown()


class TestHTTPVaultProvider:
    def _provider(self, fake, **kw):
        return HTTPVaultProvider(fake.addr, _FakeVault.ROOT, **kw)

    def test_manager_lifecycle_with_http_provider(self, fake_vault):
        """The existing manager lifecycle (derive -> renew -> revoke)
        runs unchanged with the HTTP provider slotted in."""
        mgr = VaultManager(provider=self._provider(fake_vault),
                           renew_interval_s=0.2)
        mgr.start()
        try:
            tokens = mgr.derive_tokens(
                "alloc-1", {"web": ["web-read"], "db": ["db-rw"]})
            assert set(tokens) == {"web", "db"}
            assert all(t.token.startswith("hvs.") for t in tokens.values())
            assert tokens["web"].policies == ["web-read"]
            # background renewal reaches the real renew-accessor path
            deadline = time.time() + 5
            while time.time() < deadline and not fake_vault.renew_calls:
                time.sleep(0.05)
            assert tokens["web"].accessor in fake_vault.renew_calls \
                or tokens["db"].accessor in fake_vault.renew_calls
            # terminal alloc: both accessors revoked server-side
            assert mgr.revoke_for_alloc("alloc-1") == 2
            assert fake_vault.tokens == {}
        finally:
            mgr.stop()

    def test_token_role_derivation_path(self, fake_vault):
        p = self._provider(fake_vault, token_role="nomad-cluster")
        p.create_token(["p1"], 600)
        assert fake_vault.create_calls == ["nomad-cluster"]

    def test_kv2_and_kv1_read_shapes(self, fake_vault):
        p = self._provider(fake_vault)
        task = p.create_token(["any"], 600)
        assert p.read_secret("secret/data/db", token=task.token) == \
            {"password": "hunter2"}
        assert p.read_secret("kv1/legacy", token=task.token) == \
            {"value": "old-school"}
        assert p.read_secret("secret/data/missing",
                             token=task.token) is None

    def test_bad_token_read_is_permission_error(self, fake_vault):
        p = self._provider(fake_vault)
        with pytest.raises(PermissionError):
            p.read_secret("secret/data/db", token="garbage")
        # an EMPTY task token must never fall back to the manager's
        # privileged token
        with pytest.raises(PermissionError):
            p.read_secret("secret/data/db", token="")
        assert not p.token_valid("garbage")
        good = p.create_token([], 600)
        assert p.token_valid(good.token)

    def test_unreachable_vault_is_an_error_not_invalid_token(self):
        p = HTTPVaultProvider("http://127.0.0.1:9", "tok", timeout_s=1.0)
        # conflating transport failure with revocation would rotate
        # live tokens on every network blip
        with pytest.raises(OSError):
            p.token_valid("hvs.something")

    def test_kv2_deleted_version_reads_as_absent(self, fake_vault):
        # real KV v2 deleted-version shape: metadata keeps version and
        # gains deletion_time; data is null
        fake_vault.secrets["secret/data/gone"] = {"data": {
            "data": None, "metadata": {
                "version": 2, "deletion_time": "2026-01-01"}}}
        p = self._provider(fake_vault)
        task = p.create_token([], 600)
        assert p.read_secret("secret/data/gone", token=task.token) is None

    def test_revoked_accessor_renew_raises_keyerror(self, fake_vault):
        p = self._provider(fake_vault)
        info = p.create_token([], 600)
        p.revoke(info.accessor)
        with pytest.raises(KeyError):
            p.renew(info.accessor)

    def test_server_config_slots_http_provider(self, fake_vault):
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(
            num_workers=0, vault_addr=fake_vault.addr,
            vault_token=_FakeVault.ROOT))
        assert isinstance(server.vault.provider, HTTPVaultProvider)
        info = server.vault.provider.create_token(["x"], 60)
        assert info.accessor in fake_vault.tokens
