"""Client runtime + driver tests.

Modeled on reference client/client_test.go, allocrunner/taskrunner
tests, and drivers/rawexec/driver_test.go: mock-driver-based client
integration against an in-process server (TestClient + TestServer
pattern, client/testing.go), real-subprocess rawexec tests, and
restart-recovery with task reattach.
"""

import os
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.client import Client, ClientConfig, InProcessRPC
from nomad_tpu.client.state_db import MemStateDB, StateDB
from nomad_tpu.client.task_runner import RestartTracker
from nomad_tpu.drivers import builtin_drivers
from nomad_tpu.drivers.mock import MockDriver
from nomad_tpu.drivers.rawexec import RawExecDriver, executor_path
from nomad_tpu.plugins.drivers import TaskConfig
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import consts


def wait_for(fn, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


class TestMockDriver:
    def test_run_and_exit(self):
        d = MockDriver()
        h = d.start_task(TaskConfig(
            id="t1", name="t1", driver_config={"run_for": 0.05, "exit_code": 0},
        ))
        assert h.state == "running"
        result = d.wait_task("t1", timeout=5)
        assert result.exit_code == 0

    def test_exit_code(self):
        d = MockDriver()
        d.start_task(TaskConfig(id="t2", name="t2",
                                driver_config={"run_for": 0.01, "exit_code": 3}))
        result = d.wait_task("t2", timeout=5)
        assert result.exit_code == 3

    def test_start_error(self):
        d = MockDriver()
        with pytest.raises(RuntimeError):
            d.start_task(TaskConfig(id="t3", name="t3",
                                    driver_config={"start_error": "boom"}))

    def test_stop_long_running(self):
        d = MockDriver()
        d.start_task(TaskConfig(id="t4", name="t4", driver_config={}))
        d.stop_task("t4", timeout=2)
        result = d.wait_task("t4", timeout=2)
        assert result.signal == 15


class TestRawExecDriver:
    def test_echo(self, tmp_path):
        d = RawExecDriver()
        cfg = TaskConfig(
            id="e1", name="e1", alloc_dir=str(tmp_path),
            driver_config={"command": "/bin/sh",
                           "args": ["-c", "echo raw-exec-ran"]},
        )
        d.start_task(cfg)
        result = d.wait_task("e1", timeout=10)
        assert result.exit_code == 0
        out = (tmp_path / "stdout").read_text()
        assert "raw-exec-ran" in out

    def test_exit_code_propagates(self, tmp_path):
        d = RawExecDriver()
        d.start_task(TaskConfig(
            id="e2", name="e2", alloc_dir=str(tmp_path),
            driver_config={"command": "/bin/sh", "args": ["-c", "exit 7"]},
        ))
        result = d.wait_task("e2", timeout=10)
        assert result.exit_code == 7

    def test_stop_kills_process_group(self, tmp_path):
        d = RawExecDriver()
        d.start_task(TaskConfig(
            id="e3", name="e3", alloc_dir=str(tmp_path),
            driver_config={"command": "/bin/sleep", "args": ["60"]},
        ))
        t0 = time.time()
        d.stop_task("e3", timeout=2)
        result = d.wait_task("e3", timeout=5)
        assert result is not None
        assert time.time() - t0 < 10

    def test_executor_binary_builds(self):
        # native/executor.cc must compile with the baked-in toolchain
        assert executor_path() is not None

    def test_reattach_after_driver_restart(self, tmp_path):
        """The native executor keeps supervising across a driver
        teardown (drivers/shared/executor 2-process model +
        RecoverTask)."""
        d1 = RawExecDriver()
        cfg = TaskConfig(
            id="e4", name="e4", alloc_dir=str(tmp_path),
            driver_config={"command": "/bin/sh",
                           "args": ["-c", "sleep 0.8; echo survived"]},
        )
        handle = d1.start_task(cfg)
        # simulate agent restart: fresh driver instance, recover by handle
        d2 = RawExecDriver()
        d2.recover_task(handle)
        result = d2.wait_task("e4", timeout=10)
        assert result.exit_code == 0
        assert "survived" in (tmp_path / "stdout").read_text()


class TestRestartTracker:
    def test_service_restarts_on_failure(self):
        rt = RestartTracker(structs.RestartPolicy(attempts=2, interval_s=300,
                                                  delay_s=0.01, mode="fail"),
                            consts.JOB_TYPE_SERVICE)
        assert rt.next_restart(False)[0] == "restart"
        assert rt.next_restart(False)[0] == "restart"
        assert rt.next_restart(False)[0] == "fail"

    def test_batch_success_exits(self):
        rt = RestartTracker(structs.RestartPolicy(attempts=2), consts.JOB_TYPE_BATCH)
        assert rt.next_restart(True)[0] == "exit"

    def test_service_success_restarts(self):
        rt = RestartTracker(structs.RestartPolicy(attempts=2, delay_s=0.01),
                            consts.JOB_TYPE_SERVICE)
        assert rt.next_restart(True)[0] == "restart"


class TestStateDB:
    def test_roundtrip(self, tmp_path):
        db = StateDB(str(tmp_path / "state.db"))
        alloc = mock.alloc()
        db.put_allocation(alloc)
        db.put_task_state(alloc.id, "web", local_state={"x": 1},
                          task_handle={"pid": 42})
        assert len(db.get_allocations()) == 1
        local, handle = db.get_task_state(alloc.id, "web")
        assert local == {"x": 1} and handle == {"pid": 42}
        db.delete_allocation(alloc.id)
        assert db.get_allocations() == []
        db.close()

    def test_persists_across_reopen(self, tmp_path):
        path = str(tmp_path / "state.db")
        db = StateDB(path)
        alloc = mock.alloc()
        db.put_allocation(alloc)
        db.put_meta("node_id", "abc")
        db.close()
        db2 = StateDB(path)
        assert len(db2.get_allocations()) == 1
        assert db2.get_meta("node_id") == "abc"
        db2.close()


class TestClientEndToEnd:
    def make_pair(self, tmp_path, **client_kw):
        server = Server(ServerConfig(num_workers=2, heartbeat_ttl=30.0))
        server.start()
        client = Client(
            InProcessRPC(server),
            ClientConfig(data_dir=str(tmp_path), **client_kw),
        )
        client.start()
        return server, client

    def test_client_registers_and_heartbeats(self, tmp_path):
        server, client = self.make_pair(tmp_path)
        try:
            wait_for(
                lambda: (
                    server.state.snapshot().node_by_id(client.node_id) is not None
                    and server.state.snapshot().node_by_id(client.node_id).status
                    == consts.NODE_STATUS_READY
                ),
                msg="node registered ready",
            )
            node = server.state.snapshot().node_by_id(client.node_id)
            assert node.node_resources.cpu.cpu_shares > 0
            assert "mock_driver" in node.drivers
        finally:
            client.shutdown()
            server.shutdown()

    def test_job_runs_to_completion(self, tmp_path):
        """Full loop: job -> scheduler -> client watch -> mock driver ->
        status update -> server marks complete."""
        server, client = self.make_pair(tmp_path)
        try:
            wait_for(
                lambda: server.state.snapshot().node_by_id(client.node_id) is not None
                and server.state.snapshot().node_by_id(client.node_id).ready(),
                msg="node ready",
            )
            job = mock.simple_job(type=consts.JOB_TYPE_BATCH)
            job.task_groups[0].count = 2
            job.task_groups[0].tasks[0].config = {"run_for": 0.1}
            server.job_register(job)
            wait_for(
                lambda: len([
                    a for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                    if a.client_status == consts.ALLOC_CLIENT_COMPLETE
                ]) == 2,
                timeout=30,
                msg="2 allocs complete",
            )
        finally:
            client.shutdown()
            server.shutdown()

    def test_rawexec_job_writes_output(self, tmp_path):
        server, client = self.make_pair(tmp_path)
        try:
            wait_for(
                lambda: server.state.snapshot().node_by_id(client.node_id) is not None
                and server.state.snapshot().node_by_id(client.node_id).ready(),
                msg="node ready",
            )
            job = mock.simple_job(type=consts.JOB_TYPE_BATCH)
            job.task_groups[0].count = 1
            job.task_groups[0].tasks[0].driver = "raw_exec"
            job.task_groups[0].tasks[0].config = {
                "command": "/bin/sh", "args": ["-c", "echo from-alloc"],
            }
            server.job_register(job)
            wait_for(
                lambda: any(
                    a.client_status == consts.ALLOC_CLIENT_COMPLETE
                    for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                ),
                timeout=30,
                msg="rawexec alloc complete",
            )
            allocs = server.state.snapshot().allocs_by_job(job.namespace, job.id)
            logs = os.path.join(
                str(tmp_path), "allocs", allocs[0].id, "alloc", "logs"
            )
            stdout = os.path.join(logs, "web.stdout.0")
            assert "from-alloc" in open(stdout).read()
        finally:
            client.shutdown()
            server.shutdown()

    def test_failed_task_marks_alloc_failed(self, tmp_path):
        server, client = self.make_pair(tmp_path)
        try:
            wait_for(
                lambda: server.state.snapshot().node_by_id(client.node_id) is not None
                and server.state.snapshot().node_by_id(client.node_id).ready(),
                msg="node ready",
            )
            job = mock.simple_job(type=consts.JOB_TYPE_BATCH)
            job.task_groups[0].count = 1
            job.task_groups[0].restart_policy = structs.RestartPolicy(
                attempts=0, interval_s=300, delay_s=0.01, mode="fail"
            )
            job.task_groups[0].tasks[0].config = {"run_for": 0.05, "exit_code": 1}
            server.job_register(job)
            wait_for(
                lambda: any(
                    a.client_status == consts.ALLOC_CLIENT_FAILED
                    for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                ),
                timeout=30,
                msg="alloc failed",
            )
        finally:
            client.shutdown()
            server.shutdown()

    def test_stop_job_stops_allocs(self, tmp_path):
        server, client = self.make_pair(tmp_path)
        try:
            wait_for(
                lambda: server.state.snapshot().node_by_id(client.node_id) is not None
                and server.state.snapshot().node_by_id(client.node_id).ready(),
                msg="node ready",
            )
            job = mock.simple_job()
            job.task_groups[0].count = 1
            job.task_groups[0].tasks[0].config = {}   # run until killed
            server.job_register(job)
            wait_for(
                lambda: any(
                    a.client_status == consts.ALLOC_CLIENT_RUNNING
                    for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                ),
                timeout=30,
                msg="alloc running",
            )
            server.job_deregister(job.namespace, job.id)
            wait_for(
                lambda: all(
                    a.client_terminal_status()
                    for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                ),
                timeout=30,
                msg="allocs stopped on client",
            )
        finally:
            client.shutdown()
            server.shutdown()

    def test_client_restart_recovers_rawexec_task(self, tmp_path):
        """Agent restart: the executor keeps the task alive; a new
        client reattaches via the persisted TaskHandle
        (client.go:1109 restoreState + RecoverTask)."""
        server = Server(ServerConfig(num_workers=2, heartbeat_ttl=30.0))
        server.start()
        client = Client(
            InProcessRPC(server),
            ClientConfig(data_dir=str(tmp_path), persistent_state=True),
        )
        client.start()
        try:
            wait_for(
                lambda: server.state.snapshot().node_by_id(client.node_id) is not None
                and server.state.snapshot().node_by_id(client.node_id).ready(),
                msg="node ready",
            )
            job = mock.simple_job(type=consts.JOB_TYPE_BATCH)
            job.task_groups[0].count = 1
            job.task_groups[0].tasks[0].driver = "raw_exec"
            job.task_groups[0].tasks[0].config = {
                "command": "/bin/sh",
                "args": ["-c", "sleep 1.5; echo recovered-ok"],
            }
            server.job_register(job)
            wait_for(
                lambda: any(
                    a.client_status == consts.ALLOC_CLIENT_RUNNING
                    for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                ),
                timeout=30,
                msg="alloc running",
            )
            node_id = client.node_id
            # hard-stop the agent WITHOUT stopping tasks
            client._shutdown.set()
            for t in client._threads:
                t.join(timeout=2)
            client.state_db.close()

            # new agent instance over the same data dir
            client2 = Client(
                InProcessRPC(server),
                ClientConfig(data_dir=str(tmp_path), persistent_state=True),
            )
            assert client2.node_id == node_id
            client2.start()
            wait_for(
                lambda: any(
                    a.client_status == consts.ALLOC_CLIENT_COMPLETE
                    for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                ),
                timeout=30,
                msg="recovered alloc completes",
            )
            allocs = server.state.snapshot().allocs_by_job(job.namespace, job.id)
            logs = os.path.join(
                str(tmp_path), "allocs", allocs[0].id, "alloc", "logs"
            )
            assert "recovered-ok" in open(
                os.path.join(logs, "web.stdout.0")
            ).read()
            client2.shutdown()
        finally:
            server.shutdown()

    def test_node_down_reschedules_to_other_client(self, tmp_path):
        """Kill a client; heartbeat expiry reschedules its allocs onto
        the surviving client (heartbeat.go -> node down -> eval ->
        reconcile lost)."""
        server = Server(ServerConfig(num_workers=2, heartbeat_ttl=1.0))
        server.start()
        c1 = Client(InProcessRPC(server),
                    ClientConfig(data_dir=str(tmp_path / "c1")))
        c2 = Client(InProcessRPC(server),
                    ClientConfig(data_dir=str(tmp_path / "c2")))
        c1.start()
        c2.start()
        try:
            wait_for(
                lambda: all(
                    server.state.snapshot().node_by_id(c.node_id) is not None
                    and server.state.snapshot().node_by_id(c.node_id).ready()
                    for c in (c1, c2)
                ),
                msg="both nodes ready",
            )
            job = mock.simple_job()
            job.task_groups[0].count = 2
            job.task_groups[0].tasks[0].config = {}   # run forever
            server.job_register(job)
            wait_for(
                lambda: len([
                    a for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                    if a.client_status == consts.ALLOC_CLIENT_RUNNING
                ]) == 2,
                timeout=30,
                msg="2 allocs running",
            )
            victim, survivor = c1, c2
            victim._shutdown.set()     # silent death: heartbeats stop
            wait_for(
                lambda: server.state.snapshot().node_by_id(victim.node_id).status
                == consts.NODE_STATUS_DOWN,
                timeout=15,
                msg="victim node down",
            )
            wait_for(
                lambda: len([
                    a for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                    if a.client_status == consts.ALLOC_CLIENT_RUNNING
                    and a.node_id == survivor.node_id
                ]) == 2,
                timeout=30,
                msg="allocs rescheduled to survivor",
            )
        finally:
            c1.shutdown()
            c2.shutdown()
            server.shutdown()


class TestLogmonSurvival:
    def test_logs_written_while_agent_down_are_collected(self, tmp_path):
        """logmon runs as its own process (logmon.go:46): output a task
        writes while the agent is down still lands in the rotated
        files, and the restarted agent reattaches to the SAME collector
        instead of spawning a second one."""
        server = Server(ServerConfig(num_workers=2, heartbeat_ttl=30.0))
        server.start()
        client = Client(
            InProcessRPC(server),
            ClientConfig(data_dir=str(tmp_path), persistent_state=True),
        )
        client.start()
        try:
            wait_for(
                lambda: server.state.snapshot().node_by_id(client.node_id)
                is not None
                and server.state.snapshot().node_by_id(client.node_id).ready(),
                msg="node ready",
            )
            job = mock.simple_job(type=consts.JOB_TYPE_BATCH)
            job.task_groups[0].count = 1
            job.task_groups[0].tasks[0].driver = "raw_exec"
            # slow ticker: emits one line per 0.3s for ~6s
            job.task_groups[0].tasks[0].config = {
                "command": "/bin/sh",
                "args": ["-c",
                         "for i in $(seq 1 20); do echo tick-$i; "
                         "sleep 0.3; done"],
            }
            server.job_register(job)
            wait_for(
                lambda: any(
                    a.client_status == consts.ALLOC_CLIENT_RUNNING
                    for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                ),
                timeout=30, msg="alloc running",
            )
            alloc = server.state.snapshot().allocs_by_job(
                job.namespace, job.id)[0]
            base = os.path.join(str(tmp_path), "allocs", alloc.id,
                                "alloc", "logs", "web.stdout")
            pid_path = base + ".logmon.pid"
            wait_for(lambda: os.path.exists(pid_path),
                     msg="collector pidfile")
            collector_pid = int(open(pid_path).read())

            # hard-stop the agent WITHOUT stopping tasks or collectors
            client._shutdown.set()
            for t in client._threads:
                t.join(timeout=2)
            client.state_db.close()

            def logged():
                from nomad_tpu.client.logmon import read_rotated
                return read_rotated(base).decode(errors="replace")

            # ticks keep landing while no agent exists
            before = logged()
            wait_for(lambda: logged() != before and "tick-" in logged(),
                     timeout=10, msg="logs flowing while agent down")

            # restarted agent reattaches to the same collector
            client2 = Client(
                InProcessRPC(server),
                ClientConfig(data_dir=str(tmp_path), persistent_state=True),
            )
            client2.start()
            wait_for(
                lambda: any(
                    a.client_status == consts.ALLOC_CLIENT_COMPLETE
                    for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                ),
                timeout=30, msg="task completes after restart",
            )
            assert int(open(pid_path).read()) == collector_pid \
                if os.path.exists(pid_path) else True
            final = logged()
            assert "tick-1" in final and "tick-20" in final
            client2.shutdown()
        finally:
            server.shutdown()
