"""Contention-repetition tier (VERDICT r5 Weak #4).

``pytest -m stress`` runs each contention scenario N=20 times — the
load-flake class (r4's docker exec flake, r5's committed-broken test)
lives in thread interleavings a single run rarely hits. Every test
here is marked BOTH ``stress`` and ``slow``: tier-1 (`-m 'not slow'`)
never pays for repetition, and `-m stress` selects exactly this tier.
"""

import threading
import time

import numpy as np
import pytest

pytestmark = [pytest.mark.stress, pytest.mark.slow]

N_REPS = 20


@pytest.fixture(autouse=True)
def lock_witness():
    """Every stress cell runs under the runtime lock witness (ISSUE 9):
    brokers/coalescers/membership constructed inside the test get
    order-checked, hold-timed locks, and a cell that executes an
    acquisition-order inversion FAILS even if the interleaving never
    actually deadlocked. Hold-time distributions land in the
    ``lock_hold_*`` histograms (telemetry/histogram.py) as a side
    effect — pull them when a cell's p99 regresses.

    ``witness.enable()`` only instruments locks created AFTER it, and
    the module-level singletons (coalesce's inflight/stat locks,
    scaffold's cache lock) were created at import time as plain locks
    — so the fixture swaps witnessed locks into them for the tier and
    restores the originals after (no test may hold them across the
    fixture boundary; pytest guarantees that)."""
    import nomad_tpu.parallel.coalesce as co
    import nomad_tpu.scheduler.scaffold as sc
    from nomad_tpu.utils import witness

    witness.reset()
    witness.enable()
    swapped = [
        (co, "_INFLIGHT_LOCK", "coalesce._INFLIGHT_LOCK"),
        (sc, "_LOCK", "scaffold._LOCK"),
        (co.wave_stats, "_lock", "WaveStats._lock"),
        (co.wave_latency_ewma, "_lock", "LatencyEWMA._lock"),
        (co.wave_deadline_ewma, "_lock", "LatencyEWMA._lock"),
        (co.default_cluster_cache, "_lock", "ClusterCache._lock"),
    ]
    originals = []
    for obj, attr, name in swapped:
        originals.append((obj, attr, getattr(obj, attr)))
        setattr(obj, attr, witness.witness_lock(name))
    yield
    try:
        assert witness.violations() == [], (
            "lock-order inversion(s) under contention: "
            f"{witness.violations()}")
    finally:
        for obj, attr, orig in originals:
            setattr(obj, attr, orig)
        witness.disable()
        witness.reset()


class TestBrokerContention:
    def test_concurrent_enqueue_dequeue_ack(self):
        """Producers enqueue while consumers dequeue/ack: every eval is
        processed exactly once, none lost, none double-delivered."""
        from nomad_tpu import mock
        from nomad_tpu.server.eval_broker import EvalBroker

        for rep in range(N_REPS):
            broker = EvalBroker(nack_timeout=30.0)
            broker.set_enabled(True)
            n_per_producer, n_producers, n_consumers = 25, 4, 4
            total = n_per_producer * n_producers
            acked = []
            acked_lock = threading.Lock()

            def produce(pid):
                for i in range(n_per_producer):
                    ev = mock.eval()
                    ev.job_id = f"job-{pid}-{i}"   # distinct jobs: no dedup
                    broker.enqueue(ev)

            def consume():
                while True:
                    with acked_lock:
                        if len(acked) >= total:
                            return
                    batch = broker.dequeue_batch(
                        ["service"], 8, timeout=0.2)
                    for ev, token in batch:
                        broker.ack(ev.id, token)
                        with acked_lock:
                            acked.append(ev.id)

            threads = [threading.Thread(target=produce, args=(p,))
                       for p in range(n_producers)]
            threads += [threading.Thread(target=consume)
                        for _ in range(n_consumers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert len(acked) == total, f"rep {rep}: {len(acked)}/{total}"
            assert len(set(acked)) == total, f"rep {rep}: double delivery"
            broker.set_enabled(False)

    def test_nack_redelivery_under_contention(self):
        """Nacked evals (zero delay) must re-deliver exactly until the
        delivery limit, then land on the failed queue."""
        from nomad_tpu import mock
        from nomad_tpu.server.eval_broker import (
            FAILED_QUEUE, EvalBroker)

        for rep in range(N_REPS):
            broker = EvalBroker(nack_timeout=30.0, delivery_limit=3,
                                initial_nack_delay=0.0,
                                subsequent_nack_delay=0.0)
            broker.set_enabled(True)
            ev = mock.eval()
            broker.enqueue(ev)
            for _ in range(3):
                got, token = broker.dequeue(["service"], timeout=5.0)
                assert got is not None, f"rep {rep}: lost on redelivery"
                broker.nack(got.id, token)
            got, token = broker.dequeue([FAILED_QUEUE], timeout=5.0)
            assert got is not None, f"rep {rep}: not routed to failed"
            broker.set_enabled(False)


class TestCoalescerContention:
    def test_rendezvous_under_racing_done(self, monkeypatch):
        """Members race launch() against other members' done(): every
        launcher must get a result, regardless of interleaving (the
        wave fires from whichever thread completes the rendezvous)."""
        from nomad_tpu.parallel import coalesce

        def stub_launch_wave(kins, k_steps, features, mesh=None):
            time.sleep(0.001)
            return [object() for _ in kins]

        monkeypatch.setattr(coalesce, "launch_wave", stub_launch_wave)

        class KinStub:
            class _Arr:
                shape = (8,)
            cap_cpu = _Arr()

        for rep in range(N_REPS):
            n = 12
            launchers = list(np.random.RandomState(rep).rand(n) < 0.7)
            if not any(launchers):
                launchers[0] = True
            c = coalesce.LaunchCoalescer(n)
            results = [None] * n
            errors = []

            def member(i):
                try:
                    if launchers[i]:
                        results[i] = c.launch(KinStub(), 1, None)
                    else:
                        time.sleep(0.0005 * (i % 3))
                finally:
                    c.done()

            threads = [threading.Thread(target=member, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=20)
            assert not errors
            for i, is_launcher in enumerate(launchers):
                if is_launcher:
                    assert results[i] is not None, \
                        f"rep {rep}: member {i} never resumed"
            assert c.requests == sum(launchers)


class TestFleetCell:
    def test_fleet_cell_under_lock_witness(self):
        """ISSUE 11: the fleet cell (ring-cursor subscribers +
        heartbeat storm + held blocking queries over the new broker/
        watch paths) runs under the runtime lock witness — the autouse
        fixture fails the test on ANY executed acquisition-order
        inversion in the rebuilt EventBroker, the store's block_until,
        or the client-update fan-in batcher. One rep at reduced scale:
        the cell is itself a multi-thread contention storm; N=20 of it
        would dominate the tier for no added interleaving coverage."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench"))
        import trace_report

        cell = trace_report.run_fleet_burst(
            n_clients=2000, n_nodes=150, n_jobs=16, allocs_per_job=3,
            warmup_jobs=6, batch_size=8, deadline_s=120.0)
        assert cell["allocs_placed"] == cell["allocs_wanted"], cell
        assert cell["heartbeats"] > 0
        assert cell["watch_wakeups"] > 0
        assert cell["events_delivered"] > 0
        serving = cell["serving"]
        assert serving["stream"]["subscribers"] == 2000
        assert serving["stream"]["published_events"] > 0
        # the fan-in batcher coalesced the storm's alloc syncs
        assert serving["heartbeat"]["batches"] >= 1
        assert serving["heartbeat"]["callers"] >= \
            serving["heartbeat"]["batches"]
        # every committed eval landed in the e2e distribution
        assert cell["e2e_count"] == cell["committed_evals"]
        # delivery lag was measured (the serving plane's headline)
        assert cell["stream_deliver_count"] > 0


class TestReadPlaneCell:
    def test_readplane_cell_100k_three_servers_under_chaos(self):
        """ISSUE 20: the flagship read-plane cell — 100k streaming
        clients spread across a REAL 3-server cluster while a reader
        storm mixes stale/default/linearizable reads against every
        server, under BOTH standing chaos schedules (leader kill
        mid-storm; lease-partitioning the leader), all under the
        runtime lock witness (the autouse fixture fails the test on
        ANY executed acquisition-order inversion in the read plane's
        fence/forward paths). The standing gates: zero stale-read
        violations (no bounded-stale read ever served data older than
        its bound claimed), zero linearizable-from-lapsed-lease
        serves, follower share >= 0.66 (the read plane actually put
        the follower majority to work), and the stream gap-free or
        explicitly lost on every surviving server. One rep per
        schedule: each cell is itself a three-server fault storm."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench"))
        import trace_report

        for chaos in ("leader-kill-mid-wave", "lease-leader-partition"):
            cell = trace_report.run_fleet_burst(
                n_clients=100_000, n_servers=3, deadline_s=240.0,
                chaos=chaos)
            assert cell["clients"] == 100_000
            assert cell["servers"] == 3
            assert cell["converged_ok"], (chaos, cell["violations"])
            assert cell["stale_violations"] == 0, (chaos, cell)
            assert cell["linearizable_violations"] == 0, (chaos, cell)
            assert cell["lost_events"] == 0, (chaos, cell)
            assert cell["faults_fired"] >= 1, (chaos, cell)
            assert cell["read_follower_share"] >= 0.66, (chaos, cell)
            # the mode mix exercised every path: lease fast-path
            # linearizable reads, forwarded default fences, stale
            # serves off follower roots
            assert cell["read_lease_fast"] >= 1, (chaos, cell)
            assert cell["read_forwards"] >= 1, (chaos, cell)
            assert cell["read_served"]["follower"] >= 1, (chaos, cell)
            if chaos == "lease-leader-partition":
                # the probe actually cornered the deposed leader: the
                # partition landed, its lease lapsed, and every read it
                # answered after the new side committed either demoted
                # to the barrier or was refused — never a lease-valid
                # serve of stale data
                probe = cell["lease_probe"]
                assert probe["partitioned"], (chaos, cell)
                assert probe["demoted"] >= 1, (chaos, cell)
                assert probe["fast_stale"] == 0, (chaos, cell)


class TestMeshCell:
    def test_mesh_cell_100k_nodes_under_lock_witness(self):
        """ISSUE 14: the full-shape mesh cell — 100k heterogeneous
        nodes / 1M resident allocs, waves sharded over the 8-device
        host mesh — under the runtime lock witness (the autouse
        fixture fails the test on ANY executed acquisition-order
        inversion in the registry/advance locking the sharded path
        exercises from eval threads). The standing gates: every wave
        dispatched sharded (zero fallbacks), outputs bit-identical to
        the single-device reference, steady window compile-free,
        dirty-row advancement sharded with no full-plane d2h gathers.
        One rep: coverage comes from the scale, not repetition."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench"))
        import trace_report

        cell = trace_report.run_mesh_burst(deadline_s=20.0)
        assert cell["devices"] == 8
        assert cell["nodes"] == 100_000
        assert cell["allocs_resident"] == 1_000_000
        assert cell["waves"] >= 4
        assert cell["parity_ok"], cell
        assert cell["sharded_fallbacks"] == 0, cell
        assert cell["sharded_launches"] == cell["waves"]
        assert cell["jit_cache_misses"] == 0, cell
        assert cell["allocs_placed"] > 0
        # dirty-row advancement stayed sharded: every between-wave
        # ensure was a delta scatter, never a full usage re-upload,
        # and the uploaded bytes are a sliver of full re-uploads
        assert cell["delta_advances"] >= cell["waves"]
        assert cell["usage_full_uploads"] == 0, cell
        assert cell["dirty_row_upload_ratio"] <= 0.05, cell
        # no per-wave full-plane gathers: d2h stays the small
        # replicated per-placement rows
        assert cell["no_full_gather_ok"], cell
        # ISSUE 19: with fusion on by default every steady mesh wave
        # runs the fused sharded program at ONE dispatch per wave
        assert cell["fused_launches"] == cell["waves"], cell
        assert cell["fused_fallbacks"] == 0, cell
        assert cell["dispatches_per_wave"] == 1.0, cell


class TestFusedCell:
    def test_fused_cell_under_lock_witness(self):
        """ISSUE 19: the standing fused A/B — the same burst of waves
        through the fused mega-kernel and through the composite joint
        program — under the runtime lock witness (the fused path's
        stats counter + the launcher's inflight bookkeeping get
        order-checked like every other cell's locks). Gates: exact
        bit-parity including the drained top-k planes, exactly ONE
        wave-critical dispatch per fused wave vs two composite, zero
        fused fallbacks, compile-free timed windows. One rep at
        reduced scale: the A/B is deterministic; repetition adds
        compile time, not coverage."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench"))
        import trace_report

        cell = trace_report.run_fused_burst(
            n_nodes=5_000, n_allocs=20_000, batch_size=16, waves=6)
        assert cell["parity_ok"], cell
        assert cell["dispatches_per_wave"] == 1.0, cell
        assert cell["composite_dispatches_per_wave"] == 2.0, cell
        assert cell["launches"] == cell["waves"], cell
        assert cell["fallbacks"] == 0, cell
        assert cell["jit_cache_misses"] == 0, cell
        # the fused packed readback is strictly smaller than the
        # composite's eager multi-buffer fetch
        assert cell["d2h_bytes_per_wave"] < \
            cell["composite_d2h_bytes_per_wave"], cell
        assert cell["speedup"] > 0.0

    def test_fused_cell_sharded_arm_under_lock_witness(self):
        """The same A/B over the 8-device mesh: fused_wave_sharded vs
        joint_sharded, same gates (speedup is a trajectory line on
        virtual CPU devices, not a gate)."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench"))
        import trace_report

        cell = trace_report.run_fused_burst(
            n_nodes=2_000, n_allocs=8_000, batch_size=8, waves=4,
            use_mesh=True)
        assert cell["devices"] == 8
        assert cell["parity_ok"], cell
        assert cell["dispatches_per_wave"] == 1.0, cell
        assert cell["launches"] == cell["waves"], cell
        assert cell["fallbacks"] == 0, cell
        assert cell["jit_cache_misses"] == 0, cell


class TestWorkerCell:
    def test_worker_cell_under_lock_witness(self):
        """ISSUE 17: the multi-process worker cell's A/B burst under
        the runtime lock witness — the owner-side supervisor (dispatch
        loop, per-worker handles, lease ledger, state-sync lock) plus
        the generation-lease registry run with order-checked locks and
        the test fails on ANY executed acquisition-order inversion.
        Reduced scale, one rep: the cell already runs two full server
        topologies (in-process threads, then worker processes); the
        witness coverage comes from the owner side — the child
        processes have their own interpreters the witness cannot see.
        Speedup is NOT asserted (this tier runs on whatever cores CI
        gives it); parity, drained leases, and fault-free lease
        accounting are."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench"))
        import trace_report

        cell = trace_report.run_worker_burst(
            n_workers=2, n_nodes=60, n_jobs=16, allocs_per_job=3,
            warmup_jobs=4, batch_size=8, deadline_s=120.0)
        assert cell["parity_ok"], cell
        assert cell["baseline"]["allocs_placed"] == \
            cell["baseline"]["allocs_wanted"], cell
        assert cell["multi"]["allocs_placed"] == \
            cell["multi"]["allocs_wanted"], cell
        # fault-free burst: no lease ever timed out or was reissued
        assert cell["lease_reissues"] == 0, cell
        assert cell["respawns"] == 0, cell
        # the supervisor pinged its workers and measured round-trips
        assert cell["ipc_rtts"] > 0
        # steady-window gates (owner-side)
        assert cell["jit_cache_misses"] == 0, cell
        assert cell["plan_group_fallbacks"] == 0, cell
        # both topologies torn down: no generation lease survives
        assert cell["leases_leaked"] == 0, cell


class TestChaosCell:
    def test_chaos_suite_under_lock_witness(self):
        """ISSUE 12: every standing chaos schedule (leader-kill-mid-
        wave, plan-commit raft failure, crash-and-drop) against a live
        3-node raft cluster, pinned seed, under the runtime lock
        witness (the autouse fixture fails the test on ANY executed
        acquisition-order inversion in the failover/unwind paths the
        faults force). All convergence invariants must hold — every
        eval terminal, exact placement, usage planes bit-identical to
        a from-scratch rebuild on every replica, dropped nodes down
        and drained, stream gap-free or explicitly lost. One rep: the
        cell is itself a three-server fault storm; its coverage comes
        from the schedules, not repetition."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench"))
        import trace_report

        import tempfile

        from nomad_tpu.telemetry.timeline import validate_timeline

        with tempfile.TemporaryDirectory() as td:
            tl_path = os.path.join(td, "CHAOS_TIMELINE.json")
            suite = trace_report.run_chaos_suite(deadline_s=90.0,
                                                 settle_s=60.0,
                                                 timeline_path=tl_path)
            assert os.path.exists(tl_path)
        assert suite["converged_ok"], suite["violations"]
        assert suite["faults_fired"] >= 3
        for name, r in suite["schedules"].items():
            assert r["converged_ok"], (name, r["violations"])
            assert r["allocs_placed"] == r["allocs_wanted"], (name, r)
            # ISSUE 15: every schedule's timeline is a valid artifact
            assert validate_timeline(r["timeline"]) == [], \
                (name, validate_timeline(r["timeline"]))
        # the schedules did what they say on the tin
        assert suite["schedules"]["leader-kill-mid-wave"][
            "faults"]["raft.leader.stepdown"]["fires"] == 1
        assert suite["schedules"]["crash-and-drop"]["nodes_down"] == 3
        assert suite["schedules"]["plan-commit-raft-failure"][
            "faults"]["plan.commit.raft"]["fires"] >= 1
        # ISSUE 17: the worker-kill schedule SIGKILLed real worker
        # processes mid-lease and lease recovery ran (re-enqueue +
        # respawn) — converged_ok above already proved every eval
        # terminal and placement exact THROUGH the process deaths
        wk = suite["schedules"]["worker-kill-mid-lease"]
        assert wk["faults"]["workerproc.kill"]["fires"] >= 1, wk
        assert wk["worker_lease_reissues"] >= 1, wk
        assert wk["worker_respawns"] >= 1, wk
        # ISSUE 15: the leader-kill schedule produced a failover and
        # >= 0.90 of the suite's failover wall is phase-attributed
        tl = suite["timeline"]
        assert tl["failovers"] >= 1, suite["schedules"][
            "leader-kill-mid-wave"]["timeline"]["events"]
        assert tl["attributed_share"] >= 0.9, tl
        # ISSUE 18: the lease-partition schedule's probe actually ran
        # (the lease lapsed — barrier reads observed) and the deposed
        # leader NEVER served a lease-valid read after the new side
        # committed past it (the zero-stale-reads safety gate)
        ls = suite["schedules"]["lease-leader-partition"]
        assert ls["lease_fast_stale_reads"] == 0, ls
        assert ls["lease_barrier_reads"] >= 1, ls
        assert ls["lease_fast_reads"] >= 1, ls


class TestRaftCell:
    def test_raft_cell_under_lock_witness(self):
        """ISSUE 18: the pipelined-vs-synchronous A/B under the
        runtime lock witness — the per-peer wire turnstile
        (raft_pipe_wire) is a new witnessed leaf under raft_node, so
        any executed acquisition-order inversion in the window
        fill/ack/drain paths fails the cell. The bench gates are
        asserted too: the speedup comes from overlapping INJECTED 5ms
        send latency (not from cores), so it holds on whatever box CI
        gives this tier — and a speedup with diverged logs or a
        drain storm is a regression, not a win."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench"))
        import trace_report

        cell = trace_report.run_raft_burst()
        assert cell["logs_identical"], cell
        assert not cell["sync"]["errors"], cell["sync"]["errors"]
        assert not cell["pipelined"]["errors"], \
            cell["pipelined"]["errors"]
        # the sync arm must never touch the window; the pipelined arm
        # must actually use it
        assert cell["sync"]["pipeline_batches"] == 0, cell["sync"]
        assert cell["pipelined"]["pipeline_batches"] > 0, \
            cell["pipelined"]
        assert cell["speedup_ok"], (cell["speedup"],
                                    cell["lag_improvement"])


class TestRestartCell:
    def test_restart_chaos_and_torn_fuzz_under_lock_witness(self):
        """ISSUE 13: the kill→restart recovery cell (torn-write kill +
        clean leader kill against a data_dir-backed 3-node cluster)
        under the runtime lock witness — the new WAL/stable-store
        locks are witness-created, so any executed acquisition-order
        inversion in the durability paths fails the cell. All recovery
        invariants must hold: no acked committed write lost, usage
        planes bit-identical on every restarted replica, no double
        vote in any term, stream resume explicit. Plus the full
        ≥200-seed torn-tail fuzz: recovery either truncates cleanly or
        fails loudly — never silently diverges."""
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench"))
        import trace_report

        from nomad_tpu.telemetry.timeline import validate_timeline

        cell = trace_report.run_restart_chaos(deadline_s=90.0,
                                              settle_s=45.0)
        assert cell["converged_ok"], cell["violations"]
        assert cell["restarts"] == 2, cell
        assert cell["torn_truncations"] >= 1, cell
        assert cell["replayed_entries"] > 0, cell
        assert cell["allocs_placed"] == cell["allocs_wanted"], cell
        assert cell["stream_missed_alloc_events"] == 0 or \
            cell["stream_lost_markers"] > 0, cell
        # ISSUE 15: the restart legs produced a valid, attributed
        # failover timeline (killed leader -> elect -> replay ->
        # converge), recovery events included
        tl = cell["timeline"]
        assert validate_timeline(tl) == [], validate_timeline(tl)
        assert len(tl["failovers"]) >= 1, tl["events"]
        assert tl["attribution"]["share"] >= 0.9, tl["failovers"]
        assert any(e["kind"] == "recovery" for e in tl["events"]), \
            tl["events"]

        fuzz = trace_report.run_torn_tail_fuzz(seeds=200)
        assert fuzz["silent_divergences"] == 0, fuzz
        assert fuzz["clean_prefix"] > 0 and fuzz["loud_corruption"] > 0


class TestMembershipContention:
    def test_reconcile_queue_preserves_event_order(self):
        """The satellite fix itself: MEMBER_FAILED/MEMBER_ALIVE flap
        pairs must reach the reconcile handler in arrival order (the
        old thread-per-event dispatch let the OS scheduler reorder
        them and flip raft membership the wrong way)."""
        from nomad_tpu.api.agent import SerialEventWorker

        for rep in range(N_REPS):
            seen = []
            worker = SerialEventWorker(
                lambda kind, m: seen.append((kind, m["Name"])))
            expect = []
            for i in range(50):
                kind = "member-failed" if i % 2 == 0 else "member-alive"
                worker.submit(kind, {"Name": f"srv-{i % 3}"})
                expect.append((kind, f"srv-{i % 3}"))
            deadline = time.time() + 10
            while len(seen) < len(expect) and time.time() < deadline:
                time.sleep(0.005)
            worker.shutdown()
            assert seen == expect, f"rep {rep}: events reordered"

    def test_concurrent_merge_respects_incarnation_precedence(self):
        """Gossip merges racing from multiple threads (the rx path vs
        the prober) must converge on the highest-incarnation status."""
        from nomad_tpu.server.membership import ALIVE, FAILED, Membership

        for rep in range(N_REPS):
            m = Membership(name="self", probe_interval=60.0)
            try:
                rows_a = [["peer", "127.0.0.1", 9999, inc,
                           ALIVE if inc % 2 else FAILED, {}]
                          for inc in range(1, 41)]
                rows_b = list(reversed(rows_a))

                def merge(rows):
                    for row in rows:
                        with m._lock:
                            m._merge_locked(list(row))

                ta = threading.Thread(target=merge, args=(rows_a,))
                tb = threading.Thread(target=merge, args=(rows_b,))
                ta.start(); tb.start()
                ta.join(10); tb.join(10)
                peer = m._members["peer"]
                assert peer.inc == 40, f"rep {rep}: inc {peer.inc}"
                assert peer.status == FAILED, f"rep {rep}: {peer.status}"
            finally:
                m.shutdown(leave=False)
