"""Raft consensus + replicated-server tests.

Modeled on reference in-process multi-server raft tests
(nomad/server_test.go TestJoin-style, nomad/leader_test.go,
plan_normalization_test.go): real 3-node clusters in one process over
an in-memory transport.
"""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.raft.log import LogStore, LogEntry
from nomad_tpu.raft.node import NotLeaderError, RaftConfig, RaftNode
from nomad_tpu.raft.transport import InmemTransport, TransportRegistry
from nomad_tpu.server.testing import make_cluster, wait_for_leader, wait_until
from nomad_tpu.structs import consts

FAST = RaftConfig(
    heartbeat_interval=0.02,
    election_timeout_min=0.06,
    election_timeout_max=0.12,
)


def make_raft_cluster(n, fsm_factory=None):
    """N bare RaftNodes over an in-memory transport; each applies into
    its own list (the FSM)."""
    registry = TransportRegistry()
    addrs = [f"n{i}" for i in range(n)]
    nodes, logs = [], []
    for addr in addrs:
        applied = []
        logs.append(applied)
        node = RaftNode(
            node_id=addr,
            peers=addrs,
            transport=InmemTransport(addr, registry),
            fsm_apply=(lambda a: lambda t, r: a.append((t, r)) or len(a))(applied),
            config=FAST,
        )
        nodes.append(node)
    for node in nodes:
        node.start()
    return nodes, logs, registry


def leader_of(nodes, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        leaders = [n for n in nodes if n.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.01)
    raise TimeoutError("no single leader")


def shutdown_all(nodes):
    for n in nodes:
        n.shutdown()


class TestRaftCore:
    def test_single_node_elects_self(self):
        nodes, logs, _ = make_raft_cluster(1)
        try:
            leader = leader_of(nodes)
            assert leader is nodes[0]
            result = leader.apply("set", {"k": 1})
            assert result == 1
            assert logs[0] == [("set", {"k": 1})]
        finally:
            shutdown_all(nodes)

    def test_three_node_replication(self):
        nodes, logs, _ = make_raft_cluster(3)
        try:
            leader = leader_of(nodes)
            for i in range(5):
                leader.apply("op", {"i": i})
            wait_until(
                lambda: all(len(l) == 5 for l in logs),
                msg="all FSMs applied 5 entries",
            )
            assert logs[0] == logs[1] == logs[2]
        finally:
            shutdown_all(nodes)

    def test_apply_on_follower_raises(self):
        nodes, logs, _ = make_raft_cluster(3)
        try:
            leader = leader_of(nodes)
            follower = next(n for n in nodes if n is not leader)
            with pytest.raises(NotLeaderError):
                follower.apply("op", {})
        finally:
            shutdown_all(nodes)

    def test_forward_apply_from_follower(self):
        nodes, logs, _ = make_raft_cluster(3)
        try:
            leader = leader_of(nodes)
            follower = next(n for n in nodes if n is not leader)
            result = follower.forward_apply("op", {"x": 1})
            assert result == 1
        finally:
            shutdown_all(nodes)

    def test_leader_failover(self):
        nodes, logs, _ = make_raft_cluster(3)
        try:
            leader = leader_of(nodes)
            leader.apply("op", {"i": 0})
            leader.shutdown()
            rest = [n for n in nodes if n is not leader]
            new_leader = leader_of(rest)
            assert new_leader is not leader
            new_leader.apply("op", {"i": 1})
            live_logs = [logs[nodes.index(n)] for n in rest]
            wait_until(
                lambda: all(len(l) == 2 for l in live_logs),
                msg="survivors applied both entries",
            )
        finally:
            shutdown_all(n for n in nodes if n._threads)

    def test_partition_heals(self):
        nodes, logs, registry = make_raft_cluster(3)
        try:
            leader = leader_of(nodes)
            followers = [n for n in nodes if n is not leader]
            # cut the leader from both followers: majority elects anew
            for f in followers:
                registry.partition(leader.id, f.id)
            new_leader = leader_of(followers)
            new_leader.apply("op", {"after": "partition"})
            # heal: old leader steps down and catches up
            registry.heal()
            wait_until(
                lambda: not leader.is_leader(),
                msg="old leader stepped down",
            )
            wait_until(
                lambda: all(len(l) == 1 for l in logs),
                msg="all logs converged",
            )
        finally:
            shutdown_all(nodes)

    def test_log_store_compaction(self):
        log = LogStore()
        for i in range(1, 11):
            log.append(LogEntry(index=i, term=1, data=i))
        log.compact_to(5, 1)
        assert log.base_index() == 5
        assert log.get(5) is None
        assert log.get(6).data == 6
        assert log.last_index() == 10
        log.truncate_from(8)
        assert log.last_index() == 7


class TestTcpTransport:
    def test_three_node_cluster_over_tcp(self):
        # raft_rpc.go RaftLayer analog: same RPCs over real sockets
        from nomad_tpu.raft.transport import TcpTransport

        transports = [TcpTransport() for _ in range(3)]
        addrs = [t.addr for t in transports]
        nodes, logs = [], []
        for t in transports:
            applied = []
            logs.append(applied)
            node = RaftNode(
                node_id=t.addr,
                peers=addrs,
                transport=t,
                fsm_apply=(lambda a: lambda ty, r: a.append((ty, r)) or len(a))(applied),
                config=FAST,
            )
            nodes.append(node)
        for n in nodes:
            n.start()
        try:
            leader = leader_of(nodes, timeout=10)
            for i in range(3):
                leader.apply("op", {"i": i})
            wait_until(
                lambda: all(len(l) == 3 for l in logs),
                msg="TCP replication to all nodes",
            )
            assert logs[0] == logs[1] == logs[2]
        finally:
            shutdown_all(nodes)


class TestReplicatedServer:
    def test_job_register_replicates(self):
        servers, _ = make_cluster(3)
        try:
            leader = wait_for_leader(servers)
            for _ in range(3):
                leader.node_register(mock.node())
            job = mock.job()
            resp = leader.job_register(job)
            assert resp["eval_id"]
            # every server's state store converges on the same job+allocs
            wait_until(
                lambda: all(
                    s.state.snapshot().job_by_id(job.namespace, job.id) is not None
                    for s in servers
                ),
                msg="job replicated to all servers",
            )
            wait_until(
                lambda: all(
                    len(s.state.snapshot().allocs_by_job(job.namespace, job.id)) == 10
                    for s in servers
                ),
                timeout=30,
                msg="allocs replicated to all servers",
            )
        finally:
            for s in servers:
                s.shutdown()

    def test_follower_forwards_writes(self):
        servers, _ = make_cluster(3)
        try:
            leader = wait_for_leader(servers)
            follower = next(s for s in servers if s is not leader)
            node = mock.node()
            follower.node_register(node)
            wait_until(
                lambda: all(
                    s.state.snapshot().node_by_id(node.id) is not None
                    for s in servers
                ),
                msg="node visible on all servers",
            )
        finally:
            for s in servers:
                s.shutdown()

    def test_leader_failover_keeps_scheduling(self):
        # generous timeouts: under a full-suite run, concurrent JAX
        # compiles hold the GIL for long stretches and stall the
        # Python control plane (scheduling + elections)
        servers, _ = make_cluster(3)
        try:
            leader = wait_for_leader(servers, timeout=30)
            for _ in range(3):
                leader.node_register(mock.node())
            job1 = mock.job()
            leader.job_register(job1)
            wait_until(
                lambda: len(leader.state.snapshot().allocs_by_job(
                    job1.namespace, job1.id)) == 10,
                timeout=90,
                msg="first job placed",
            )
            leader.shutdown()
            rest = [s for s in servers if s is not leader]
            new_leader = wait_for_leader(rest, timeout=30)
            job2 = mock.job()
            new_leader.job_register(job2)
            wait_until(
                lambda: len(new_leader.state.snapshot().allocs_by_job(
                    job2.namespace, job2.id)) == 10,
                timeout=90,
                msg="second job placed by new leader",
            )
        finally:
            for s in servers:
                if s.raft is not None and s.raft._threads:
                    s.shutdown()

    def test_snapshot_restore_roundtrip(self):
        from nomad_tpu.state.store import StateStore

        store = StateStore()
        node = mock.node()
        store.upsert_node(node)
        job = mock.job()
        store.upsert_job(job)
        data = store.to_snapshot_bytes()

        fresh = StateStore()
        fresh.restore_from_bytes(data)
        snap = fresh.snapshot()
        assert snap.node_by_id(node.id) is not None
        assert snap.job_by_id(job.namespace, job.id) is not None
        assert snap.latest_index() == store.latest_index()
