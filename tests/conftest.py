"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding
(`shard_map` over the node axis) is exercised without TPU hardware;
the driver's dryrun separately validates the real multi-chip path.

NOTE: the environment's sitecustomize imports jax at interpreter
startup (before this file runs), so setting JAX_PLATFORMS via
os.environ here is too late -- we must also update the live jax
config. XLA_FLAGS still works because the CPU backend has not been
initialized yet when conftest runs.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Collector processes (logmon) normally OUTLIVE the agent so a
# restarted agent can reattach; a test suite spawning hundreds of
# short-lived agents must not leak hundreds of pollers (a past round's
# benchmarks degraded under exactly that load). With this set, a
# collector also exits once its spawning agent is gone.
os.environ["NOMAD_TPU_LOGMON_ORPHAN_EXIT"] = "1"
# Server.start() tunes the interpreter's cyclic GC for long-running
# processes (deferred full passes). A suite starting hundreds of
# short-lived servers in ONE process must keep normal GC behavior or
# cyclic garbage accumulates across tests.
os.environ["NOMAD_TPU_GC_TUNING"] = "0"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier conventions (ROADMAP.md tier-1 runs `-m 'not slow'`):
    #   slow   -- excluded from tier-1
    #   stress -- the contention-repetition tier (`pytest -m stress`,
    #             N-rerun loops over broker/coalescer/membership
    #             contention); stress tests are ALSO marked slow so
    #             tier-1 never pays for repetition
    config.addinivalue_line(
        "markers", "slow: excluded from tier-1 (-m 'not slow')")
    config.addinivalue_line(
        "markers",
        "stress: contention-repetition tier (pytest -m stress); "
        "always paired with slow")
    # buffer-donation misalignment is silent perf debt (XLA ignores the
    # donation and warns); promote it to an error so a donate_argnums
    # edit that can't alias its outputs fails the suite instead of
    # regressing quietly (ISSUE 2 satellite)
    config.addinivalue_line(
        "filterwarnings",
        "error:Some donated buffers were not usable")
