"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding
(`shard_map` over the node axis) is exercised without TPU hardware;
the driver's dryrun separately validates the real multi-chip path.
Must set env before jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
