"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding
(`shard_map` over the node axis) is exercised without TPU hardware;
the driver's dryrun separately validates the real multi-chip path.

NOTE: the environment's sitecustomize imports jax at interpreter
startup (before this file runs), so setting JAX_PLATFORMS via
os.environ here is too late -- we must also update the live jax
config. XLA_FLAGS still works because the CPU backend has not been
initialized yet when conftest runs.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
