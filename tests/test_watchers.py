"""Watcher/lifecycle tests: GC, periodic, events, drainer, deployments.

Modeled on reference nomad/core_sched_test.go, periodic_test.go,
drainer tests, deploymentwatcher/deployments_watcher_test.go, and
stream/event_broker_test.go.
"""

import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.client import Client, ClientConfig, InProcessRPC
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server import fsm as fsm_msgs
from nomad_tpu.server import stream
from nomad_tpu.server.drainer import DrainStrategy
from nomad_tpu.structs import consts
from nomad_tpu.utils.cron import CronExpr
from nomad_tpu.utils.timetable import TimeTable


def wait_for(fn, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


class TestCron:
    def test_every(self):
        e = CronExpr("@every 5s")
        now = time.time()
        assert abs(e.next_after(now) - (now + 5)) < 0.01

    def test_every_minute(self):
        e = CronExpr("* * * * *")
        now = time.time()
        nxt = e.next_after(now)
        assert 0 < nxt - now <= 60

    def test_specific_minute(self):
        e = CronExpr("30 * * * *")
        nxt = time.localtime(e.next_after())
        assert nxt.tm_min == 30

    def test_step_and_range(self):
        e = CronExpr("*/15 9-17 * * *")
        nxt = time.localtime(e.next_after())
        assert nxt.tm_min in (0, 15, 30, 45)
        assert 9 <= nxt.tm_hour <= 17

    def test_bad_spec_raises(self):
        with pytest.raises(ValueError):
            CronExpr("not a cron")


class TestTimeTable:
    def test_nearest(self):
        tt = TimeTable()
        tt.witness(10, when=100.0)
        tt.witness(20, when=200.0)
        assert tt.nearest_index(150.0) == 10
        assert tt.nearest_index(250.0) == 20
        assert tt.nearest_index(50.0) == 0


class TestEventBroker:
    def test_publish_subscribe(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            sub = server.event_broker.subscribe({stream.TOPIC_JOB: ["*"]})
            job = mock.job()
            server.job_register(job)
            events = sub.next_events(timeout=2)
            assert any(
                e.topic == stream.TOPIC_JOB and e.key == job.id
                for e in events
            )
            sub.close()
        finally:
            server.shutdown()

    def test_topic_filter(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            sub = server.event_broker.subscribe({stream.TOPIC_NODE: ["*"]})
            server.job_register(mock.job())
            events = sub.next_events(timeout=0.3)
            assert all(e.topic == stream.TOPIC_NODE for e in events)
            node = mock.node()
            server.node_register(node)
            events = sub.next_events(timeout=2)
            assert any(e.key == node.id for e in events)
        finally:
            server.shutdown()


class TestCoreGC:
    def test_eval_and_alloc_gc(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            ev = mock.eval(status=consts.EVAL_STATUS_COMPLETE)
            server.state.upsert_evals([ev])
            alloc = mock.alloc(
                eval_id=ev.id,
                desired_status=consts.ALLOC_DESIRED_STOP,
                client_status=consts.ALLOC_CLIENT_COMPLETE,
            )
            server.state.upsert_allocs([alloc])
            server.force_gc()
            snap = server.state.snapshot()
            assert snap.eval_by_id(ev.id) is None
            assert snap.alloc_by_id(alloc.id) is None
        finally:
            server.shutdown()

    def test_live_eval_not_collected(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            ev = mock.eval(status=consts.EVAL_STATUS_COMPLETE)
            server.state.upsert_evals([ev])
            alloc = mock.alloc(eval_id=ev.id)   # still running
            server.state.upsert_allocs([alloc])
            server.force_gc()
            assert server.state.snapshot().eval_by_id(ev.id) is not None
        finally:
            server.shutdown()

    def test_job_gc(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            job = mock.job(stop=True)
            server.state.upsert_job(job)
            server.force_gc()
            assert server.state.snapshot().job_by_id(job.namespace, job.id) is None
        finally:
            server.shutdown()

    def test_node_gc(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            node = mock.node(status=consts.NODE_STATUS_DOWN)
            server.state.upsert_node(node)
            server.force_gc()
            assert server.state.snapshot().node_by_id(node.id) is None
        finally:
            server.shutdown()

    def test_threshold_respected_without_force(self):
        server = Server(ServerConfig(num_workers=0, eval_gc_threshold=3600))
        server.start()
        try:
            ev = mock.eval(status=consts.EVAL_STATUS_COMPLETE)
            server.state.upsert_evals([ev])
            from nomad_tpu.server.core_sched import CoreScheduler
            sched = CoreScheduler(server.state.snapshot(), None, server)
            sched.eval_gc(force=False)   # too young to collect
            assert server.state.snapshot().eval_by_id(ev.id) is not None
        finally:
            server.shutdown()


class TestPeriodic:
    def test_periodic_launches_children(self):
        server = Server(ServerConfig(num_workers=2, heartbeat_ttl=60.0))
        server.start()
        try:
            for _ in range(2):
                server.node_register(mock.node())
            job = mock.simple_job(type=consts.JOB_TYPE_BATCH)
            job.task_groups[0].count = 1
            job.periodic = structs.PeriodicConfig(
                enabled=True, spec="@every 0.2s"
            )
            resp = server.job_register(job)
            assert resp["eval_id"] == ""    # parent gets no eval
            wait_for(
                lambda: len([
                    j for j in server.state.snapshot().jobs()
                    if j.parent_id == job.id
                ]) >= 2,
                timeout=10,
                msg="two periodic children launched",
            )
            child = next(
                j for j in server.state.snapshot().jobs()
                if j.parent_id == job.id
            )
            wait_for(
                lambda: len(server.state.snapshot().allocs_by_job(
                    child.namespace, child.id)) == 1,
                timeout=15,
                msg="child job scheduled",
            )
        finally:
            server.shutdown()

    def test_stop_parent_stops_launches(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            job = mock.simple_job(type=consts.JOB_TYPE_BATCH)
            job.periodic = structs.PeriodicConfig(enabled=True, spec="@every 0.2s")
            server.job_register(job)
            assert server.periodic_dispatcher.tracked_count() == 1
            server.job_deregister(job.namespace, job.id)
            assert server.periodic_dispatcher.tracked_count() == 0
        finally:
            server.shutdown()


class TestDrainer:
    def test_drain_migrates_allocs(self, tmp_path):
        server = Server(ServerConfig(num_workers=2, heartbeat_ttl=30.0))
        server.start()
        c1 = Client(InProcessRPC(server), ClientConfig(data_dir=str(tmp_path / "c1")))
        c2 = Client(InProcessRPC(server), ClientConfig(data_dir=str(tmp_path / "c2")))
        c1.start()
        c2.start()
        try:
            wait_for(
                lambda: all(
                    server.state.snapshot().node_by_id(c.node_id) is not None
                    and server.state.snapshot().node_by_id(c.node_id).ready()
                    for c in (c1, c2)
                ),
                msg="both nodes ready",
            )
            job = mock.simple_job()
            job.task_groups[0].count = 2
            job.task_groups[0].tasks[0].config = {}   # run forever
            server.job_register(job)
            wait_for(
                lambda: len([
                    a for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                    if a.client_status == consts.ALLOC_CLIENT_RUNNING
                ]) == 2,
                timeout=30,
                msg="2 allocs running",
            )
            server.node_update_drain(
                c1.node_id, True, DrainStrategy(deadline_s=60)
            )
            # all running allocs end up on c2; drain flag clears
            wait_for(
                lambda: len([
                    a for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                    if a.client_status == consts.ALLOC_CLIENT_RUNNING
                    and a.node_id == c2.node_id
                ]) == 2,
                timeout=30,
                msg="allocs migrated to c2",
            )
            wait_for(
                lambda: not server.state.snapshot().node_by_id(c1.node_id).drain,
                timeout=15,
                msg="drain completed",
            )
            node = server.state.snapshot().node_by_id(c1.node_id)
            assert node.scheduling_eligibility == consts.NODE_SCHEDULING_INELIGIBLE
        finally:
            c1.shutdown()
            c2.shutdown()
            server.shutdown()


class TestDeployments:
    def make_update_job(self):
        job = mock.simple_job()
        job.task_groups[0].count = 3
        job.task_groups[0].tasks[0].config = {}   # run forever
        job.task_groups[0].update = structs.UpdateStrategy(
            max_parallel=1,
            min_healthy_time_s=0.1,
            healthy_deadline_s=10.0,
            progress_deadline_s=30.0,
        )
        return job

    def test_deployment_succeeds_when_healthy(self, tmp_path):
        server = Server(ServerConfig(num_workers=2, heartbeat_ttl=30.0))
        server.start()
        client = Client(InProcessRPC(server), ClientConfig(data_dir=str(tmp_path)))
        client.start()
        try:
            wait_for(
                lambda: server.state.snapshot().node_by_id(client.node_id) is not None
                and server.state.snapshot().node_by_id(client.node_id).ready(),
                msg="node ready",
            )
            job = self.make_update_job()
            server.job_register(job)
            wait_for(
                lambda: server.state.snapshot().latest_deployment_by_job_id(
                    job.namespace, job.id) is not None,
                timeout=30,
                msg="deployment created",
            )
            wait_for(
                lambda: server.state.snapshot().latest_deployment_by_job_id(
                    job.namespace, job.id).status
                == consts.DEPLOYMENT_STATUS_SUCCESSFUL,
                timeout=30,
                msg="deployment successful",
            )
            d = server.state.snapshot().latest_deployment_by_job_id(
                job.namespace, job.id)
            state = d.task_groups[job.task_groups[0].name]
            assert state.healthy_allocs >= state.desired_total == 3
        finally:
            client.shutdown()
            server.shutdown()

    def test_failed_deployment_marked_failed(self, tmp_path):
        server = Server(ServerConfig(num_workers=2, heartbeat_ttl=30.0))
        server.start()
        client = Client(InProcessRPC(server), ClientConfig(data_dir=str(tmp_path)))
        client.start()
        try:
            wait_for(
                lambda: server.state.snapshot().node_by_id(client.node_id) is not None
                and server.state.snapshot().node_by_id(client.node_id).ready(),
                msg="node ready",
            )
            job = self.make_update_job()
            job.task_groups[0].count = 1
            job.task_groups[0].restart_policy = structs.RestartPolicy(
                attempts=0, interval_s=300, delay_s=0.01, mode="fail"
            )
            # tasks crash: deployment must fail
            job.task_groups[0].tasks[0].config = {"run_for": 0.05, "exit_code": 1}
            server.job_register(job)
            wait_for(
                lambda: (
                    server.state.snapshot().latest_deployment_by_job_id(
                        job.namespace, job.id) is not None
                    and server.state.snapshot().latest_deployment_by_job_id(
                        job.namespace, job.id).status
                    == consts.DEPLOYMENT_STATUS_FAILED
                ),
                timeout=30,
                msg="deployment failed",
            )
        finally:
            client.shutdown()
            server.shutdown()

    def test_idle_watcher_caches_against_deployment_table_index(self):
        """Alloc commits wake the deployments watcher on every plan; with
        nothing tracked and no active deployments, the tick must early-out
        against the deployment table index WITHOUT re-scanning the
        deployments table (the PR5 drainer/volume-watcher discipline). A
        deployment write re-arms the scan."""
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            node = mock.node()
            server.node_register(node)
            calls = []
            orig = server.state.active_deployments
            server.state.active_deployments = \
                lambda: (calls.append(1), orig())[1]
            # let the watcher prove idleness once
            deadline = time.time() + 10
            while time.time() < deadline and not calls:
                time.sleep(0.05)
            time.sleep(0.3)
            baseline = len(calls)
            assert baseline >= 1
            # alloc-table churn: each upsert wakes the watcher loop,
            # but the deployment index is unchanged -> no re-scan
            for _ in range(15):
                a = mock.alloc(node_id=node.id)
                server.state.upsert_allocs([a])
                time.sleep(0.02)
            time.sleep(0.5)
            assert len(calls) <= baseline + 1, (baseline, len(calls))
            # a deployment write bumps the index and re-arms the scan
            from nomad_tpu.structs.eval_plan import Deployment

            server.state.upsert_deployment(
                Deployment(job_id="j", namespace="default"))
            deadline = time.time() + 10
            while time.time() < deadline and len(calls) <= baseline + 1:
                time.sleep(0.05)
            assert len(calls) > baseline
        finally:
            server.shutdown()
